"""LR schedulers as graph ops over the global step counter.

ref ``python/paddle/fluid/layers/learning_rate_scheduler.py`` — each decay
builds a tiny op subgraph reading ``@LR_DECAY_COUNTER@``; here they lower
into the same XLA computation as the train step, so the schedule costs
nothing per step.
"""

from __future__ import annotations

import math

from ..framework.core import default_main_program
from ..layer_helper import LayerHelper
from . import nn, tensor


def _decay_step_counter(begin=0):
    from .nn import autoincreased_step_counter
    counter = autoincreased_step_counter(
        counter_name="@LR_DECAY_COUNTER@", begin=begin, step=1)
    return tensor.cast(counter, "float32")


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """ref learning_rate_scheduler.py:noam_decay (the Transformer schedule)."""
    step = _decay_step_counter(1)
    a = step ** -0.5
    b = (warmup_steps ** -1.5) * step
    lr = learning_rate * (d_model ** -0.5) * nn.elementwise_min(a, b)
    return lr


def exponential_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = nn.floor(div)
    return learning_rate * _pow_scalar(decay_rate, div)


def _pow_scalar(base, exponent_var):
    # base^x = exp(x * ln base)
    return nn.exp(exponent_var * math.log(base))


def natural_exp_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = nn.floor(div)
    return learning_rate * nn.exp(-1.0 * decay_rate * div)


def inverse_time_decay(learning_rate, decay_steps, decay_rate, staircase=False):
    step = _decay_step_counter()
    div = step / float(decay_steps)
    if staircase:
        div = nn.floor(div)
    return learning_rate / (1.0 + decay_rate * div)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    step = _decay_step_counter()
    if cycle:
        div_res = nn.ceil(step / float(decay_steps))
        # guard zero step
        decay_steps_var = div_res * float(decay_steps)
        frac = step / decay_steps_var
    else:
        frac = nn.elementwise_min(step / float(decay_steps),
                                  step * 0.0 + 1.0)
    return (learning_rate - end_learning_rate) * _frac_pow(1.0 - frac, power) \
        + end_learning_rate


def _frac_pow(x_var, p):
    if p == 1.0:
        return x_var
    return nn.exp(nn.log(nn.elementwise_max(x_var, x_var * 0.0 + 1e-12)) * p)


def piecewise_decay(boundaries, values):
    """piecewise-constant lr: select by comparing step to boundaries."""
    step = _decay_step_counter()
    lr = step * 0.0 + float(values[-1])
    # build nested where via arithmetic masks (static unrolled, tiny)
    for b, v in zip(reversed(boundaries), reversed(values[:-1])):
        from ..layer_helper import LayerHelper
        helper = LayerHelper("piecewise_select")
        cond = helper.create_variable_for_type_inference("bool", True)
        helper.append_op("less_than",
                         inputs={"X": [step], "Y": [_const_like(step, float(b))]},
                         outputs={"Out": [cond]})
        mask = tensor.cast(cond, "float32")
        lr = mask * float(v) + (1.0 - mask) * lr
    return lr


def _const_like(ref, value):
    helper = LayerHelper("const")
    out = helper.create_variable_for_type_inference("float32", True)
    helper.append_op("fill_constant", outputs={"Out": [out]},
                     attrs={"shape": [], "dtype": "float32", "value": value})
    return out


def cosine_decay(learning_rate, step_each_epoch, epochs):
    step = _decay_step_counter()
    epoch = nn.floor(step / step_each_epoch)
    return learning_rate * 0.5 * (nn.cos(epoch * math.pi / epochs) + 1.0)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    step = _decay_step_counter()
    helper = LayerHelper("lr_warmup")
    cond = helper.create_variable_for_type_inference("bool", True)
    helper.append_op("less_than",
                     inputs={"X": [step], "Y": [_const_like(step, float(warmup_steps))]},
                     outputs={"Out": [cond]})
    mask = tensor.cast(cond, "float32")
    warm = start_lr + (end_lr - start_lr) * (step / float(warmup_steps))
    if not hasattr(learning_rate, "block"):
        learning_rate = step * 0.0 + float(learning_rate)
    return mask * warm + (1.0 - mask) * learning_rate


def _lr_sched(fn):
    """Tag scheduler-emitted ops 'lrsched' so clone(for_test=True) prunes
    them (ref framework.py _lr_schedule_guard / OpRole::kLRSched)."""
    import functools

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        from ..framework.core import default_main_program
        with default_main_program()._op_role_guard("lrsched"):
            return fn(*args, **kwargs)
    return wrapped


noam_decay = _lr_sched(noam_decay)
exponential_decay = _lr_sched(exponential_decay)
natural_exp_decay = _lr_sched(natural_exp_decay)
inverse_time_decay = _lr_sched(inverse_time_decay)
polynomial_decay = _lr_sched(polynomial_decay)
piecewise_decay = _lr_sched(piecewise_decay)
cosine_decay = _lr_sched(cosine_decay)
linear_lr_warmup = _lr_sched(linear_lr_warmup)
