"""Device-memory observability (VERDICT r2 #10; ref capability:
``memory/allocation/allocator_facade.h`` stats +
``platform/flags.cc:370-391`` memory-fraction flags +
``memory/allocation/retry_allocator.h`` OOM handling).

On TPU, HBM allocation belongs to XLA — the framework can't (and
shouldn't) re-implement the arena.  What the reference's allocator stack
actually gives users is *observability*: what is resident, how big, and
what was live when an OOM hit.  This module provides that:

- ``summary(scope)``     — per-var device bytes of live scope arrays,
  plus anonymous (non-scope) live arrays, sorted by size
- ``device_memory_stats()`` — the runtime allocator's own counters
  (bytes_in_use, peak_bytes_in_use, bytes_limit) where the backend
  exposes them (TPU does; CPU returns {})
- the executor appends ``summary()`` to RESOURCE_EXHAUSTED errors, so an
  on-chip OOM names the tensors that were resident (executor.py).
"""

from __future__ import annotations

from typing import Optional

__all__ = ["summary", "device_memory_stats", "live_bytes"]


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:8.2f} {unit}"
        n /= 1024
    return f"{n:.2f} TiB"


def _live_device_arrays():
    import jax
    out = []
    for a in jax.live_arrays():
        try:
            if a.is_deleted():
                continue
            out.append(a)
        except Exception:
            continue
    return out


def live_bytes() -> int:
    """Total bytes of all live device arrays in the process."""
    return sum(a.nbytes for a in _live_device_arrays())


def device_memory_stats(device=None) -> dict:
    """The backend allocator's counters for one device (TPU exposes
    bytes_in_use / peak_bytes_in_use / bytes_limit; CPU gives {})."""
    import jax
    dev = device if device is not None else jax.devices()[0]
    try:
        return dict(dev.memory_stats() or {})
    except Exception:
        return {}


def summary(scope: Optional[object] = None, max_rows: int = 40) -> str:
    """Human-readable residency report: scope vars (named) first, then
    anonymous live arrays (jit temporaries, donated-buffer survivors),
    largest first, with totals and allocator counters."""
    from .framework.scope import global_scope
    scope = scope if scope is not None else global_scope()

    live = _live_device_arrays()
    by_id = {id(a): a for a in live}
    named = []
    seen = set()
    for name, val in scope.items():
        if id(val) in by_id:
            named.append((name, val))
            seen.add(id(val))
    anon = [a for a in live if id(a) not in seen]

    named.sort(key=lambda kv: -kv[1].nbytes)
    anon.sort(key=lambda a: -a.nbytes)

    lines = ["=== paddle_tpu device memory summary ==="]
    total_named = sum(v.nbytes for _, v in named)
    total_anon = sum(a.nbytes for a in anon)
    lines.append(f"scope vars: {len(named)}  ({_fmt_bytes(total_named).strip()})"
                 f"   anonymous arrays: {len(anon)}  "
                 f"({_fmt_bytes(total_anon).strip()})")
    for name, v in named[:max_rows]:
        dev = next(iter(v.devices())) if hasattr(v, "devices") else "?"
        lines.append(f"  {_fmt_bytes(v.nbytes)}  {str(v.dtype):>9s} "
                     f"{str(v.shape):>20s}  {name}  [{dev}]")
    if len(named) > max_rows:
        rest = sum(v.nbytes for _, v in named[max_rows:])
        lines.append(f"  {_fmt_bytes(rest)}  … {len(named) - max_rows} "
                     "more scope vars")
    for a in anon[:8]:
        lines.append(f"  {_fmt_bytes(a.nbytes)}  {str(a.dtype):>9s} "
                     f"{str(a.shape):>20s}  <anonymous>")
    if len(anon) > 8:
        rest = sum(a.nbytes for a in anon[8:])
        lines.append(f"  {_fmt_bytes(rest)}  … {len(anon) - 8} more "
                     "anonymous arrays")
    stats = device_memory_stats()
    if stats:
        parts = []
        for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if k in stats:
                parts.append(f"{k}={_fmt_bytes(stats[k]).strip()}")
        if parts:
            lines.append("allocator: " + "  ".join(parts))
    for tag, plan in hbm_plans().items():
        lines.append(
            f"hbm plan [{tag[:48]}]: peak "
            f"{_fmt_bytes(plan['peak_bytes']).strip()} "
            f"(args {_fmt_bytes(plan['argument_bytes']).strip()}, temps "
            f"{_fmt_bytes(plan['temp_bytes']).strip()}, out "
            f"{_fmt_bytes(plan['output_bytes']).strip()}, aliased "
            f"-{_fmt_bytes(plan['alias_bytes']).strip()})")
    lines.append(f"total live device bytes: "
                 f"{_fmt_bytes(total_named + total_anon).strip()}")
    return "\n".join(lines)


# --- compiled-executable HBM plans (ref allocator_facade.h stats) ----------
# device.memory_stats() returns nothing through the axon tunnel, so the
# measured footprint comes from the XLA buffer assignment of each compiled
# step: the executor records memory_analysis() here when
# PADDLE_TPU_RECORD_HBM=1 (framework/executor.py _CompiledBlock.__call__).

_HBM_PLANS: dict = {}


def record_hbm_plan(tag: str, ma) -> str:
    """Store one executable's memory_analysis; returns the tag the plan
    was stored under (suffixed on collision — callers reading the entry
    back must use the RETURNED tag, not the one they passed)."""
    # distinct compiled blocks can share a fetch list (startup programs
    # all tag '<block>') — suffix instead of silently overwriting
    if tag in _HBM_PLANS:
        n = 2
        while f"{tag}#{n}" in _HBM_PLANS:
            n += 1
        tag = f"{tag}#{n}"
    arg = int(getattr(ma, "argument_size_in_bytes", 0))
    out = int(getattr(ma, "output_size_in_bytes", 0))
    tmp = int(getattr(ma, "temp_size_in_bytes", 0))
    alias = int(getattr(ma, "alias_size_in_bytes", 0))
    code = int(getattr(ma, "generated_code_size_in_bytes", 0))
    _HBM_PLANS[tag] = {
        "argument_bytes": arg, "output_bytes": out, "temp_bytes": tmp,
        "alias_bytes": alias, "generated_code_bytes": code,
        # donated (aliased) outputs reuse their argument buffers
        "peak_bytes": arg + out + tmp + code - alias,
    }
    return tag


def hbm_plans() -> dict:
    return dict(_HBM_PLANS)


def _is_oom_error(e: BaseException) -> bool:
    s = str(e)
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s or "OOM" in s)
