"""Parameter initializers (ref ``python/paddle/fluid/initializer.py``).

Each initializer appends an init op to the *startup* program targeting the
parameter var, exactly as in the reference: Constant → fill_constant,
Uniform → uniform_random, Normal → gaussian_random, Xavier/MSRA → scaled
uniform/normal, TruncatedNormal → truncated_gaussian_random.
"""

from __future__ import annotations

import math

import numpy as np

from .framework.core import Variable, default_startup_program


class Initializer:
    def __call__(self, var: Variable, block=None):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, var, block=None):
        block = block or default_startup_program().global_block()
        block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                         persistable=True)
        block.append_op("fill_constant", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "value": float(self.value)})


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block=None):
        block = block or default_startup_program().global_block()
        block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                         persistable=True)
        block.append_op("uniform_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "min": self.low, "max": self.high,
                               "seed": self.seed})


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block=None):
        block = block or default_startup_program().global_block()
        block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                         persistable=True)
        block.append_op("gaussian_random", outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": self.loc, "std": self.scale,
                               "seed": self.seed})


class TruncatedNormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block=None):
        block = block or default_startup_program().global_block()
        block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                         persistable=True)
        block.append_op("truncated_gaussian_random",
                        outputs={"Out": [var.name]},
                        attrs={"shape": list(var.shape), "dtype": var.dtype,
                               "mean": self.loc, "std": self.scale,
                               "seed": self.seed})


def _fan_in_out(var):
    shape = var.shape
    if len(shape) < 2:
        return shape[0] if shape else 1, shape[0] if shape else 1
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    fan_in = shape[1] * receptive if len(shape) > 2 else shape[0]
    fan_out = shape[0] * receptive if len(shape) > 2 else shape[1]
    return fan_in, fan_out


class XavierInitializer(Initializer):
    """Glorot init (ref initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = \
            uniform, fan_in, fan_out, seed

    def __call__(self, var, block=None):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std, self.seed)(var, block)


class MSRAInitializer(Initializer):
    """He/Kaiming init (ref initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block=None):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit, self.seed)(var, block)
        else:
            std = math.sqrt(2.0 / fi)
            NormalInitializer(0.0, std, self.seed)(var, block)


class BilinearInitializer(Initializer):
    """For conv-transpose upsampling kernels (ref initializer.py Bilinear)."""

    def __call__(self, var, block=None):
        block = block or default_startup_program().global_block()
        shape = var.shape
        if len(shape) != 4:
            raise ValueError("BilinearInitializer expects 4-D weight")
        f = math.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        weight = np.zeros(shape, dtype="float32")
        size = int(np.prod(shape))
        idx = np.arange(size)
        x = idx % shape[3]
        y = (idx // shape[3]) % shape[2]
        w = (1 - np.abs(x / f - c)) * (1 - np.abs(y / f - c))
        weight.flat[:] = w
        block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                         persistable=True)
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(shape), "dtype": var.dtype,
                               "values": weight.reshape(-1).tolist()})


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block=None):
        block = block or default_startup_program().global_block()
        block.create_var(name=var.name, shape=var.shape, dtype=var.dtype,
                         persistable=True)
        block.append_op("assign_value", outputs={"Out": [var.name]},
                        attrs={"shape": list(self.value.shape),
                               "dtype": var.dtype,
                               "values": self.value.reshape(-1).tolist()})


# aliases matching fluid's public names
Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
Bilinear = BilinearInitializer


def _global_weight_initializer():
    return XavierInitializer()


def _global_bias_initializer():
    return ConstantInitializer(0.0)
