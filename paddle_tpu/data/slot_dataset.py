"""Slot-file Dataset API over the native MultiSlot data feed.

ref ``python/paddle/fluid/dataset.py``: DatasetFactory:21,
InMemoryDataset:269, QueueDataset:621 — configured with use_vars/filelist/
thread-count, consumed by ``Executor.train_from_dataset``
(ref ``framework/executor.cc:143`` RunFromDataset + MultiTrainer).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .. import native


class DatasetBase:
    def __init__(self):
        self._batch_size = 1
        self._thread_num = 1
        self._filelist: List[str] = []
        self._use_vars = []          # Variables, in slot order
        self._shuffle_seed = 0
        self._pipe_command = None    # accepted for parity, unused

    # -- configuration (ref dataset.py set_* methods) ------------------------
    def set_batch_size(self, batch_size: int):
        self._batch_size = batch_size

    def set_thread(self, thread_num: int):
        self._thread_num = thread_num

    def set_filelist(self, filelist: Sequence[str]):
        self._filelist = list(filelist)

    def set_use_var(self, var_list):
        self._use_vars = list(var_list)

    def set_pipe_command(self, cmd: str):
        self._pipe_command = cmd

    def _slots(self):
        out = []
        for v in self._use_vars:
            dtype = "int64" if "int" in str(v.dtype) else "float"
            out.append((v.name, dtype))
        return out

    # -- iteration: yields {var_name: dense ndarray} feed dicts --------------
    def _batches(self):
        if not native.available():
            yield from self._batches_python()
            return
        feed = native.MultiSlotDataFeed(self._slots(), self._batch_size)
        feed.set_filelist(self._filelist)
        feed.start(self._thread_num, self._shuffle_seed)
        for raw in feed:
            yield self._to_feed(raw)

    def _batches_python(self):
        """Pure-python fallback parser for the same MultiSlot text format.
        Matches the native parser's behavior: malformed lines are skipped,
        never fatal; local shuffle honors _shuffle_seed."""
        slots = self._slots()
        rng = (np.random.RandomState(self._shuffle_seed)
               if self._shuffle_seed else None)
        pending = []
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    inst = self._parse_line(line, slots)
                    if inst is None:
                        continue
                    if rng is not None and pending:
                        j = rng.randint(0, len(pending) + 1)
                        if j < len(pending):
                            pending[j], inst = inst, pending[j]
                    pending.append(inst)
                    if len(pending) == self._batch_size:
                        yield self._pack(pending, slots)
                        pending = []
        if pending:
            yield self._pack(pending, slots)

    @staticmethod
    def _parse_line(line, slots):
        toks = line.split()
        i = 0
        inst = []
        try:
            for name, dtype in slots:
                n = int(toks[i]); i += 1
                if n < 0 or i + n > len(toks):
                    return None
                vals = toks[i:i + n]; i += n
                inst.append(np.array(vals, np.int64 if dtype == "int64"
                                     else np.float32))
        except (ValueError, IndexError):
            return None
        return inst

    def _pack(self, pending, slots):
        raw = {}
        for s, (name, dtype) in enumerate(slots):
            vals = np.concatenate([inst[s] for inst in pending])
            offs = np.cumsum([0] + [len(inst[s]) for inst in pending])
            raw[name] = (vals, offs.astype(np.int64))
        return self._to_feed(raw)

    def _to_feed(self, raw):
        feed = {}
        for v in self._use_vars:
            vals, offs = raw[v.name]
            widths = np.diff(offs)
            if len(widths) and (widths == widths[0]).all():
                # fixed-width slot → dense (batch, w) (w==1 squeezes to the
                # declared var shape)
                w = int(widths[0])
                arr = vals.reshape(-1, w)
            else:
                # ragged slot → dense padded + implicit zero pad (the LoD
                # replacement; SURVEY §5.7).  Width is bucketed to the next
                # power of two: the executor's jit cache is keyed on feed
                # shapes, so per-batch max-widths would recompile XLA nearly
                # every batch
                w = int(widths.max()) if len(widths) else 1
                w = 1 << (w - 1).bit_length() if w > 1 else 1
                arr = np.zeros((len(widths), w), vals.dtype)
                for i in range(len(widths)):
                    arr[i, :widths[i]] = vals[offs[i]:offs[i + 1]]
            feed[v.name] = arr
        return feed

    def __iter__(self):
        return self._batches()


class QueueDataset(DatasetBase):
    """ref dataset.py:621 — streaming from files through the native queue."""


class InMemoryDataset(DatasetBase):
    """ref dataset.py:269 — load_into_memory + local/global shuffle."""

    def __init__(self):
        super().__init__()
        self._memory: Optional[List[dict]] = None

    def load_into_memory(self):
        self._memory = list(self._batches())

    def local_shuffle(self, seed: int = 0):
        rng = np.random.RandomState(seed)
        if self._memory is None:
            self._shuffle_seed = seed or 1
        else:
            rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, seed: int = 0):
        self.local_shuffle(seed)

    def release_memory(self):
        self._memory = None

    def __iter__(self):
        if self._memory is not None:
            return iter(self._memory)
        return self._batches()


class DatasetFactory:
    """ref dataset.py:21 — create_dataset by class name."""

    def create_dataset(self, datafeed_class: str = "QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError(f"unknown dataset class {datafeed_class!r}")
