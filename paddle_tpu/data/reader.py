"""Reader decorators (ref ``python/paddle/reader/decorator.py``): composable
generator transforms — batch/shuffle/map/chain/compose/buffered/xmap."""

from __future__ import annotations

import itertools
import queue
import random
import threading

import numpy as np
from typing import Callable, Iterable


def batch(reader, batch_size, drop_last=True):
    """ref decorator.py batch — group samples into lists."""
    def batch_reader():
        b = []
        for sample in reader():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return batch_reader


def shuffle(reader, buf_size):
    """ref decorator.py shuffle — bounded-buffer shuffling."""
    def shuffled():
        buf = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                random.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            random.shuffle(buf)
            yield from buf
    return shuffled


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def chain(*readers):
    def reader():
        for r in readers:
            yield from r()
    return reader


def compose(*readers, check_alignment=True):
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        for outputs in zip(*rs):
            yield sum((make_tuple(o) for o in outputs), ())
    return reader


def buffered(reader, size):
    """Background-thread prefetch (ref decorator.py buffered)."""
    class _End:
        pass

    def buffered_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(_End)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is _End:
                break
            yield e
    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        yield from itertools.islice(reader(), n)
    return firstn_reader


def cache(reader):
    all_data = []

    def cached():
        if not all_data:
            all_data.extend(reader())
        yield from all_data
    return cached


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Multi-thread map (ref decorator.py xmap_readers)."""
    class _End:
        pass

    def xreader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        def feed():
            for d in reader():
                in_q.put(d)
            for _ in range(process_num):
                in_q.put(_End)

        def work():
            while True:
                d = in_q.get()
                if d is _End:
                    out_q.put(_End)
                    return
                out_q.put(mapper(d))

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()
        done = 0
        while done < process_num:
            e = out_q.get()
            if e is _End:
                done += 1
            else:
                yield e
    return xreader


def prefetch_to_device(reader, depth=2):
    """Keep ``depth`` batches resident on device ahead of the consumer.

    TPU-native addition (the reference's analog is py_reader's
    double-buffering into CUDA pinned memory): ``jax.device_put`` is
    asynchronous, so issuing the NEXT batches' transfers while the
    current step computes hides host→device latency entirely.  Works on
    feed dicts (name → numpy) or bare arrays/tuples.
    """
    import time as _time
    from collections import deque

    from .. import monitor as _monitor
    from .dataloader import _put as _stage, _stage_serials

    def put(item, src):
        # shared staging helper: int64 feeds get their first-batch wrap
        # check on the original host values before the H2D copy
        t0 = _time.perf_counter()
        if isinstance(item, dict):
            out = {k: _stage(v, name=k, src=src)
                   for k, v in item.items()}
        elif isinstance(item, (list, tuple)):
            out = type(item)(_stage(v, name=f"@{j}", src=src)
                             for j, v in enumerate(item))
        else:
            out = _stage(item, name="@", src=src)
        if _monitor.TRACER.enabled:
            _monitor.TRACER.add_complete(
                "reader.stage_batch", "dataloader", t0,
                _time.perf_counter())
        return out

    def prefetching_reader():
        pending = deque()
        it = iter(reader())
        # per-iteration check-token namespace (see dataloader._put): one
        # reader's in-range first batch must never suppress the wrap
        # warning for a different reader reusing the feed name
        src = ("stage", next(_stage_serials))
        from .dataloader import _drop_stage_tokens
        try:
            try:
                for _ in range(depth):
                    pending.append(put(next(it), src))
            except StopIteration:
                pass
            while pending:
                out = pending.popleft()
                try:
                    pending.append(put(next(it), src))
                except StopIteration:
                    pass
                yield out
        finally:
            # retire this iteration's int64-check tokens (see
            # dataloader._drop_stage_tokens: the set is process-global)
            _drop_stage_tokens(src)
    return prefetching_reader
