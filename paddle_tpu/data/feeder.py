"""DataFeeder: list-of-samples → feed-dict of batched numpy arrays
(ref ``python/paddle/fluid/data_feeder.py``)."""

from __future__ import annotations

import numpy as np


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    def feed(self, iterable):
        """iterable: list of tuples, one element per feed var."""
        cols = list(zip(*iterable))
        out = {}
        for var, col in zip(self.feed_vars, cols):
            name = var.name if hasattr(var, "name") else var
            dtype = var.dtype if hasattr(var, "dtype") else "float32"
            arrs = [np.asarray(c, dtype=dtype) for c in col]
            batch = np.stack(arrs, axis=0)
            # fluid convention: int labels declared [.., 1] keep trailing dim
            shape = getattr(var, "shape", None)
            if shape is not None and len(shape) == batch.ndim + 1 \
                    and shape[-1] == 1:
                batch = batch[..., None]
            out[name] = batch
        return out

    def feed_parallel(self, iterable_list, num_places=None):
        """ref data_feeder.py feed_parallel: one feed dict per device; the
        GSPMD executor shards one global batch instead, so the per-device
        dicts are concatenated into it."""
        dicts = [self.feed(it) for it in iterable_list]
        if not dicts:
            raise ValueError("feed_parallel got an empty iterable_list")
        if num_places is not None and len(dicts) != num_places:
            raise ValueError(
                f"feed_parallel got {len(dicts)} per-device batches for "
                f"{num_places} places")
        if len(dicts) == 1:
            return dicts[0]
        return {k: np.concatenate([d[k] for d in dicts]) for k in dicts[0]}
