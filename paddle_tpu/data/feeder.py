"""DataFeeder: list-of-samples → feed-dict of batched numpy arrays
(ref ``python/paddle/fluid/data_feeder.py``)."""

from __future__ import annotations

import numpy as np


class DataFeeder:
    def __init__(self, feed_list, place=None, program=None):
        self.feed_vars = feed_list
        self.place = place

    @staticmethod
    def _sample_shape(shape):
        """Per-sample target shape from a declared var shape: drop the
        leading batch dim (fluid's ``data`` prepends -1); None if any
        remaining dim is symbolic."""
        if shape is None:
            return None
        dims = [int(d) for d in shape]
        if dims and dims[0] == -1:
            dims = dims[1:]
        if any(d <= 0 for d in dims):
            return None
        return tuple(dims)

    def feed(self, iterable):
        """iterable: list of tuples, one element per feed var.

        Each sample is reshaped to the var's declared per-sample shape
        (ref ``python/paddle/fluid/data_feeder.py`` DataToLoDTensorConverter
        — cifar-style flat float rows reach conv2d as [N,C,H,W])."""
        cols = list(zip(*iterable))
        out = {}
        for var, col in zip(self.feed_vars, cols):
            name = var.name if hasattr(var, "name") else var
            dtype = var.dtype if hasattr(var, "dtype") else "float32"
            arrs = [np.asarray(c, dtype=dtype) for c in col]
            target = self._sample_shape(getattr(var, "shape", None))
            if target is not None:
                size = int(np.prod(target)) if target else 1
                arrs = [a.reshape(target) if a.size == size else a
                        for a in arrs]
            batch = np.stack(arrs, axis=0)
            # fluid convention: int labels declared [.., 1] keep trailing dim
            shape = getattr(var, "shape", None)
            if shape is not None and len(shape) == batch.ndim + 1 \
                    and shape[-1] == 1:
                batch = batch[..., None]
            out[name] = batch
        return out

    def feed_parallel(self, iterable_list, num_places=None):
        """ref data_feeder.py feed_parallel: one feed dict per device; the
        GSPMD executor shards one global batch instead, so the per-device
        dicts are concatenated into it."""
        dicts = [self.feed(it) for it in iterable_list]
        if not dicts:
            raise ValueError("feed_parallel got an empty iterable_list")
        if num_places is not None and len(dicts) != num_places:
            raise ValueError(
                f"feed_parallel got {len(dicts)} per-device batches for "
                f"{num_places} places")
        if len(dicts) == 1:
            return dicts[0]
        return {k: np.concatenate([d[k] for d in dicts]) for k in dicts[0]}
