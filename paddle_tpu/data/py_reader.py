"""PyReader: decorated-reader → blocking-queue → train-loop staging.

ref ``python/paddle/fluid/reader.py:47`` (PyReader) + pybind
``reader_py.cc``: a Python thread pushes numpy batches into the *native*
C++ blocking queue (``native/src/blocking_queue.cc`` ≈
LoDTensorBlockingQueue); the train loop pops and device-puts.  Falls back
to queue.Queue when the native library is unavailable.
"""

from __future__ import annotations

import queue as _pyqueue
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from .. import native
from .feeder import DataFeeder


class _PyQueueShim:
    def __init__(self, capacity):
        self._q = _pyqueue.Queue(maxsize=capacity)
        self._closed = False

    def push(self, obj, timeout_ms=-1):
        self._q.put(obj)
        return True

    def pop(self, timeout_ms=-1):
        item = self._q.get()
        if item is StopIteration:
            raise StopIteration
        return item

    def close(self):
        self._q.put(StopIteration)

    def reopen(self):
        self._closed = False


class PyReader:
    """ref reader.py PyReader(feed_list, capacity, iterable).

    decorate_sample_list_generator / decorate_batch_generator mirror the
    reference decorators; iteration yields feed dicts.
    """

    def __init__(self, feed_list: Optional[Sequence] = None,
                 capacity: int = 8, use_double_buffer: bool = True,
                 iterable: bool = True):
        self.feed_list = list(feed_list or [])
        self.capacity = capacity
        self.iterable = iterable
        self._gen: Optional[Callable] = None
        self._thread: Optional[threading.Thread] = None
        self._queue = None
        self._err: List[BaseException] = []

    # -- decoration (ref reader.py:453-620) ----------------------------------
    def decorate_sample_list_generator(self, generator, places=None):
        feeder = DataFeeder(self.feed_list)

        def batches():
            for samples in generator():
                yield feeder.feed(samples)
        self._gen = batches
        return self

    def decorate_batch_generator(self, generator, places=None):
        self._gen = generator
        return self

    # -- lifecycle -----------------------------------------------------------
    def start(self):
        if self._gen is None:
            raise ValueError("decorate a generator first")
        if native.available():
            self._queue = native.BlockingQueue(self.capacity)
        else:
            self._queue = _PyQueueShim(self.capacity)
        self._err = []

        def producer():
            try:
                for batch in self._gen():
                    self._queue.push(batch)
            except BaseException as e:
                self._err.append(e)
            finally:
                self._queue.close()

        self._thread = threading.Thread(target=producer, daemon=True)
        self._thread.start()

    def reset(self):
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._thread = None
        self._queue = None

    def __iter__(self):
        if self._thread is None:
            self.start()
        try:
            while True:
                try:
                    yield self._queue.pop()
                except StopIteration:
                    break
            if self._err:
                raise self._err[0]
        finally:
            # consumer may abandon iteration early: close the queue so a
            # producer blocked in push() unwinds before the queue is dropped
            if self._queue is not None:
                self._queue.close()
            self.reset()

    def next(self):
        if self._thread is None:
            self.start()
        return self._queue.pop()
