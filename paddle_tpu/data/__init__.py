from . import dataset, reader  # noqa
from .dataloader import DataLoader  # noqa
from .feeder import DataFeeder  # noqa
from .py_reader import PyReader  # noqa
from .slot_dataset import (DatasetBase, DatasetFactory,  # noqa
                           InMemoryDataset, QueueDataset)
