from . import dataset, reader  # noqa
from .dataloader import DataLoader  # noqa
from .feeder import DataFeeder  # noqa
