"""Dataset loaders (ref ``python/paddle/dataset/``: mnist, imdb, wmt14/16,
uci_housing, imagenet…).

This environment has zero egress, so each corpus has a *synthetic* generator
with the exact sample schema of the reference loader (shape/dtype/range), a
fixed seed for reproducibility, and enough structure (class-dependent means,
label-correlated tokens) that models measurably learn — which is what the
book-style convergence tests need.  Real-data loading hooks are the same
function signatures reading from ``data_dir`` when provided.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np


class mnist:
    """ref python/paddle/dataset/mnist.py — 28×28 images in [-1,1], int label.

    Synthetic mode: class-conditional blob images (digit = position of a
    bright patch), linearly separable enough for the book convergence test.
    """

    IMAGE_SIZE = 784

    @staticmethod
    def _synthetic(n, seed):
        rng = np.random.RandomState(seed)
        labels = rng.randint(0, 10, size=n).astype("int64")
        imgs = rng.randn(n, 28, 28).astype("float32") * 0.15
        for i, lab in enumerate(labels):
            r, c = divmod(int(lab), 5)
            imgs[i, 4 + r * 12: 12 + r * 12, 2 + c * 5: 7 + c * 5] += 1.0
        imgs = np.clip(imgs, -1.0, 1.0).reshape(n, 784)
        return imgs, labels

    @staticmethod
    def _reader(n, seed):
        def reader():
            imgs, labels = mnist._synthetic(n, seed)
            for i in range(n):
                yield imgs[i], int(labels[i])
        return reader

    @staticmethod
    def train(data_dir=None):
        if data_dir:
            return mnist._idx_reader(data_dir, "train")
        return mnist._reader(2048, seed=42)

    @staticmethod
    def test(data_dir=None):
        if data_dir:
            return mnist._idx_reader(data_dir, "t10k")
        return mnist._reader(512, seed=7)

    @staticmethod
    def _idx_reader(data_dir, split):
        def reader():
            imgf = os.path.join(data_dir, f"{split}-images-idx3-ubyte.gz")
            labf = os.path.join(data_dir, f"{split}-labels-idx1-ubyte.gz")
            with gzip.open(imgf, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                imgs = np.frombuffer(f.read(), dtype=np.uint8)
                imgs = imgs.reshape(n, rows * cols).astype("float32")
                imgs = imgs / 127.5 - 1.0
            with gzip.open(labf, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), dtype=np.uint8).astype("int64")
            for i in range(n):
                yield imgs[i], int(labels[i])
        return reader


class uci_housing:
    """ref dataset/uci_housing.py — 13 features → 1 price."""

    @staticmethod
    def _make(n, seed):
        rng = np.random.RandomState(seed)
        x = rng.randn(n, 13).astype("float32")
        w = np.random.RandomState(0).randn(13).astype("float32")
        y = (x @ w + 0.1 * rng.randn(n)).astype("float32")[:, None]
        return x, y

    @staticmethod
    def train():
        def reader():
            x, y = uci_housing._make(404, seed=1)
            for i in range(len(x)):
                yield x[i], y[i]
        return reader

    @staticmethod
    def test():
        def reader():
            x, y = uci_housing._make(102, seed=2)
            for i in range(len(x)):
                yield x[i], y[i]
        return reader


class imdb:
    """ref dataset/imdb.py — tokenized reviews, binary sentiment.

    Synthetic: vocab of `word_dict_size`; positive docs oversample the first
    half of the vocab, negative the second half."""

    WORD_DICT_SIZE = 5147

    @staticmethod
    def word_dict():
        return {i: i for i in range(imdb.WORD_DICT_SIZE)}

    @staticmethod
    def _reader(n, seed, maxlen=100):
        def reader():
            rng = np.random.RandomState(seed)
            V = imdb.WORD_DICT_SIZE
            for _ in range(n):
                label = int(rng.randint(0, 2))
                length = int(rng.randint(10, maxlen))
                if label == 1:
                    words = rng.randint(0, V // 2, size=length)
                else:
                    words = rng.randint(V // 2, V, size=length)
                yield words.astype("int64").tolist(), label
        return reader

    @staticmethod
    def train(word_idx=None):
        return imdb._reader(1024, seed=3)

    @staticmethod
    def test(word_idx=None):
        return imdb._reader(256, seed=4)


class wmt14:
    """ref dataset/wmt14.py — (src_ids, trg_ids, trg_next_ids) triples."""

    DICT_SIZE = 30000

    @staticmethod
    def _reader(n, seed, dict_size, maxlen=16):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                length = int(rng.randint(4, maxlen))
                src = rng.randint(3, dict_size, size=length).astype("int64")
                # synthetic "translation": reversed source with offset
                trg = ((src[::-1] + 7) % (dict_size - 3) + 3).astype("int64")
                trg_in = np.concatenate([[1], trg])       # <s>
                trg_out = np.concatenate([trg, [2]])      # <e>
                yield src.tolist(), trg_in.tolist(), trg_out.tolist()
        return reader

    @staticmethod
    def train(dict_size=30000):
        return wmt14._reader(1024, 5, dict_size)

    @staticmethod
    def test(dict_size=30000):
        return wmt14._reader(128, 6, dict_size)


class imagenet_synthetic:
    """Synthetic ImageNet-shaped batches for ResNet-50 benchmarking."""

    @staticmethod
    def train(image_shape=(3, 224, 224), num_classes=1000, n=512, seed=11):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                label = int(rng.randint(0, num_classes))
                img = rng.randn(*image_shape).astype("float32")
                yield img, label
        return reader


class ctr_synthetic:
    """Criteo-shaped CTR data for DeepFM/Wide&Deep (ref dist_ctr.py):
    26 sparse slots + 13 dense features → click."""

    @staticmethod
    def train(n=4096, sparse_dim=1000, seed=13):
        def reader():
            rng = np.random.RandomState(seed)
            w_dense = np.random.RandomState(0).randn(13) * 0.3
            for _ in range(n):
                dense = rng.randn(13).astype("float32")
                sparse = rng.randint(0, sparse_dim, size=26).astype("int64")
                logit = dense @ w_dense + 0.05 * (sparse[0] % 7 - 3)
                click = int(rng.rand() < 1 / (1 + np.exp(-logit)))
                yield dense, sparse, click
        return reader


class cifar:
    """ref dataset/cifar.py — 32×32×3 images flattened to 3072 floats in
    [0,1]; cifar10 and cifar100 label spaces."""

    @staticmethod
    def _reader(n, seed, num_classes):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                label = int(rng.randint(0, num_classes))
                img = rng.rand(3, 32, 32).astype("float32") * 0.4
                # class-dependent color bias so models can learn
                img[label % 3] += 0.3 + 0.3 * ((label // 3) % 2)
                yield np.clip(img, 0, 1).ravel(), label
        return reader

    @staticmethod
    def train10():
        return cifar._reader(2048, 21, 10)

    @staticmethod
    def test10():
        return cifar._reader(512, 22, 10)

    @staticmethod
    def train100():
        return cifar._reader(2048, 23, 100)

    @staticmethod
    def test100():
        return cifar._reader(512, 24, 100)


class imikolov:
    """ref dataset/imikolov.py — PTB-style n-gram LM tuples.

    Synthetic text follows a deterministic first-order chain (next word =
    f(prev)) + noise, so an n-gram model is learnable."""

    DICT_SIZE = 2073

    @staticmethod
    def build_dict(min_word_freq=50):
        return {f"w{i}": i for i in range(imikolov.DICT_SIZE)}

    @staticmethod
    def _reader(n, seed, word_idx, ngram):
        V = len(word_idx) if word_idx else imikolov.DICT_SIZE

        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                w = [int(rng.randint(0, V))]
                for _ in range(ngram - 1):
                    nxt = (w[-1] * 7 + 3) % V if rng.rand() < 0.8 \
                        else int(rng.randint(0, V))
                    w.append(nxt)
                yield tuple(w)
        return reader

    @staticmethod
    def train(word_idx=None, n=5):
        return imikolov._reader(4096, 31, word_idx, n)

    @staticmethod
    def test(word_idx=None, n=5):
        return imikolov._reader(512, 32, word_idx, n)


class movielens:
    """ref dataset/movielens.py — (user features, movie features, rating)."""

    MAX_USER_ID = 6040
    MAX_MOVIE_ID = 3952
    MAX_JOB_ID = 20
    AGES = [1, 18, 25, 35, 45, 50, 56]
    CATEGORIES = 18
    TITLE_DICT_LEN = 5175

    @staticmethod
    def max_user_id():
        return movielens.MAX_USER_ID

    @staticmethod
    def max_movie_id():
        return movielens.MAX_MOVIE_ID

    @staticmethod
    def max_job_id():
        return movielens.MAX_JOB_ID

    @staticmethod
    def age_table():
        return list(movielens.AGES)

    @staticmethod
    def movie_categories():
        return {f"cat{i}": i for i in range(movielens.CATEGORIES)}

    @staticmethod
    def get_movie_title_dict():
        return {f"title{i}": i for i in range(movielens.TITLE_DICT_LEN)}

    @staticmethod
    def _reader(n, seed):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                user = int(rng.randint(1, movielens.MAX_USER_ID + 1))
                gender = int(rng.randint(0, 2))
                age = int(rng.randint(0, len(movielens.AGES)))
                job = int(rng.randint(0, movielens.MAX_JOB_ID + 1))
                movie = int(rng.randint(1, movielens.MAX_MOVIE_ID + 1))
                cats = rng.randint(0, movielens.CATEGORIES,
                                   size=rng.randint(1, 4)).tolist()
                title = rng.randint(0, movielens.TITLE_DICT_LEN,
                                    size=rng.randint(1, 6)).tolist()
                # learnable rating: affinity between user and movie hashes
                score = 1 + (user * 31 + movie * 17) % 5
                yield user, gender, age, job, movie, cats, title, \
                    float(score)
        return reader

    @staticmethod
    def train():
        return movielens._reader(4096, 41)

    @staticmethod
    def test():
        return movielens._reader(512, 42)


class conll05:
    """ref dataset/conll05.py — SRL tuples: (words, predicate, ctx windows,
    marks, labels) as index lists."""

    WORD_DICT_LEN = 44068
    LABEL_DICT_LEN = 59
    PRED_DICT_LEN = 3162

    @staticmethod
    def get_dict():
        word_dict = {f"w{i}": i for i in range(conll05.WORD_DICT_LEN)}
        verb_dict = {f"v{i}": i for i in range(conll05.PRED_DICT_LEN)}
        label_dict = {f"l{i}": i for i in range(conll05.LABEL_DICT_LEN)}
        return word_dict, verb_dict, label_dict

    @staticmethod
    def get_embedding():
        rng = np.random.RandomState(55)
        return rng.randn(conll05.WORD_DICT_LEN, 32).astype("float32")

    @staticmethod
    def test():
        """Reference slot order (conll05.py reader): (words, ctx_n2,
        ctx_n1, ctx_0, ctx_p1, ctx_p2, verb, mark, labels) — the five
        context windows and the verb are per-token sequences (the sentence
        -level value repeated for every token)."""
        def reader():
            rng = np.random.RandomState(51)
            for _ in range(256):
                length = int(rng.randint(5, 30))
                words = rng.randint(0, conll05.WORD_DICT_LEN,
                                    size=length).astype("int64")
                pred_pos = int(rng.randint(0, length))
                predicate = int(words[pred_pos] % conll05.PRED_DICT_LEN)
                mark = np.zeros(length, "int64")
                mark[pred_pos] = 1
                # labels depend on distance to predicate: learnable
                labels = np.minimum(np.abs(np.arange(length) - pred_pos),
                                    conll05.LABEL_DICT_LEN - 1
                                    ).astype("int64")
                ctx = [[int(words[max(0, min(length - 1, pred_pos + d))])]
                       * length for d in (-2, -1, 0, 1, 2)]
                yield (words.tolist(), ctx[0], ctx[1], ctx[2], ctx[3],
                       ctx[4], [predicate] * length, mark.tolist(),
                       labels.tolist())
        return reader


class sentiment:
    """ref dataset/sentiment.py — NLTK movie-review polarity; shares the
    imdb vocabulary since its readers delegate to imdb._reader."""

    @staticmethod
    def get_word_dict():
        return {f"w{i}": i for i in range(imdb.WORD_DICT_SIZE)}

    @staticmethod
    def train():
        return imdb._reader(1024, 61)

    @staticmethod
    def test():
        return imdb._reader(256, 62)


class wmt16:
    """ref dataset/wmt16.py — like wmt14 with explicit dict sizes + BPE."""

    @staticmethod
    def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
        return wmt14._reader(1024, 71, min(src_dict_size, trg_dict_size))

    @staticmethod
    def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
        return wmt14._reader(128, 72, min(src_dict_size, trg_dict_size))

    @staticmethod
    def validation(src_dict_size=10000, trg_dict_size=10000, src_lang="en"):
        return wmt14._reader(128, 73, min(src_dict_size, trg_dict_size))

    @staticmethod
    def get_dict(lang, dict_size, reverse=False):
        d = {f"{lang}{i}": i for i in range(dict_size)}
        return {v: k for k, v in d.items()} if reverse else d


class flowers:
    """ref dataset/flowers.py — 102-class 3×224×224 images."""

    @staticmethod
    def _reader(n, seed):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                label = int(rng.randint(0, 102))
                img = rng.rand(3, 224, 224).astype("float32")
                yield img, label
        return reader

    @staticmethod
    def train(mapper=None, buffered_size=1024, use_xmap=True):
        return flowers._reader(512, 81)

    @staticmethod
    def test(mapper=None, buffered_size=1024, use_xmap=True):
        return flowers._reader(128, 82)

    @staticmethod
    def valid(mapper=None, buffered_size=1024, use_xmap=True):
        return flowers._reader(128, 83)


class voc2012:
    """ref dataset/voc2012.py — segmentation pairs (image, label mask)."""

    @staticmethod
    def _reader(n, seed, hw=64):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                img = rng.rand(3, hw, hw).astype("float32")
                mask = (img.sum(0) > 1.5).astype("int32")  # learnable seg
                yield img, mask
        return reader

    @staticmethod
    def train():
        return voc2012._reader(256, 91)

    @staticmethod
    def test():
        return voc2012._reader(64, 92)

    @staticmethod
    def val():
        return voc2012._reader(64, 93)
