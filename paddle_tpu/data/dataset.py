"""Dataset loaders (ref ``python/paddle/dataset/``: mnist, imdb, wmt14/16,
uci_housing, imagenet…).

This environment has zero egress, so each corpus has a *synthetic* generator
with the exact sample schema of the reference loader (shape/dtype/range), a
fixed seed for reproducibility, and enough structure (class-dependent means,
label-correlated tokens) that models measurably learn — which is what the
book-style convergence tests need.  Real-data loading hooks are the same
function signatures reading from ``data_dir`` when provided.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np


class mnist:
    """ref python/paddle/dataset/mnist.py — 28×28 images in [-1,1], int label.

    Synthetic mode: class-conditional blob images (digit = position of a
    bright patch), linearly separable enough for the book convergence test.
    """

    IMAGE_SIZE = 784

    @staticmethod
    def _synthetic(n, seed):
        rng = np.random.RandomState(seed)
        labels = rng.randint(0, 10, size=n).astype("int64")
        imgs = rng.randn(n, 28, 28).astype("float32") * 0.15
        for i, lab in enumerate(labels):
            r, c = divmod(int(lab), 5)
            imgs[i, 4 + r * 12: 12 + r * 12, 2 + c * 5: 7 + c * 5] += 1.0
        imgs = np.clip(imgs, -1.0, 1.0).reshape(n, 784)
        return imgs, labels

    @staticmethod
    def _reader(n, seed):
        def reader():
            imgs, labels = mnist._synthetic(n, seed)
            for i in range(n):
                yield imgs[i], int(labels[i])
        return reader

    @staticmethod
    def train(data_dir=None):
        if data_dir:
            return mnist._idx_reader(data_dir, "train")
        return mnist._reader(2048, seed=42)

    @staticmethod
    def test(data_dir=None):
        if data_dir:
            return mnist._idx_reader(data_dir, "t10k")
        return mnist._reader(512, seed=7)

    @staticmethod
    def _idx_reader(data_dir, split):
        def reader():
            imgf = os.path.join(data_dir, f"{split}-images-idx3-ubyte.gz")
            labf = os.path.join(data_dir, f"{split}-labels-idx1-ubyte.gz")
            with gzip.open(imgf, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                imgs = np.frombuffer(f.read(), dtype=np.uint8)
                imgs = imgs.reshape(n, rows * cols).astype("float32")
                imgs = imgs / 127.5 - 1.0
            with gzip.open(labf, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), dtype=np.uint8).astype("int64")
            for i in range(n):
                yield imgs[i], int(labels[i])
        return reader


class uci_housing:
    """ref dataset/uci_housing.py — 13 features → 1 price."""

    @staticmethod
    def _make(n, seed):
        rng = np.random.RandomState(seed)
        x = rng.randn(n, 13).astype("float32")
        w = rng.RandomState(0).randn(13).astype("float32")
        y = (x @ w + 0.1 * rng.randn(n)).astype("float32")[:, None]
        return x, y

    @staticmethod
    def train():
        def reader():
            x, y = uci_housing._make(404, seed=1)
            for i in range(len(x)):
                yield x[i], y[i]
        return reader

    @staticmethod
    def test():
        def reader():
            x, y = uci_housing._make(102, seed=2)
            for i in range(len(x)):
                yield x[i], y[i]
        return reader


class imdb:
    """ref dataset/imdb.py — tokenized reviews, binary sentiment.

    Synthetic: vocab of `word_dict_size`; positive docs oversample the first
    half of the vocab, negative the second half."""

    WORD_DICT_SIZE = 5147

    @staticmethod
    def word_dict():
        return {i: i for i in range(imdb.WORD_DICT_SIZE)}

    @staticmethod
    def _reader(n, seed, maxlen=100):
        def reader():
            rng = np.random.RandomState(seed)
            V = imdb.WORD_DICT_SIZE
            for _ in range(n):
                label = int(rng.randint(0, 2))
                length = int(rng.randint(10, maxlen))
                if label == 1:
                    words = rng.randint(0, V // 2, size=length)
                else:
                    words = rng.randint(V // 2, V, size=length)
                yield words.astype("int64").tolist(), label
        return reader

    @staticmethod
    def train(word_idx=None):
        return imdb._reader(1024, seed=3)

    @staticmethod
    def test(word_idx=None):
        return imdb._reader(256, seed=4)


class wmt14:
    """ref dataset/wmt14.py — (src_ids, trg_ids, trg_next_ids) triples."""

    DICT_SIZE = 30000

    @staticmethod
    def _reader(n, seed, dict_size, maxlen=16):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                length = int(rng.randint(4, maxlen))
                src = rng.randint(3, dict_size, size=length).astype("int64")
                # synthetic "translation": reversed source with offset
                trg = ((src[::-1] + 7) % (dict_size - 3) + 3).astype("int64")
                trg_in = np.concatenate([[1], trg])       # <s>
                trg_out = np.concatenate([trg, [2]])      # <e>
                yield src.tolist(), trg_in.tolist(), trg_out.tolist()
        return reader

    @staticmethod
    def train(dict_size=30000):
        return wmt14._reader(1024, 5, dict_size)

    @staticmethod
    def test(dict_size=30000):
        return wmt14._reader(128, 6, dict_size)


class imagenet_synthetic:
    """Synthetic ImageNet-shaped batches for ResNet-50 benchmarking."""

    @staticmethod
    def train(image_shape=(3, 224, 224), num_classes=1000, n=512, seed=11):
        def reader():
            rng = np.random.RandomState(seed)
            for _ in range(n):
                label = int(rng.randint(0, num_classes))
                img = rng.randn(*image_shape).astype("float32")
                yield img, label
        return reader


class ctr_synthetic:
    """Criteo-shaped CTR data for DeepFM/Wide&Deep (ref dist_ctr.py):
    26 sparse slots + 13 dense features → click."""

    @staticmethod
    def train(n=4096, sparse_dim=1000, seed=13):
        def reader():
            rng = np.random.RandomState(seed)
            w_dense = rng.RandomState(0).randn(13) * 0.3
            for _ in range(n):
                dense = rng.randn(13).astype("float32")
                sparse = rng.randint(0, sparse_dim, size=26).astype("int64")
                logit = dense @ w_dense + 0.05 * (sparse[0] % 7 - 3)
                click = int(rng.rand() < 1 / (1 + np.exp(-logit)))
                yield dense, sparse, click
        return reader
