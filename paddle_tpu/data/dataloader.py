"""Device-feeding DataLoader with background prefetch.

TPU-native replacement for the reference reader stack: ``PyReader``
(``python/paddle/fluid/reader.py:47``) pushing into a C++
``LoDTensorBlockingQueue`` drained by ``create_py_reader`` +
``create_double_buffer_reader`` (``operators/reader/buffered_reader.cc`` —
prefetch to device).  Here a Python thread stages numpy batches and
``jax.device_put`` starts the host→HBM copy ahead of compute; with a mesh it
shards the batch across devices (the multi-device feed split the reference
does in ``ParallelExecutor::FeedTensorsIntoLocalScopes``).
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Callable, Dict, Iterable, Optional

import jax
import numpy as np

from .. import monitor as _monitor

#: dataloader telemetry: queue depth (gauge = live value, histogram =
#: occupancy distribution sampled at consumer gets) and staged-batch
#: counts.  A queue that is usually EMPTY at get time means the device is
#: starved and the host pipeline is the bottleneck; usually FULL means
#: compute-bound — the occupancy histogram makes that one glance.
#: Labeled per pipeline (the staging serial) so two concurrent loaders —
#: a saturated eval queue next to a starved train queue — never blend
#: into one misleading series; finished pipelines fold into
#: pipeline="retired" (totals preserved, registry growth bounded).
_QUEUE_DEPTH = _monitor.REGISTRY.gauge(
    "paddle_tpu_dataloader_queue_depth",
    "current prefetch-queue depth (staged batches waiting)",
    ("pipeline",))
_QUEUE_OCC = _monitor.REGISTRY.histogram(
    "paddle_tpu_dataloader_queue_occupancy",
    "prefetch-queue depth sampled at each consumer get",
    ("pipeline",),
    buckets=(0.0, 1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0))
_BATCHES_STAGED = _monitor.REGISTRY.counter(
    "paddle_tpu_dataloader_batches_staged",
    "batches parsed + staged to device by producer threads",
    ("pipeline",))
_PRODUCER_ERRORS = _monitor.REGISTRY.counter(
    "paddle_tpu_dataloader_producer_errors_total",
    "producer-thread failures surfaced to the consumer (the re-raise "
    "chains the producer traceback)")
_PRODUCER_RESTARTS = _monitor.REGISTRY.counter(
    "paddle_tpu_dataloader_producer_restarts_total",
    "bounded producer restarts after an injected/transient fault "
    "(at most one per pipeline, with backoff)")


def _retire_producer_series(pipe: str):
    """Registry hygiene for the series the PRODUCER thread writes, called
    from its own finally — the consumer's join has a timeout, so retiring
    these from the consumer could pop cells a still-running producer then
    bumps into the void, losing counts from the process totals.  A dead
    pipeline's live depth is meaningless, so the gauge is just dropped."""
    _BATCHES_STAGED.fold({"pipeline": pipe}, {"pipeline": "retired"})
    _QUEUE_DEPTH.fold({"pipeline": pipe}, None)


def _retire_consumer_series(pipe: str):
    """Registry hygiene for the consumer-side occupancy histogram."""
    _QUEUE_OCC.fold({"pipeline": pipe}, {"pipeline": "retired"})

#: per-prefetch-source identity for the staging-side int64 wrap check:
#: each loader/reader iteration gets its own token namespace, so one
#: run's in-range first batch can never suppress a later run's warning
#: (and no Executor.close() interplay is needed to re-arm it)
_stage_serials = itertools.count()


def _drop_stage_tokens(src):
    """Retire a finished pipeline's int64-check dedup tokens: each
    iteration mints a fresh serial, so a long-running process re-iterating
    a loader per epoch would otherwise grow the module-global token set
    forever.  This is the ONLY retirement path — program-id tokens are
    process-lifetime (Executor.close() no longer re-arms them; the
    verifier's static classification subsumes the check for verified
    programs)."""
    from ..framework.executor import (_checked_int64_feeds,
                                      _checked_int64_lock)
    with _checked_int64_lock:
        _checked_int64_feeds.difference_update(
            [t for t in _checked_int64_feeds if t[0] == src])


class DataLoader:
    def __init__(self, feed_list=None, capacity=4, iterable=True,
                 return_list=False, use_double_buffer=True):
        self.feed_list = feed_list or []
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self._batch_fn: Optional[Callable] = None
        self._places = None

    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=False):
        return DataLoader(feed_list, capacity, iterable, return_list,
                          use_double_buffer)

    def set_batch_generator(self, generator, places=None):
        self._batch_fn = generator
        self._places = places
        return self

    def set_sample_list_generator(self, generator, places=None):
        from .feeder import DataFeeder
        feeder = DataFeeder(self.feed_list)

        def batches():
            for samples in generator():
                yield feeder.feed(samples)
        self._batch_fn = batches
        self._places = places
        return self

    def __iter__(self):
        if self._batch_fn is None:
            raise ValueError("call set_batch_generator/"
                             "set_sample_list_generator first")
        if not self.use_double_buffer:
            yield from self._batch_fn()
            return
        yield from _prefetch_to_device(self._batch_fn, self.capacity)


def _prefetch_to_device(batch_fn, capacity, sharding=None, stage=True):
    """Double-buffer: stage next batch to device while current one computes.

    ``stage=False`` keeps batches as host arrays (the producer thread still
    overlaps file parsing with device compute): a mesh spanning processes
    needs host-local numpy for ``host_local_array_to_global_array`` — a
    pre-staged single-device ``jax.Array`` would be pulled BACK to host
    (a D2H sync on the dispatch thread) every step.

    The producer thread is shutdown-safe: a consumer that stops iterating
    early (break / exception / generator close) sets a stop flag and drains
    the queue, so a producer parked on a full-queue ``put`` wakes, skips the
    rest of its input, and exits — instead of blocking forever and leaking
    the thread (and whatever file handles its ``batch_fn`` holds)."""
    q: queue.Queue = queue.Queue(maxsize=capacity)
    stop = threading.Event()
    err = []
    _End = object()
    src = ("stage", next(_stage_serials))
    pipe = str(src[1])
    depth_cell = _QUEUE_DEPTH.labels(pipeline=pipe)
    occ_cell = _QUEUE_OCC.labels(pipeline=pipe)
    staged_cell = _BATCHES_STAGED.labels(pipeline=pipe)

    def _put_or_stop(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def producer():
        from .. import resilience as _resil
        restarts = 0
        try:
            it = iter(batch_fn())
            while not stop.is_set():
                try:
                    # bounded restart: ONE injected-transient fault gets a
                    # backed-off second chance.  The hook fires BEFORE the
                    # user iterator is touched, so the restart provably
                    # skips or duplicates no batch.  A fault raised inside
                    # the source itself is NOT restartable this way — a
                    # generator that raised is closed by PEP 342, and
                    # re-calling next() would silently truncate the epoch
                    # — so source errors always surface to the consumer.
                    _resil.maybe_inject("dataloader.produce")
                except Exception as e:
                    if _resil.is_transient(e) and restarts < 1:
                        restarts += 1
                        _PRODUCER_RESTARTS.inc()
                        delay = _resil.backoff_schedule(
                            2, base_delay_s=0.05, seed=0)[0]
                        with _monitor.TRACER.span(
                                "retry.backoff", "resilience",
                                site="dataloader.produce"):
                            stop.wait(delay)
                        continue
                    raise
                try:
                    batch = next(it)
                except StopIteration:
                    return
                tb0 = time.perf_counter()
                if not stage:
                    staged = batch
                elif isinstance(batch, dict):
                    staged = {k: _put(v, sharding, name=k, src=src)
                              for k, v in batch.items()}
                else:
                    # positional slots need distinct check tokens, or only
                    # the first int64 column of the source is ever scanned
                    staged = [_put(v, sharding, name=f"@{j}", src=src)
                              for j, v in enumerate(batch)]
                staged_cell.inc()
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.add_complete(
                        "dataloader.stage_batch", "dataloader", tb0,
                        time.perf_counter())
                if not _put_or_stop(staged):
                    return
                depth = q.qsize()
                depth_cell.set(depth)
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.counter(
                        "dataloader.queue_depth", depth)
        except Exception as e:   # surfaced on next consumer get
            err.append(e)
            _PRODUCER_ERRORS.inc()
        finally:
            _put_or_stop(_End)
            _retire_producer_series(pipe)

    t = threading.Thread(target=producer, daemon=True,
                         name="pt-prefetch")
    t.start()
    try:
        while True:
            # occupancy sampled BEFORE the blocking get: 0 here means the
            # consumer will now stall on the producer (host-bound input)
            depth = q.qsize()
            occ_cell.observe(depth)
            tw0 = time.perf_counter()
            item = q.get()
            tw1 = time.perf_counter()
            depth_cell.set(q.qsize())
            if _monitor.TRACER.enabled and depth == 0:
                _monitor.TRACER.add_complete(
                    "dataloader.wait", "dataloader", tw0, tw1)
            if item is _End:
                if err:
                    # chain, don't re-raise bare: the consumer-side error
                    # carries BOTH stacks — where the loop consumed and
                    # (via __cause__) where the producer thread actually
                    # failed inside the user's generator
                    raise RuntimeError(
                        "dataloader producer thread failed: "
                        f"{err[0]}") from err[0]
                return
            yield item
    finally:
        stop.set()
        try:                     # unblock a producer waiting on a full queue
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=5)
        _drop_stage_tokens(src)
        _retire_consumer_series(pipe)


def _put(x, sharding=None, name=None, src=None):
    if isinstance(x, jax.Array):
        if sharding is not None:
            # an already-staged array still honors a requested placement
            # (it may be committed to one device; the mesh needs it laid
            # out per the sharding)
            return jax.device_put(x, sharding)
        return x                 # already staged — device_put would re-copy
    a = np.asarray(x)
    if a.dtype in (np.int64, np.uint64) and not jax.config.jax_enable_x64:
        # the silent int32-narrowing wrap check must see the original host
        # values, and staging happens before the executor ever would — so
        # run it HERE, in the producer thread (a first-batch-per-source
        # min/max scan, off the dispatch path), then stage as usual so
        # the H2D copy still overlaps compute
        from ..framework.executor import _check_int64_range
        _check_int64_range(a, name, src)
    if sharding is not None:
        return jax.device_put(a, sharding)
    return jax.device_put(a)
