"""Device-feeding DataLoader with background prefetch.

TPU-native replacement for the reference reader stack: ``PyReader``
(``python/paddle/fluid/reader.py:47``) pushing into a C++
``LoDTensorBlockingQueue`` drained by ``create_py_reader`` +
``create_double_buffer_reader`` (``operators/reader/buffered_reader.cc`` —
prefetch to device).  Here a Python thread stages numpy batches and
``jax.device_put`` starts the host→HBM copy ahead of compute; with a mesh it
shards the batch across devices (the multi-device feed split the reference
does in ``ParallelExecutor::FeedTensorsIntoLocalScopes``).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, Iterable, Optional

import jax
import numpy as np


class DataLoader:
    def __init__(self, feed_list=None, capacity=4, iterable=True,
                 return_list=False, use_double_buffer=True):
        self.feed_list = feed_list or []
        self.capacity = capacity
        self.use_double_buffer = use_double_buffer
        self._batch_fn: Optional[Callable] = None
        self._places = None

    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=False):
        return DataLoader(feed_list, capacity, iterable, return_list,
                          use_double_buffer)

    def set_batch_generator(self, generator, places=None):
        self._batch_fn = generator
        self._places = places
        return self

    def set_sample_list_generator(self, generator, places=None):
        from .feeder import DataFeeder
        feeder = DataFeeder(self.feed_list)

        def batches():
            for samples in generator():
                yield feeder.feed(samples)
        self._batch_fn = batches
        self._places = places
        return self

    def __iter__(self):
        if self._batch_fn is None:
            raise ValueError("call set_batch_generator/"
                             "set_sample_list_generator first")
        if not self.use_double_buffer:
            yield from self._batch_fn()
            return
        yield from _prefetch_to_device(self._batch_fn, self.capacity)


def _prefetch_to_device(batch_fn, capacity, sharding=None):
    """Double-buffer: stage next batch to device while current one computes."""
    class _End:
        pass

    q: queue.Queue = queue.Queue(maxsize=capacity)
    err = []

    def producer():
        try:
            for batch in batch_fn():
                if isinstance(batch, dict):
                    staged = {k: _put(v, sharding) for k, v in batch.items()}
                else:
                    staged = [_put(v, sharding) for v in batch]
                q.put(staged)
        except Exception as e:   # surfaced on next consumer get
            err.append(e)
        finally:
            q.put(_End)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    while True:
        item = q.get()
        if item is _End:
            if err:
                raise err[0]
            break
        yield item


def _put(x, sharding=None):
    if sharding is not None:
        return jax.device_put(x, sharding)
    return jax.device_put(np.asarray(x))
