"""Evaluator API (ref ``python/paddle/fluid/evaluator.py``).

Deprecated in the reference in favor of ``fluid.metrics`` — kept for API
parity.  Each evaluator owns host-side accumulator state and exposes the
reference protocol: construct with graph outputs, call ``update`` with the
fetched per-batch values, ``eval()`` for the aggregate, ``reset()`` between
passes (the reference stores state in scope variables and appends update
ops; under the block-compiler the per-batch stats are just fetched and
reduced host-side, same numbers)."""

from __future__ import annotations

from . import metrics as _metrics

__all__ = ["Evaluator", "ChunkEvaluator", "EditDistance", "DetectionMAP"]


class Evaluator:
    """ref evaluator.py Evaluator: named metric states + reset/eval."""

    def __init__(self, name, **kwargs):
        self.metric = None
        self.states = []
        self.helper_name = name

    def reset(self, executor=None, reset_program=None):
        if self.metric is not None:
            self.metric.reset()

    def eval(self, executor=None, eval_program=None):
        raise NotImplementedError


class ChunkEvaluator(Evaluator):
    """ref evaluator.py ChunkEvaluator: F1 over chunk counts; pass the
    ``chunk_eval`` op's count outputs to ``update``."""

    def __init__(self, input=None, label=None, chunk_scheme=None,
                 num_chunk_types=None, excluded_chunk_types=None):
        super().__init__("chunk_eval")
        self.metric = _metrics.ChunkEvaluator()

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.metric.update(num_infer_chunks, num_label_chunks,
                           num_correct_chunks)

    def eval(self, executor=None, eval_program=None):
        return self.metric.eval()


class EditDistance(Evaluator):
    """ref evaluator.py EditDistance."""

    def __init__(self, input=None, label=None, ignored_tokens=None):
        super().__init__("edit_distance")
        self.metric = _metrics.EditDistance()

    def update(self, distances, seq_num):
        self.metric.update(distances, seq_num)

    def eval(self, executor=None, eval_program=None):
        return self.metric.eval()


class DetectionMAP(Evaluator):
    """ref evaluator.py DetectionMAP."""

    def __init__(self, input=None, gt_label=None, gt_box=None,
                 gt_difficult=None, class_num=None,
                 background_label=0, overlap_threshold=0.5,
                 evaluate_difficult=True, ap_version="integral"):
        super().__init__("map_eval")
        self.metric = _metrics.DetectionMAP(
            overlap_threshold=overlap_threshold,
            evaluate_difficult=evaluate_difficult, ap_version=ap_version)

    def update(self, pred, gt):
        self.metric.update(pred, gt)

    def eval(self, executor=None, eval_program=None):
        return self.metric.eval()
