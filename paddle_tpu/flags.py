"""Runtime flag system (ref ``platform/flags.cc`` ~40 gflags,
``python/paddle/fluid/__init__.py`` ``__bootstrap__`` reading ``FLAGS_*``
env vars, ``core.globals()`` pybind dict).

TPU mapping: knobs that steer CUDA allocators/cudnn autotune have no
hardware meaning here and are accepted as inert parity flags; the ones
with a real XLA-side effect are wired:

- ``check_nan_inf``   → per-op output finite-checks naming the fluid op
  (executor.py _sanitize_outputs; the per-kernel validation of
  ``FLAGS_check_nan_inf``, tests/test_sanitizers.py)
- ``benchmark``       → per-step host sync in the executor (the reference
  adds per-op sync timing)
- ``allocator_strategy`` / ``eager_delete_tensor_gb`` → recorded; XLA owns
  device memory, the native host allocator reads the strategy
"""

from __future__ import annotations

import os
from typing import Any, Dict

__all__ = ["get_flags", "set_flags", "globals"]

#: name → default (ref platform/flags.cc:33-391; GPU-only knobs kept for
#: API parity, marked inert)
_DEFAULTS: Dict[str, Any] = {
    "FLAGS_check_nan_inf": False,
    "FLAGS_benchmark": False,
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_fast_eager_deletion_mode": True,
    "FLAGS_memory_fraction_of_eager_deletion": 1.0,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,     # inert on TPU
    "FLAGS_initial_gpu_memory_in_mb": 0,             # inert
    "FLAGS_reallocate_gpu_memory_in_mb": 0,          # inert
    "FLAGS_gpu_allocator_retry_time": 0,             # inert
    "FLAGS_cudnn_deterministic": False,              # inert
    "FLAGS_cudnn_exhaustive_search": False,          # inert
    "FLAGS_conv_workspace_size_limit": 512,          # inert
    "FLAGS_enable_parallel_graph": False,
    "FLAGS_sync_nccl_allreduce": True,               # inert (XLA collectives)
    "FLAGS_fuse_parameter_memory_size": -1,
    "FLAGS_fuse_parameter_groups_size": 3,
    "FLAGS_inner_op_parallelism": 0,
    "FLAGS_max_inmem_feed_queue_size": 64,
    "FLAGS_reader_queue_speed_test_mode": False,
    "FLAGS_pe_profile_fname": "",
    "FLAGS_print_sub_graph_dir": "",
    "FLAGS_selected_gpus": "",                       # inert
    "FLAGS_paddle_num_threads": 1,
    "FLAGS_dist_threadpool_size": 0,
    "FLAGS_rpc_deadline": 180000,
    "FLAGS_rpc_retry_times": 3,
    "FLAGS_tracer_profile_fname": "",
    # persistent XLA compilation cache (no reference analog — its CUDA
    # kernels ship precompiled; here first-compile is the analogous cost,
    # 20-40 s for a big train step, and the cache removes it on re-runs)
    "FLAGS_xla_compile_cache_dir": "",
    # unified runtime telemetry (paddle_tpu.monitor): span recording for
    # the step tracer.  The metrics REGISTRY is always live (it backs the
    # executor dispatch counters); this flag gates only the chrome-trace
    # span ring, which is cheap enough to default on.
    "FLAGS_telemetry": True,
    # when set, monitor.export() runs at process exit into this directory
    # (metrics.json + metrics.prom + trace.json)
    "FLAGS_telemetry_export_path": "",
    # span ring capacity: the tracer keeps the most recent N events so a
    # week-long training loop cannot grow host memory unbounded
    "FLAGS_telemetry_max_events": 200000,
    # fault-tolerance layer (paddle_tpu.resilience): deterministic fault
    # injection ("site:spec[;site:spec]", e.g. "ps.put:every=3;
    # dataloader.produce:p=0.1,seed=7") — empty disables every hook
    "FLAGS_fault_inject": "",
    # hung-step watchdog: a watched dispatch/materialize exceeding this
    # many seconds dumps all thread stacks + the telemetry ring and
    # raises HungStepError in the hung thread.  0 disables (default —
    # first compiles can legitimately take tens of seconds).
    "FLAGS_watchdog_timeout_s": 0.0,
    # where watchdog dumps land ("" = the system temp dir)
    "FLAGS_watchdog_dump_dir": "",
    # watchdog escalation tier for C-level hangs: the async HungStepError
    # only lands at a Python bytecode boundary, so a thread stuck inside
    # an XLA execute gets the dump but not the error.  "abort" SIGABRTs
    # the process (after a grace window past the deadline) when the hung
    # call still hasn't returned — faulthandler writes every thread's
    # stack on the way down.  "" (default) disables the tier.
    "FLAGS_watchdog_escalate": "",
    # background checkpoint daemon (resilience.CheckpointDaemon) cadence:
    # snapshot persistables every N completed steps and/or every S
    # seconds (whichever fires first); 0 disables that trigger.  The
    # capture runs on the training thread as cheap device-side copies;
    # serialization + the durable commit run on the daemon thread.
    "FLAGS_checkpoint_interval_steps": 0,
    "FLAGS_checkpoint_interval_secs": 0.0,
    # per-endpoint PS circuit breaker: after a retry budget is exhausted
    # at an endpoint, fail calls fast for this many seconds instead of
    # re-paying the full backoff per call; a half-open probe then
    # re-closes it.  0 disables the breaker.
    "FLAGS_rpc_circuit_break_secs": 0.0,
    # gang-commit barrier: how long the rank-0 leader waits for every
    # rank to announce the same emergency-checkpoint step before giving
    # up on publishing the COMMITTED manifest for it
    "FLAGS_gang_commit_timeout_s": 30.0,
    # socket gang coordinator (distributed/coordinator.py): heartbeat
    # cadence of every rank's GangClient, and how long a rank may miss
    # heartbeats before the coordinator declares it dead and degrades
    # the gang (survivors drain and park instead of hanging inside a
    # collective).  The timeout should comfortably exceed the longest
    # legitimate heartbeat gap — a cold XLA compile does NOT block the
    # heartbeat thread, so a few seconds of slack is plenty.
    "FLAGS_gang_heartbeat_interval_s": 0.5,
    "FLAGS_gang_heartbeat_timeout_s": 10.0,
    # elastic rejoin barrier: how long a surviving rank parks in
    # GangClient.wait_ready() for the launcher (--max_restarts) to
    # respawn a dead rank before giving up
    "FLAGS_gang_rejoin_timeout_s": 300.0,
    # chunked snapshot capture (resilience.CheckpointDaemon): snapshot
    # persistables in groups of at most this many MiB, materializing
    # each group to host before copying the next — bounds the extra HBM
    # of the capture window at the chunk size instead of doubling the
    # model.  Tradeoff: the device→host sync of each chunk lands on the
    # training thread.  0 (default) = single-pass device-side copies
    # (fastest capture, transient 2x HBM).
    "FLAGS_checkpoint_capture_chunk_mb": 0,
    # adaptive daemon cadence: when > 0, a checkpoint capture is
    # deferred until the last observed save time is at most this
    # fraction of the gap since the previous capture — a writer slower
    # than the cadence stretches the effective interval instead of
    # queueing (and dropping) snapshots.  Each stretched window bumps
    # paddle_tpu_checkpoint_cadence_stretched_total.  0 disables.
    "FLAGS_checkpoint_cadence_stretch_frac": 0.0,
    # program verifier (paddle_tpu.analysis.verifier): static checks
    # (def-before-use, dangling feed/fetch, shape consistency, dead ops,
    # use-after-donate, int64 feed-wrap classification, collective
    # ordering) run inside compiler.optimize before lowering.  Results
    # are cached on the source-program fingerprint, so steady-state
    # dispatch never re-verifies; error-severity findings raise
    # ProgramVerificationError at optimize time.
    "FLAGS_program_verify": True,
    # static HBM budget (paddle_tpu.analysis.memory): when > 0, the
    # verifier's static peak-memory plan exceeding this many MiB adds a
    # "memory_budget" warning diagnostic to the verify report (symbolic
    # -1 dims count as 1, so the estimate is a per-example lower bound).
    # 0 disables the check.
    "FLAGS_memory_budget_mb": 0,
    # automatic per-step gang barrier for the executor's collective
    # shard_map mode: each dispatched collective step first runs the
    # coordinator's fingerprint-enforcing step_barrier (socket gang
    # backend only), so divergent programs refuse BEFORE entering the
    # collective instead of deadlocking inside it.  Off by default: the
    # barrier costs one coordinator round trip per step.
    "FLAGS_gang_step_barrier": False,
    # step_barrier timeout for the automatic executor barrier above
    "FLAGS_gang_step_barrier_timeout_s": 60.0,
    # -- GSPMD model parallelism (paddle_tpu.parallel.partitioner) ---------
    # default mesh for CompiledProgram.with_gspmd when neither `mesh` nor
    # `axes` is passed: "dp:2,mp:4" grammar ({axis: size}, sizes must
    # multiply to the visible device count).  "" = 1×model-parallel over
    # every visible device.
    "FLAGS_gspmd_mesh": "",
    # default rule table for with_gspmd: "auto" (planner-driven — the
    # cheapest-communication table whose PER-SHARD static peak fits
    # FLAGS_memory_budget_mb), or a table name ("replicated",
    # "mp_hidden", "mp_hidden_vocab")
    "FLAGS_gspmd_rules": "auto",
    # sampling profiler (paddle_tpu.profiler.SAMPLER): every N executor
    # dispatches, capture a jax.profiler device-trace window of
    # FLAGS_profile_sample_window_steps steps into a bounded rotating
    # directory (FLAGS_profile_sample_dir, at most
    # FLAGS_profile_sample_max_windows kept, oldest deleted; a
    # manifest.json maps each window to its step range) — a week-long
    # run costs a few sampled windows, not a monolithic trace.  0
    # disables (default): the hot path is then one int compare.
    "FLAGS_profile_sample_every_n_steps": 0,
    "FLAGS_profile_sample_window_steps": 4,
    "FLAGS_profile_sample_dir": "",
    "FLAGS_profile_sample_max_windows": 8,
    # cost-guided graph fusion (analysis.fusion): the master gate for
    # the training-safe fusion pass in compiler.optimize's
    # pass-before-lowering slot (conv+bn+relu, matmul+bias+act+dropout,
    # embedding+layernorm -> fused Pallas-backed ops).  Default on:
    # with autotune off the pass applies on static legality + roofline
    # rank alone, and every fused lowering is an exact composition of
    # the unfused ops.  Executor dispatch plans and compiled programs
    # key on the fusion config, so flipping any of these invalidates
    # stale plans.
    "FLAGS_graph_fusion": True,
    # measured fallback: micro-benchmark each legal candidate (fused op
    # vs the XLA default chain, fingerprint+shape-keyed, persisted next
    # to the XLA compile cache) and rewrite only when the fused kernel
    # wins — makes a fused-program regression structurally impossible.
    # Off by default: the first encounter of each (pattern, shape) pays
    # two small jit compiles.
    "FLAGS_fusion_autotune": False,
    # roofline rank threshold: a candidate whose op class is below this
    # share of the program's analytic flop AND byte budget
    # (analysis.cost per-class shares) is not worth a rewrite
    "FLAGS_fusion_rank_threshold": 0.02,
    # sampling-profiler auto-trigger: when > 0, a capture window opens
    # the moment the executor's windowed-median step time regresses by
    # this fraction over the best median seen — the trace captures
    # exactly the slow window instead of whatever the periodic cadence
    # lands on.  Re-arms after the median recovers.  0 disables.
    "FLAGS_profile_sample_regress_frac": 0.0,
    # analytic-cost cross-check (analysis.cost vs XLA cost_analysis):
    # when on, a fresh compile goes through the AOT path so XLA's own
    # flop count is available, and the analytic model diverging >3x
    # warns + counts in paddle_tpu_cost_crosscheck_total{verdict}.  Off
    # by default: the AOT lower() pays a second trace of the block.
    "FLAGS_cost_crosscheck": False,
    # -- serving plane (paddle_tpu.serving) --------------------------------
    # bucketized shape cache: the sequence-length compile buckets incoming
    # requests are padded up to.  "16,32,64" = explicit list;
    # "pow2:LO:HI" = powers of two from LO to HI inclusive; "" lets the
    # server derive pow2 buckets from its max request length.  Compile
    # cost is bounded by the bucket count — arbitrary request shapes
    # never trigger a fresh XLA compile (TVM-style AOT shape buckets).
    "FLAGS_serving_shape_buckets": "",
    # continuous-batching width: requests per dispatched batch (each
    # bucket's batch is padded to exactly this many rows, so one bucket =
    # one compiled executable).  Per-bucket width is lowered automatically
    # when the static HBM plan at this width exceeds
    # FLAGS_memory_budget_mb (admission control).
    "FLAGS_serving_max_batch": 8,
    # how long the scheduler waits for more same-bucket arrivals before
    # dispatching a partial batch (the continuous-batching coalescing
    # window; 0 = dispatch immediately)
    "FLAGS_serving_batch_wait_ms": 2.0,
    # per-tenant admission quota: max requests a tenant may have queued +
    # in flight; excess submits are rejected (counted per tenant).
    # 0 = unlimited.
    "FLAGS_serving_tenant_quota": 0,
    # transient-fault absorption: how many times the scheduler re-runs a
    # batch whose dispatch raised a transient error (injected faults,
    # infra flakes tagged via resilience.mark_transient) before failing
    # the batch's requests
    "FLAGS_serving_max_retries": 1,
    # paged KV cache (gpt_causal decode serving): fixed-size page length
    # in tokens, and the page-pool size (0 = derive from the decode
    # engine's slot count and max sequence length).  Pages are donated to
    # each decode step so updates alias in place; per-request page lists
    # are freed on completion and reused with no recompile.
    "FLAGS_serving_kv_page_len": 16,
    "FLAGS_serving_kv_pages": 0,
    # per-tenant SLO objectives (serving.slo):
    # "tenantA:p99_ms=250,avail=99.9;tenantB:avail=99;*:p99_ms=500" —
    # p99_ms is the latency objective (a slower completed request is a
    # bad event), avail the good-fraction objective in percent (default
    # 99.0 when only p99_ms is given; failed requests are always bad).
    # Empty (default) disables the whole SLO plane.  Parse errors reject
    # at set_flags.
    "FLAGS_serving_slo": "",
    # multi-window burn-rate evaluation: trailing window lengths and the
    # breach threshold.  burn = bad_fraction / (1 - avail/100); a tenant
    # breaches when burn >= threshold on BOTH windows and recovers when
    # the fast-window burn falls under threshold/2 (hysteresis).
    "FLAGS_serving_slo_fast_window_s": 60.0,
    "FLAGS_serving_slo_slow_window_s": 600.0,
    "FLAGS_serving_slo_burn_threshold": 10.0,
    # evaluator cadence of the server's SLO thread
    "FLAGS_serving_slo_eval_interval_s": 1.0,
    # shed-on-burn: while a tenant is in breach, reject its NEW submits
    # at admission (reason="slo_shed") instead of queueing work that
    # will miss its objective anyway.  Off by default: shedding is a
    # policy decision (it trades availability burn for latency burn).
    "FLAGS_serving_slo_shed": False,
    # live scrape surface (serving.httpd): /metrics (Prometheus text),
    # /healthz (drain-aware), /statusz (JSON) on this port.  0 (default)
    # disables; serve_until_terminated starts it automatically when set.
    "FLAGS_metrics_port": 0,
    # bind address of the scrape endpoint.  The default exposes it to
    # the fleet (scrapers/balancers are off-box); set 127.0.0.1 to keep
    # it loopback-only.  Only consulted when the port is enabled.
    "FLAGS_metrics_host": "0.0.0.0",
    # -- serving fleet (paddle_tpu.serving.fleet) --------------------------
    # FleetRouter placement policy: "least_loaded" places each request on
    # the fresh, non-draining replica with the smallest serving queue
    # depth (srv_q digest key, tie-broken round-robin); "round_robin"
    # ignores load and rotates.
    "FLAGS_fleet_route_policy": "least_loaded",
    # serving-load digest freshness TTL: the srv_q/occ/slots/tps digest
    # keys stop riding the heartbeat (and the replica drops out of
    # router placement) when the serving scheduler has not proven
    # liveness within this many seconds — a wedged replica's last-known
    # -good load digest must not attract traffic forever.  Must be > 0.
    "FLAGS_fleet_digest_ttl_s": 10.0,
    # coordinator high availability: the launcher also starts a warm
    # standby coordinator (primary port + 1) mirroring manifest +
    # durable announcements over the replicated log, and exports a
    # two-address PADDLE_GANG_COORD so clients fail over to it.  When
    # the cluster has a second node, the STANDBY's launcher is node 1
    # (cross-node placement — the standby must not share the primary's
    # failure domain); single-node clusters keep both on node 0.
    "FLAGS_coordinator_standby": False,
    # -- fleet autoscaler (serving.autoscaler) -----------------------------
    # closed-loop target-size policy: the controller keeps the live
    # replica count inside [min, max].  min == max pins a static fleet
    # size (the controller still repairs deaths and runs the
    # degradation ladder, but never scales).  min must be >= 1 and
    # <= max (validated as an effective pair).
    "FLAGS_fleet_min_replicas": 1,
    "FLAGS_fleet_max_replicas": 4,
    # controller tick cadence — every decision (scale, shed, shrink)
    # is re-evaluated at this interval; the *_ticks knobs below are
    # counted in units of it.  Must be > 0.
    "FLAGS_fleet_scale_eval_interval_s": 2.0,
    # hysteresis: how many CONSECUTIVE ticks the scale-up condition
    # (fleet SLO burn breached on both windows AND mean queue depth >=
    # queue_high) / the scale-down condition (no breach, queue empty,
    # per-replica completion rate under idle_qps) must hold before the
    # target moves — a one-tick blip never scales the fleet
    "FLAGS_fleet_scale_up_ticks": 2,
    "FLAGS_fleet_scale_down_ticks": 5,
    # post-decision cooldown: after ANY target change the controller
    # refuses further target changes this long (death repair is exempt
    # — restoring a SIGKILLed replica is not a flap).  Must be >= 0.
    "FLAGS_fleet_scale_cooldown_s": 30.0,
    # scale-up pressure floor: mean srv_q across live replicas that
    # (together with SLO breach) counts as sustained queue pressure
    "FLAGS_fleet_queue_high": 4.0,
    # scale-down idle floor: a fleet whose per-replica completion rate
    # (req/s) stays under this while queues are empty is idle enough
    # to drain-and-retire one replica (down to min_replicas)
    "FLAGS_fleet_idle_qps": 0.5,
    # shed-vs-scale arbitration: how many consecutive breached ticks
    # before admission shedding engages (only while a spawn is in
    # flight or the fleet is already at max_replicas, and only when
    # FLAGS_serving_slo_shed is on — shedding is a policy decision)
    "FLAGS_fleet_shed_after_ticks": 2,
    # degradation ladder: a replica reporting HBM headroom below this
    # fraction (the PR-15 OOM-risk signal) gets a bucket-width shrink
    # control op before any global action; must be in [0, 1)
    "FLAGS_fleet_oom_headroom_frac": 0.10,
    # ladder escalation: ticks a replica may stay at OOM risk AFTER its
    # shrink before the controller drains and respawns it fresh
    "FLAGS_fleet_shrink_grace_ticks": 3,
    # spawn-failure backoff: after a failed spawn the controller waits
    # this long before retrying (shedding stays engaged meanwhile —
    # the failure must re-shed, never crash the loop).  Must be >= 0.
    "FLAGS_fleet_spawn_backoff_s": 10.0,
    # -- numerics observability plane (analysis.numerics) ------------------
    # in-graph tensor-health statistics folded into one packed output per
    # lowered step: "off" (default, zero cost), "sentinel" (NaN/Inf
    # trips for gradients + weight state and the global grad norm, one
    # reduction per tensor — the cheap always-on tier; no absmax, no
    # activations), "full" (adds per-variable grad norms/absmax,
    # weight-update ratios ‖Δw‖/‖w‖, activation absmax and log2
    # dynamic-range histograms).
    # Stats ride the PR-1 lazy-fetch path: the training thread never
    # syncs on them.  The mode is part of the executor's compiled-block
    # key, so flipping it re-lowers cleanly.
    "FLAGS_numerics": "off",
    # spike detection: a per-variable grad norm above spike_factor x its
    # windowed median fires a numerics.anomaly record (hysteresis
    # re-arms at factor/2); window is the median's sample count
    "FLAGS_numerics_spike_factor": 10.0,
    "FLAGS_numerics_window": 16,
    # bounded per-variable gauge series: only the top-K variables by
    # grad norm / update ratio hold registry series at a time (churn
    # folds out — PR-2 retirement semantics)
    "FLAGS_numerics_topk": 8,
    # checkpoint quarantine: a NaN/Inf-poisoned step HOLDS CheckpointDaemon
    # commits so the (gang) manifest never advances past the last
    # healthy step.  Disable only if you want poisoned snapshots.
    "FLAGS_numerics_quarantine": True,
    # -- collective-communication observability (analysis.comms) ----------
    # per-collective attribution for the executor's collective shard_map
    # path: synchronous payload-byte counters, a pre-collective host
    # timestamp exchange through the gang coordinator (straggler-wait vs
    # wire-time decomposition), and an off-thread monitor publishing
    # collective_ms/wait_ms histograms + the live bus-bandwidth gauge.
    # Default on: the hot-path cost is a few counter bumps and one queue
    # append; the coordinator gate engages only when a socket gang is
    # attached.
    "FLAGS_comms_telemetry": True,
    # how long the pre-collective timestamp exchange waits for every
    # rank to arrive before returning a partial view (the collective
    # itself would block at least this long on the same straggler; the
    # gate self-disarms after 3 consecutive failures so a desynced or
    # coordinator-less gang never stalls training on telemetry)
    "FLAGS_comms_gate_timeout_s": 10.0,
    # coordinator scrape surface: the launcher hosting the gang
    # coordinator also serves /metrics /healthz /statusz (the serving
    # MetricsHTTPServer, reused) on this port, so gang/comms gauges are
    # scrapeable without a serving stack.  0 (default) disables;
    # /healthz answers 503 while the gang is degraded.
    "FLAGS_coordinator_metrics_port": 0,
    # -- runtime HBM observability plane (paddle_tpu.hbm) ------------------
    # per-step live-bytes accounting: the executor notes every sampled
    # step boundary to an off-thread accountant that publishes
    # paddle_tpu_hbm_{live,peak,budget,headroom}_bytes, the plan-drift
    # gauge, and the per-class attribution.  Default on: the hot-path
    # cost is one bounded deque append per sampled step.
    "FLAGS_hbm_telemetry": True,
    # sample every Nth dispatched step (1 = every step; raise it on
    # very fast steps to cut worker-thread churn)
    "FLAGS_hbm_sample_every_n_steps": 1,
    # peak-watermark window: paddle_tpu_hbm_peak_bytes is the max of the
    # last N live-bytes samples
    "FLAGS_hbm_window": 16,
    # record each compiled executable's XLA buffer-assignment plan
    # (memory_analysis) through hbm.record_xla_plan on its first call —
    # the AOT object is reused for execution, so recording costs no
    # extra compile.  PADDLE_TPU_RECORD_HBM=1 is the legacy env alias.
    "FLAGS_hbm_record_plans": False,
    # headroom-regression capture trigger (the memory twin of
    # FLAGS_profile_sample_regress_frac): when > 0 and a budget is
    # known, a profiler capture window (trigger:"hbm_regress") opens
    # the sample the measured headroom shrinks by this fraction under
    # the best headroom seen; re-arms after it recovers half-way.
    "FLAGS_hbm_headroom_regress_frac": 0.0,
    # where OOM forensics dumps land ("" = FLAGS_watchdog_dump_dir,
    # else the system temp dir)
    "FLAGS_oom_dump_dir": "",
    # async dispatch throttle: max run() calls in flight before the
    # executor blocks on the oldest step's output.  2 ≈ classic double
    # buffering — enough to hide host work behind device compute without
    # letting lazy-fetch loops queue unbounded live buffers in HBM.
    # 0 disables the throttle (unbounded run-ahead).  FLAGS_benchmark's
    # per-step sync takes precedence: with it set the throttle never
    # engages.
    "FLAGS_executor_max_inflight_steps": 2,
}

_values: Dict[str, Any] = dict(_DEFAULTS)


def _coerce(name: str, raw):
    default = _DEFAULTS[name]
    if isinstance(default, bool):
        if isinstance(raw, str):
            return raw.lower() in ("1", "true", "yes", "on")
        return bool(raw)
    if isinstance(default, int) and not isinstance(default, bool):
        return int(raw)
    if isinstance(default, float):
        return float(raw)
    return str(raw)


def _apply_side_effects(name: str, value):
    # FLAGS_check_nan_inf is implemented at the framework level: the
    # executor binds a finite-check to every float output and reports the
    # producing FLUID op by name (executor.py _sanitize_outputs) — more
    # actionable than jax_debug_nans, which names XLA ops and aborts the
    # step before any framework-side reporting can run.
    if name == "FLAGS_telemetry":
        from . import monitor
        monitor.TRACER.enabled = bool(value)
    elif name == "FLAGS_telemetry_max_events":
        from . import monitor
        monitor.TRACER.set_capacity(int(value))
    elif name == "FLAGS_telemetry_export_path":
        from . import monitor
        if value:
            monitor.enable_export_on_exit(str(value))
        else:
            monitor.disable_export_on_exit()
    elif name == "FLAGS_fault_inject":
        from . import resilience
        resilience.configure(str(value))   # already validated in set_flags
    elif name == "FLAGS_watchdog_timeout_s":
        from . import resilience
        resilience.WATCHDOG.set_timeout(float(value))
    elif name == "FLAGS_watchdog_escalate":
        from . import resilience
        resilience.WATCHDOG.escalate = str(value)
    elif name in ("FLAGS_profile_sample_every_n_steps",
                  "FLAGS_profile_sample_window_steps",
                  "FLAGS_profile_sample_dir",
                  "FLAGS_profile_sample_max_windows",
                  "FLAGS_profile_sample_regress_frac"):
        from . import profiler
        # the store write precedes side effects in set_flags, so this
        # re-read already sees the new value
        fl = get_flags(["FLAGS_profile_sample_every_n_steps",
                        "FLAGS_profile_sample_window_steps",
                        "FLAGS_profile_sample_dir",
                        "FLAGS_profile_sample_max_windows",
                        "FLAGS_profile_sample_regress_frac"])
        profiler.SAMPLER.configure(
            int(fl["FLAGS_profile_sample_every_n_steps"]),
            int(fl["FLAGS_profile_sample_window_steps"]),
            str(fl["FLAGS_profile_sample_dir"]),
            int(fl["FLAGS_profile_sample_max_windows"]),
            regress_frac=float(
                fl["FLAGS_profile_sample_regress_frac"]))
    elif name in ("FLAGS_numerics", "FLAGS_numerics_spike_factor",
                  "FLAGS_numerics_window", "FLAGS_numerics_topk",
                  "FLAGS_numerics_quarantine"):
        from .analysis import numerics
        fl = get_flags(["FLAGS_numerics", "FLAGS_numerics_spike_factor",
                        "FLAGS_numerics_window", "FLAGS_numerics_topk",
                        "FLAGS_numerics_quarantine"])
        numerics.configure(
            str(fl["FLAGS_numerics"]),
            spike_factor=float(fl["FLAGS_numerics_spike_factor"]),
            window=int(fl["FLAGS_numerics_window"]),
            topk=int(fl["FLAGS_numerics_topk"]),
            quarantine=bool(fl["FLAGS_numerics_quarantine"]))
    elif name in ("FLAGS_hbm_telemetry", "FLAGS_hbm_sample_every_n_steps",
                  "FLAGS_hbm_window", "FLAGS_hbm_headroom_regress_frac"):
        from . import hbm
        fl = get_flags(["FLAGS_hbm_telemetry",
                        "FLAGS_hbm_sample_every_n_steps",
                        "FLAGS_hbm_window",
                        "FLAGS_hbm_headroom_regress_frac"])
        hbm.ACCOUNTANT.configure(
            bool(fl["FLAGS_hbm_telemetry"]),
            int(fl["FLAGS_hbm_sample_every_n_steps"]),
            int(fl["FLAGS_hbm_window"]),
            float(fl["FLAGS_hbm_headroom_regress_frac"]))
    elif name in ("FLAGS_rpc_retry_times", "FLAGS_rpc_deadline"):
        # the NATIVE ps client reads these via getenv (retry_times per
        # request, deadline at connect) — mirror flag changes into the
        # env so set_flags governs the transport retry loop
        os.environ[name] = str(int(value))
    elif name == "FLAGS_xla_compile_cache_dir":
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          str(value) if value else None)
        if value:
            try:
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 1.0)
            except Exception:
                pass  # knob varies across jax versions; dir alone works


def set_flags(flags: Dict[str, Any]):
    """ref paddle.set_flags / core.globals()[k] = v.

    All names and values validate before ANY is applied, so a bad entry
    cannot leave half-applied state."""
    coerced = {}
    for name, value in flags.items():
        if name not in _DEFAULTS:
            raise ValueError(f"unknown flag {name!r}")
        coerced[name] = _coerce(name, value)
        if name == "FLAGS_fault_inject":
            # parse HERE, in the validate-before-apply phase: a typo'd
            # spec must neither half-apply this set_flags call nor be
            # stored while silently never injecting
            from . import resilience
            resilience.parse_fault_inject(coerced[name])
        if name == "FLAGS_serving_slo" and coerced[name]:
            # same validate-before-apply treatment: a typo'd SLO spec
            # must not be stored to fail later at server construction
            from .serving.slo import parse_slo
            parse_slo(coerced[name])
        if name == "FLAGS_numerics" and \
                coerced[name] not in ("off", "sentinel", "full"):
            raise ValueError(
                "FLAGS_numerics must be 'off', 'sentinel' or 'full', "
                f"got {coerced[name]!r}")
        if name == "FLAGS_watchdog_escalate" and \
                coerced[name] not in ("", "abort"):
            raise ValueError(
                f"FLAGS_watchdog_escalate must be '' or 'abort', got "
                f"{coerced[name]!r}")
        if name == "FLAGS_gspmd_mesh" and coerced[name]:
            # validate the "axis:size,axis:size" grammar here so a typo
            # refuses at set_flags, not inside with_gspmd at compile time
            try:
                parsed = {k: int(v) for k, v in
                          (kv.split(":") for kv in coerced[name].split(","))}
            except Exception:
                raise ValueError(
                    "FLAGS_gspmd_mesh must be 'axis:size[,axis:size...]' "
                    f"e.g. 'dp:2,mp:4', got {coerced[name]!r}")
            if not parsed or any(s <= 0 for s in parsed.values()):
                raise ValueError(
                    f"FLAGS_gspmd_mesh sizes must be positive: "
                    f"{coerced[name]!r}")
        if name == "FLAGS_fleet_route_policy" and \
                coerced[name] not in ("least_loaded", "round_robin"):
            raise ValueError(
                "FLAGS_fleet_route_policy must be 'least_loaded' or "
                f"'round_robin', got {coerced[name]!r}")
        if name == "FLAGS_fleet_digest_ttl_s" and coerced[name] <= 0:
            raise ValueError(
                "FLAGS_fleet_digest_ttl_s must be > 0 (a zero/negative "
                f"TTL would blind placement), got {coerced[name]!r}")
        if name == "FLAGS_fleet_scale_eval_interval_s" and \
                coerced[name] <= 0:
            raise ValueError(
                "FLAGS_fleet_scale_eval_interval_s must be > 0, got "
                f"{coerced[name]!r}")
        if name in ("FLAGS_fleet_scale_cooldown_s",
                    "FLAGS_fleet_spawn_backoff_s",
                    "FLAGS_fleet_queue_high",
                    "FLAGS_fleet_idle_qps") and coerced[name] < 0:
            raise ValueError(f"{name} must be >= 0, got {coerced[name]!r}")
        if name in ("FLAGS_fleet_scale_up_ticks",
                    "FLAGS_fleet_scale_down_ticks",
                    "FLAGS_fleet_shed_after_ticks",
                    "FLAGS_fleet_shrink_grace_ticks") and coerced[name] < 1:
            raise ValueError(f"{name} must be >= 1, got {coerced[name]!r}")
        if name == "FLAGS_fleet_oom_headroom_frac" and \
                not 0 <= coerced[name] < 1:
            raise ValueError(
                "FLAGS_fleet_oom_headroom_frac must be in [0, 1), got "
                f"{coerced[name]!r}")
        if name == "FLAGS_gspmd_rules" and coerced[name] != "auto":
            from .parallel.partitioner import rule_table
            rule_table(coerced[name])   # raises on unknown table name
    slo_numeric = ("FLAGS_serving_slo_fast_window_s",
                   "FLAGS_serving_slo_slow_window_s",
                   "FLAGS_serving_slo_burn_threshold")
    if any(n in coerced for n in slo_numeric):
        # validate the EFFECTIVE window pair/threshold (new values merged
        # over current) so an inconsistent pair is refused here, not at
        # server construction deep inside a deployment's startup
        eff = {n: float(coerced.get(n, _values[n])) for n in slo_numeric}
        fast = eff["FLAGS_serving_slo_fast_window_s"]
        slow = eff["FLAGS_serving_slo_slow_window_s"]
        if not 0 < fast <= slow:
            raise ValueError(
                "SLO windows must satisfy 0 < fast <= slow (got "
                f"fast={fast}, slow={slow})")
        if eff["FLAGS_serving_slo_burn_threshold"] <= 0:
            raise ValueError(
                "FLAGS_serving_slo_burn_threshold must be > 0 (got "
                f"{eff['FLAGS_serving_slo_burn_threshold']})")
    fleet_size = ("FLAGS_fleet_min_replicas", "FLAGS_fleet_max_replicas")
    if any(n in coerced for n in fleet_size):
        # same effective-pair discipline: the bounds the controller will
        # actually run with (new values merged over current) must form a
        # sane interval, refused here rather than at controller start
        eff = {n: int(coerced.get(n, _values[n])) for n in fleet_size}
        lo = eff["FLAGS_fleet_min_replicas"]
        hi = eff["FLAGS_fleet_max_replicas"]
        if not 1 <= lo <= hi:
            raise ValueError(
                "fleet size bounds must satisfy 1 <= min <= max (got "
                f"min={lo}, max={hi})")
    for name, value in coerced.items():
        _values[name] = value
        _apply_side_effects(name, value)


def get_flags(flags):
    """ref paddle.get_flags: name or list of names → dict."""
    names = [flags] if isinstance(flags, str) else list(flags)
    out = {}
    for name in names:
        if name not in _values:
            raise ValueError(f"unknown flag {name!r}")
        out[name] = _values[name]
    return out


class _Globals:
    """Mapping facade (ref pybind ``core.globals()``)."""

    def __getitem__(self, name):
        return get_flags(name)[name]

    def __setitem__(self, name, value):
        set_flags({name: value})

    def __contains__(self, name):
        return name in _DEFAULTS

    def keys(self):
        return _DEFAULTS.keys()


def globals():  # noqa: A001  (parity with core.globals())
    return _Globals()


def _bootstrap_from_env():
    """ref __init__.py __bootstrap__: FLAGS_* env vars seed the registry.
    Malformed values warn and are ignored (gflags behavior) — a typo'd env
    var must not brick ``import paddle_tpu``."""
    import warnings
    for name in _DEFAULTS:
        raw = os.environ.get(name)
        if raw is None:
            continue
        try:
            set_flags({name: raw})
        except (ValueError, TypeError) as e:
            warnings.warn(f"ignoring malformed env var {name}={raw!r}: {e}")


_bootstrap_from_env()


# ---------------------------------------------------------------------------
# one-time parity-knob warnings: several reference API switches are no-ops
# under XLA (fusion/memory-opt are the compiler's job, there is no GPU) —
# accepting them silently would hide that from users porting configs
# (VERDICT r1 weak #7), so each ignored knob logs once per process.
# ---------------------------------------------------------------------------

_warned_noop_knobs = set()


def warn_noop(knob: str, why: str = "") -> None:
    """Log once that a reference-parity knob has no effect on TPU."""
    if knob in _warned_noop_knobs:
        return
    _warned_noop_knobs.add(knob)
    import logging
    logging.getLogger("paddle_tpu").warning(
        "%s is accepted for API parity but has no effect on TPU%s",
        knob, f" ({why})" if why else "")
