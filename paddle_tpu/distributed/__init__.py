"""paddle_tpu.distributed — multi-process/multi-host training surface.

Reference layers replaced here (SURVEY §2.5, §3.3):

- ``operators/collective/c_*`` NCCL-ring ops            → collective_ops
  (XLA collectives on the mesh's ``dp`` axis under the executor's
  collective shard_map mode)
- ``transpiler/collective.py`` GradAllReduce/LocalSGD   → transpiler
- ``incubate/fleet``  fleet.init/distributed_optimizer  → fleet
- ``python/paddle/distributed/launch.py`` process spawn → launch
- ``c_gen_nccl_id`` RPC bootstrap                       → init_parallel_env
  (jax.distributed coordination service)
"""

from . import collective_ops  # noqa  (registers c_* lowerings)
from . import ps  # noqa  (registers send/recv/listen_and_serv lowerings)
from .ps import (Communicator, DistributeTranspiler,  # noqa
                 DistributeTranspilerConfig, GeoCommunicator)
from .coordinator import (GangClient, GangCoordinator,  # noqa
                          GangDegradedError, GangFingerprintError)
from .env import (Env, GangRendezvous, get_rank,  # noqa
                  get_world_size, init_parallel_env)
from .fleet import (CollectiveOptimizer, DistributedStrategy,  # noqa
                    PaddleCloudRoleMaker, PSFleet, TranspilerOptimizer,
                    UserDefinedRoleMaker, fleet, ps_fleet)
from .transpiler import GradAllReduce, LocalSGD  # noqa
from . import downpour  # noqa  (legacy Downpour PS python API)
