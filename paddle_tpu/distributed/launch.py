"""Process launcher: ``python -m paddle_tpu.distributed.launch train.py``.

Reference: ``python/paddle/distributed/launch.py:147-281`` — parses the
cluster env (node ips, per-node device count), spawns one trainer process
per device with the PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS contract, streams logs,
and tears the job down if any rank dies.

TPU note: on TPU pods the natural unit is one process per *host* (each
process owns all local chips; jax.distributed federates hosts), so
``--nproc_per_node`` defaults to 1.  The rank-0 endpoint doubles as the
jax.distributed coordinator address.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="paddle_tpu distributed launcher "
                    "(ref python/paddle/distributed/launch.py)")
    p.add_argument("--cluster_node_ips", default="127.0.0.1",
                   help="comma-separated node ips")
    p.add_argument("--node_ip", default="127.0.0.1",
                   help="this node's ip")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 per TPU host)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_env(args):
    """Build the per-rank env dicts (ref launch.py start_procs :147)."""
    node_ips = args.cluster_node_ips.split(",")
    nnodes = len(node_ips)
    nproc = args.nproc_per_node
    world = nnodes * nproc
    endpoints = [f"{ip}:{args.started_port + i}"
                 for ip in node_ips for i in range(nproc)]
    node_idx = node_ips.index(args.node_ip)
    envs = []
    for local in range(nproc):
        rank = node_idx * nproc + local
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "FLAGS_selected_tpus": str(local),
            "TRAINING_ROLE": "TRAINER",
        }
        envs.append(env)
    return envs


def start_procs(args, envs):
    """Spawn one training process per local rank (ref launch.py:147)."""
    procs, logs = [], []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for local, env in enumerate(envs):
        cmd = [sys.executable, "-u", args.training_script] + \
            args.training_script_args
        full_env = dict(os.environ, **env)
        out = None
        if args.log_dir:
            log_name = env.get("PADDLE_LOG_NAME",
                               f"worker.{env['PADDLE_TRAINER_ID']}")
            out = open(os.path.join(args.log_dir, f"{log_name}.log"), "w")
            logs.append(out)
        procs.append(subprocess.Popen(cmd, env=full_env, stdout=out,
                                      stderr=subprocess.STDOUT if out
                                      else None))
    return procs, logs


def wait_procs(procs):
    """Wait for all ranks; kill the gang if any rank fails (ref :256)."""
    try:
        while True:
            alive = False
            for p in procs:
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    for q in procs:
                        if q.poll() is None:
                            q.send_signal(signal.SIGTERM)
                    raise SystemExit(
                        f"rank process {p.pid} exited with {ret}")
            if not alive:
                return
            time.sleep(0.5)
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        raise


def launch(argv=None):
    args = _parse_args(argv)
    envs = get_cluster_env(args)
    procs, logs = start_procs(args, envs)
    try:
        wait_procs(procs)
    finally:
        for f in logs:
            f.close()


if __name__ == "__main__":
    launch()
