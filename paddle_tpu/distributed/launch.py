"""Process launcher: ``python -m paddle_tpu.distributed.launch train.py``.

Reference: ``python/paddle/distributed/launch.py:147-281`` — parses the
cluster env (node ips, per-node device count), spawns one trainer process
per device with the PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS contract, streams logs,
and tears the job down if any rank dies.

TPU note: on TPU pods the natural unit is one process per *host* (each
process owns all local chips; jax.distributed federates hosts), so
``--nproc_per_node`` defaults to 1.  The rank-0 endpoint doubles as the
jax.distributed coordinator address.

Gang coordination: by default (``--gang_backend socket``) the node-0
launcher hosts a :class:`~paddle_tpu.distributed.coordinator.
GangCoordinator` on ``started_port + world_size`` and exports
``PADDLE_GANG_COORD`` so every rank's heartbeats, checkpoint commits,
and barriers ride sockets — no shared filesystem needed (the manifest is
still mirrored into ``PADDLE_GANG_DIR`` so a full job restart refuses
torn saves).  ``--gang_backend file`` keeps the PR-4 shared-directory
rendezvous.

Elastic recovery: ``--max_restarts N`` lets the launcher respawn a rank
that died abnormally (SIGKILL, OOM, crash) instead of tearing the job
down.  The coordinator has already declared the rank dead (survivors
drained and parked at the rejoin barrier); the respawned process resumes
from the gang manifest step via ``resume_or_init``, re-admits itself
with its ``hello``, and training continues — the gang never committed a
step past the last all-rank-durable one, so the combined loss trajectory
is exactly the uninterrupted one.

Gang preemption (PR 4, unchanged): a SIGTERM/SIGINT to the launcher
forwards SIGTERM to every rank, then WAITS up to ``--grace_secs`` for
the gang to drain: each rank's ``PreemptionGuard`` finishes its
emergency checkpoint, announces it, and the rank-0 leader publishes the
``COMMITTED`` manifest only when all ranks saved the same step.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="paddle_tpu distributed launcher "
                    "(ref python/paddle/distributed/launch.py)")
    p.add_argument("--cluster_node_ips", default="127.0.0.1",
                   help="comma-separated node ips")
    p.add_argument("--node_ip", default="127.0.0.1",
                   help="this node's ip")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 per TPU host)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--gang_dir", default=None,
                   help="shared rendezvous dir for gang checkpoint "
                        "commits (exported as PADDLE_GANG_DIR; default: "
                        "<log_dir>/gang, or a fresh temp dir)")
    p.add_argument("--gang_backend", choices=("socket", "file"),
                   default="socket",
                   help="gang coordination transport: 'socket' (default) "
                        "hosts a rank-0 TCP coordinator on the node-0 "
                        "launcher at started_port + world_size and "
                        "exports PADDLE_GANG_COORD (liveness plane + "
                        "elastic recovery, no shared FS needed); 'file' "
                        "keeps the shared-directory rendezvous")
    p.add_argument("--coordinator_standby", action="store_true",
                   default=None,
                   help="also host a warm-standby gang coordinator at "
                        "started_port + world_size + 1 that mirrors the "
                        "primary's manifest + announcements over a "
                        "replicated log and promotes itself (epoch-"
                        "fenced) on primary heartbeat loss; ranks get "
                        "both addresses via PADDLE_GANG_COORD and fail "
                        "over automatically (default: "
                        "FLAGS_coordinator_standby)")
    p.add_argument("--max_restarts", type=int, default=0,
                   help="how many abnormal rank exits the launcher may "
                        "absorb by respawning the rank (elastic "
                        "recovery; the respawned rank resumes from the "
                        "gang manifest step).  0 = any abnormal exit "
                        "tears the job down (the old behavior)")
    p.add_argument("--grace_secs", type=float, default=60.0,
                   help="how long a SIGTERM'd launcher waits for ranks "
                        "to finish their gang-coordinated emergency "
                        "checkpoint before SIGKILLing stragglers")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _cluster_shape(args):
    """(node_ips, world_size) — the one derivation every launch helper
    shares, so the coordinator port, the rank envs, and the hosting
    gate can never disagree."""
    node_ips = args.cluster_node_ips.split(",")
    return node_ips, len(node_ips) * args.nproc_per_node


def gang_coord_address(args) -> str:
    """The (derivable, launcher-independent) coordinator endpoint: node-0
    at ``started_port + world_size`` — every node's launcher computes the
    same address without any cross-node exchange."""
    node_ips, world = _cluster_shape(args)
    return f"{node_ips[0]}:{args.started_port + world}"


def _standby_enabled(args) -> bool:
    """--coordinator_standby, defaulting to FLAGS_coordinator_standby
    when the CLI flag was not given (None)."""
    if args.coordinator_standby is not None:
        return bool(args.coordinator_standby)
    try:
        from ..flags import get_flags
        return bool(get_flags("FLAGS_coordinator_standby")
                    ["FLAGS_coordinator_standby"])
    except Exception:
        return False


def standby_node(node_ips) -> str:
    """Cross-node standby placement (pure — the unit-tested decision):
    the warm standby must not share the primary's failure domain, so it
    lands on node 1 whenever the cluster HAS a second node; a
    single-node cluster keeps it next to the primary (the pre-cross-node
    behavior, still useful against process death)."""
    node_ips = list(node_ips)
    return node_ips[1] if len(node_ips) > 1 else node_ips[0]


def gang_standby_address(args) -> str:
    """The warm standby's endpoint: one port above the primary, hosted
    on ``standby_node`` (same derivable-everywhere property — every
    launcher computes the same address with no cross-node exchange)."""
    node_ips, world = _cluster_shape(args)
    return f"{standby_node(node_ips)}:{args.started_port + world + 1}"


def _resolve_gang_dir(args) -> str:
    """One gang dir per launcher invocation — memoized on the args
    namespace so the ranks' PADDLE_GANG_DIR and the coordinator's
    manifest mirror are the SAME directory (a mkdtemp fallback resolved
    twice would give the coordinator a manifest path no rank reads)."""
    cached = getattr(args, "_resolved_gang_dir", None)
    if cached is None:
        cached = args.gang_dir or (
            os.path.join(args.log_dir, "gang") if args.log_dir
            else tempfile.mkdtemp(prefix="pt_gang_"))
        args._resolved_gang_dir = cached
    return cached


def get_cluster_env(args):
    """Build the per-rank env dicts (ref launch.py start_procs :147)."""
    node_ips, world = _cluster_shape(args)
    nnodes = len(node_ips)
    nproc = args.nproc_per_node
    endpoints = [f"{ip}:{args.started_port + i}"
                 for ip in node_ips for i in range(nproc)]
    node_idx = node_ips.index(args.node_ip)
    gang_dir = _resolve_gang_dir(args)
    if nnodes > 1 and not args.gang_dir and args.gang_backend == "file":
        # every launcher invents its own default dir, so on a multi-NODE
        # job the ranks would rendezvous in per-node directories the
        # leader never reads — the gang could then never commit, and
        # every resume would cold-start.  (The socket backend has no
        # shared-FS requirement: ranks talk to the node-0 coordinator.)
        import warnings
        warnings.warn(
            "multi-node launch without --gang_dir: gang checkpoint "
            f"commits need ONE directory visible to every node, but "
            f"{gang_dir!r} is node-local; pass --gang_dir on shared "
            "storage or gang commits will never publish")
    envs = []
    for local in range(nproc):
        rank = node_idx * nproc + local
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_GANG_DIR": gang_dir,
            "FLAGS_selected_tpus": str(local),
            "TRAINING_ROLE": "TRAINER",
        }
        if args.gang_backend == "socket" and world > 1:
            addr = gang_coord_address(args)
            if _standby_enabled(args):
                # both addresses, primary first: GangClient rotates to
                # the standby on primary loss (epoch-fenced failover)
                addr = f"{addr},{gang_standby_address(args)}"
            env["PADDLE_GANG_COORD"] = addr
        envs.append(env)
    return envs


def start_coordinator(args):
    """Host this node's share of the gang coordination plane (socket
    backend, multi-rank jobs only).  The node-0 launcher hosts the
    primary; the ``standby_node`` launcher (node 1 on multi-node
    clusters — cross-node placement, so the standby survives the
    primary's whole node dying; node 0 itself when single-node) hosts
    the warm standby.  Returns the list of coordinators THIS launcher
    started — possibly empty.  The launcher is the natural host: it
    outlives every rank, so rank death, respawn, and the rejoin barrier
    all survive any trainer process dying."""
    node_ips, world = _cluster_shape(args)
    if args.gang_backend != "socket" or world <= 1:
        return []
    from .coordinator import GangCoordinator
    coords = []
    if node_ips.index(args.node_ip) == 0:
        host, _, port = gang_coord_address(args).rpartition(":")
        coords.append(GangCoordinator(
            world, host=host, port=int(port),
            manifest_dir=_resolve_gang_dir(args)).start())
    if _standby_enabled(args) and args.node_ip == standby_node(node_ips):
        sb_host, _, sb_port = gang_standby_address(args).rpartition(":")
        # same manifest_dir: the standby's promotion path re-reads the
        # durable MANIFEST so replication lag can never regress it, and
        # its EPOCH fence token lands where the zombie primary looks.
        # (Multi-node jobs need --gang_dir on shared storage for the
        # mirror to be shared — the same rule the file backend has.)
        # standby_of is the DERIVED primary address: on a multi-node
        # cluster this launcher never constructed the primary object.
        coords.append(GangCoordinator(
            world, host=sb_host, port=int(sb_port),
            manifest_dir=_resolve_gang_dir(args),
            standby_of=gang_coord_address(args)).start())
    if not coords:
        return []
    # FLAGS_coordinator_metrics_port: the launcher's process registry
    # holds the whole gang's per-rank digest gauges (the coordinator
    # folds every heartbeat into it), so serving /metrics + /statusz
    # HERE makes the gang scrapeable with no serving stack — reusing
    # the serving plane's MetricsHTTPServer.  /statusz carries the same
    # rank table gangtop renders; /healthz answers 503 while degraded.
    try:
        from ..flags import get_flags
        fl = get_flags(["FLAGS_coordinator_metrics_port",
                        "FLAGS_metrics_host"])
        mport = int(fl["FLAGS_coordinator_metrics_port"])
        if mport:
            srv = coords[0].start_metrics_http(
                mport, host=str(fl["FLAGS_metrics_host"]))
            sys.stderr.write(
                f"paddle_tpu launch: coordinator metrics at "
                f"{srv.url}/metrics\n")
    except Exception as e:       # scrape surface must never kill launch
        sys.stderr.write(
            f"paddle_tpu launch: coordinator metrics server failed: "
            f"{e!r}\n")
    return coords


def _spawn(args, env, log_mode="w"):
    """Start one rank process (``log_mode='a'`` on a respawn, so the
    restarted rank's output lands after its first life's)."""
    cmd = [sys.executable, "-u", args.training_script] + \
        args.training_script_args
    full_env = dict(os.environ, **env)
    out = None
    if args.log_dir:
        log_name = env.get("PADDLE_LOG_NAME",
                           f"worker.{env['PADDLE_TRAINER_ID']}")
        out = open(os.path.join(args.log_dir, f"{log_name}.log"),
                   log_mode)
    proc = subprocess.Popen(cmd, env=full_env, stdout=out,
                            stderr=subprocess.STDOUT if out else None)
    return proc, out


def start_procs(args, envs):
    """Spawn one training process per local rank (ref launch.py:147)."""
    procs, logs = [], []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for env in envs:
        proc, out = _spawn(args, env)
        procs.append(proc)
        if out is not None:
            logs.append(out)
    return procs, logs


def drain_gang(procs, grace_secs: float = 60.0):
    """Forward SIGTERM to every live rank, then WAIT for the gang to
    drain: ranks run their PreemptionGuard emergency save + gang
    announce, the leader publishes the COMMITTED manifest, and only
    stragglers still alive after ``grace_secs`` are SIGKILLed.  Returns
    True iff every rank exited cleanly (exit 0) within the grace window —
    i.e. the gang checkpoint is trustworthy."""
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + float(grace_secs)
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs):
            break
        time.sleep(0.2)
    clean = True
    for p in procs:
        if p.poll() is None:
            p.kill()
            clean = False
    for p in procs:
        p.wait()
        clean = clean and p.returncode == 0
    return clean


def wait_procs(procs, grace_secs: float = 60.0, stop=None, args=None,
               envs=None, max_restarts: int = 0, logs=None):
    """Wait for all ranks; on an abnormal rank exit, either respawn it
    (elastic: ``max_restarts`` budget left and ``args``/``envs`` given —
    the rank resumes from the gang manifest and the coordinator re-admits
    it at the rejoin barrier) or kill the gang (ref :256).

    A SIGTERM to the launcher (``stop`` flag set by the signal handler)
    or a Ctrl-C drains the gang gracefully — every rank gets SIGTERM and
    ``grace_secs`` to finish its coordinated emergency checkpoint —
    instead of orphaning ranks mid-save."""
    restarts_left = int(max_restarts)
    try:
        while True:
            if stop is not None and stop.get("signum") is not None:
                ok = drain_gang(procs, grace_secs)
                raise SystemExit(0 if ok else 1)
            alive = False
            for i, p in enumerate(procs):
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    if restarts_left > 0 and args is not None \
                            and envs is not None:
                        restarts_left -= 1
                        sys.stderr.write(
                            f"paddle_tpu launch: rank "
                            f"{envs[i]['PADDLE_TRAINER_ID']} (pid "
                            f"{p.pid}) exited {ret}; respawning "
                            f"({restarts_left} restart(s) left) — it "
                            "will resume from the gang manifest step\n")
                        sys.stderr.flush()
                        newp, out = _spawn(args, envs[i], log_mode="a")
                        procs[i] = newp
                        if out is not None and logs is not None:
                            logs.append(out)
                        alive = True
                    else:
                        drain_gang(procs, grace_secs)
                        raise SystemExit(
                            f"rank process {p.pid} exited with {ret}")
            if not alive:
                return
            time.sleep(0.5)
    except KeyboardInterrupt:
        ok = drain_gang(procs, grace_secs)
        raise SystemExit(0 if ok else 1) from None


class ReplicaLauncher:
    """The ``--max_restarts`` respawn machinery generalized into a
    target-size actuator for the fleet autoscaler: ``spawn()`` starts
    one serving-replica process and blocks until it prints its
    ``READY <host:port>`` line; ``retire(addr)`` SIGTERMs it — the
    replica's guard path drains its in-flight work (the PR-18 drain
    contract, never a kill) — and SIGKILLs only a straggler still alive
    past ``grace_secs``.

    The command is re-invoked verbatim per spawn; each child inherits
    ``env`` over the parent's.  The READY protocol is the same one
    ``tools/fleet_smoke.py`` children speak, so the autoscaler drill
    exercises this exact path.
    """

    def __init__(self, cmd, env=None, grace_secs: float = 30.0,
                 ready_timeout_s: float = 120.0):
        self.cmd = list(cmd)
        self.env = dict(env or {})
        self.grace_secs = float(grace_secs)
        self.ready_timeout_s = float(ready_timeout_s)
        self._procs = {}    # addr -> subprocess.Popen

    def spawn(self) -> str:
        """Start one replica; returns its address.  Raises
        ``RuntimeError`` when the child dies or stays silent past
        ``ready_timeout_s`` (the autoscaler turns that into backoff +
        re-shed, never a crash)."""
        proc = subprocess.Popen(
            self.cmd, env=dict(os.environ, **self.env),
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True)
        deadline = time.monotonic() + self.ready_timeout_s
        addr = None
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break                      # child closed stdout / died
            line = line.strip()
            if line.startswith("READY "):
                addr = line.split(None, 1)[1]
                break
        if addr is None:
            if proc.poll() is None:
                proc.kill()
            proc.wait()
            raise RuntimeError(
                f"replica spawn failed: no READY line (exit "
                f"{proc.returncode})")
        self._procs[addr] = proc
        return addr

    def retire(self, addr: str) -> int:
        """Drain-then-stop the replica at ``addr``; returns its exit
        code (0 = the drain finished every in-flight request)."""
        proc = self._procs.pop(str(addr), None)
        if proc is None:
            raise KeyError(f"no spawned replica at {addr!r}")
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            deadline = time.monotonic() + self.grace_secs
            while time.monotonic() < deadline and proc.poll() is None:
                time.sleep(0.05)
            if proc.poll() is None:
                proc.kill()
        return proc.wait()

    def alive(self):
        """Addresses of spawned replicas whose process is still up."""
        return [a for a, p in self._procs.items() if p.poll() is None]

    def stop_all(self, grace_secs=None) -> None:
        """Teardown: retire every spawned replica (best effort)."""
        if grace_secs is not None:
            self.grace_secs = float(grace_secs)
        for addr in list(self._procs):
            try:
                self.retire(addr)
            except Exception:
                pass


def launch(argv=None):
    args = _parse_args(argv)
    envs = get_cluster_env(args)
    coords = start_coordinator(args)
    procs, logs = start_procs(args, envs)
    # a scheduler preempts the LAUNCHER: forward + drain, don't die and
    # leave ranks checkpointing into a gang that can never commit
    stop = {"signum": None}
    old = None
    try:
        old = signal.signal(signal.SIGTERM,
                            lambda s, f: stop.__setitem__("signum", s))
    except ValueError:          # not the main thread (embedded use)
        pass
    try:
        wait_procs(procs, grace_secs=args.grace_secs, stop=stop,
                   args=args, envs=envs,
                   max_restarts=args.max_restarts, logs=logs)
    finally:
        if old is not None:
            signal.signal(signal.SIGTERM, old)
        for c in coords:
            c.stop()
        for f in logs:
            f.close()


if __name__ == "__main__":
    launch()
