"""Process launcher: ``python -m paddle_tpu.distributed.launch train.py``.

Reference: ``python/paddle/distributed/launch.py:147-281`` — parses the
cluster env (node ips, per-node device count), spawns one trainer process
per device with the PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT /
PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS contract, streams logs,
and tears the job down if any rank dies.

TPU note: on TPU pods the natural unit is one process per *host* (each
process owns all local chips; jax.distributed federates hosts), so
``--nproc_per_node`` defaults to 1.  The rank-0 endpoint doubles as the
jax.distributed coordinator address.

Gang preemption: the launcher exports ``PADDLE_GANG_DIR`` (one shared
rendezvous directory per job — see ``env.GangRendezvous``), and a
SIGTERM/SIGINT to the launcher forwards SIGTERM to every rank, then
WAITS up to ``--grace_secs`` for the gang to drain: each rank's
``PreemptionGuard`` finishes its emergency checkpoint, announces it,
and the rank-0 leader publishes the ``COMMITTED`` manifest only when
all ranks saved the same step.  Killing the ranks immediately (the old
behavior) is exactly how multi-host emergency saves tear.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="paddle_tpu distributed launcher "
                    "(ref python/paddle/distributed/launch.py)")
    p.add_argument("--cluster_node_ips", default="127.0.0.1",
                   help="comma-separated node ips")
    p.add_argument("--node_ip", default="127.0.0.1",
                   help="this node's ip")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="processes per node (1 per TPU host)")
    p.add_argument("--log_dir", default=None)
    p.add_argument("--gang_dir", default=None,
                   help="shared rendezvous dir for gang checkpoint "
                        "commits (exported as PADDLE_GANG_DIR; default: "
                        "<log_dir>/gang, or a fresh temp dir)")
    p.add_argument("--grace_secs", type=float, default=60.0,
                   help="how long a SIGTERM'd launcher waits for ranks "
                        "to finish their gang-coordinated emergency "
                        "checkpoint before SIGKILLing stragglers")
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_cluster_env(args):
    """Build the per-rank env dicts (ref launch.py start_procs :147)."""
    node_ips = args.cluster_node_ips.split(",")
    nnodes = len(node_ips)
    nproc = args.nproc_per_node
    world = nnodes * nproc
    endpoints = [f"{ip}:{args.started_port + i}"
                 for ip in node_ips for i in range(nproc)]
    node_idx = node_ips.index(args.node_ip)
    gang_dir = args.gang_dir or (
        os.path.join(args.log_dir, "gang") if args.log_dir
        else tempfile.mkdtemp(prefix="pt_gang_"))
    if nnodes > 1 and not args.gang_dir:
        # every launcher invents its own default dir, so on a multi-NODE
        # job the ranks would rendezvous in per-node directories the
        # leader never reads — the gang could then never commit, and
        # every resume would cold-start
        import warnings
        warnings.warn(
            "multi-node launch without --gang_dir: gang checkpoint "
            f"commits need ONE directory visible to every node, but "
            f"{gang_dir!r} is node-local; pass --gang_dir on shared "
            "storage or gang commits will never publish")
    envs = []
    for local in range(nproc):
        rank = node_idx * nproc + local
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
            "PADDLE_GANG_DIR": gang_dir,
            "FLAGS_selected_tpus": str(local),
            "TRAINING_ROLE": "TRAINER",
        }
        envs.append(env)
    return envs


def start_procs(args, envs):
    """Spawn one training process per local rank (ref launch.py:147)."""
    procs, logs = [], []
    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)
    for local, env in enumerate(envs):
        cmd = [sys.executable, "-u", args.training_script] + \
            args.training_script_args
        full_env = dict(os.environ, **env)
        out = None
        if args.log_dir:
            log_name = env.get("PADDLE_LOG_NAME",
                               f"worker.{env['PADDLE_TRAINER_ID']}")
            out = open(os.path.join(args.log_dir, f"{log_name}.log"), "w")
            logs.append(out)
        procs.append(subprocess.Popen(cmd, env=full_env, stdout=out,
                                      stderr=subprocess.STDOUT if out
                                      else None))
    return procs, logs


def drain_gang(procs, grace_secs: float = 60.0):
    """Forward SIGTERM to every live rank, then WAIT for the gang to
    drain: ranks run their PreemptionGuard emergency save + gang
    announce, the leader publishes the COMMITTED manifest, and only
    stragglers still alive after ``grace_secs`` are SIGKILLed.  Returns
    True iff every rank exited cleanly (exit 0) within the grace window —
    i.e. the gang checkpoint is trustworthy."""
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    deadline = time.monotonic() + float(grace_secs)
    while time.monotonic() < deadline:
        if all(p.poll() is not None for p in procs):
            break
        time.sleep(0.2)
    clean = True
    for p in procs:
        if p.poll() is None:
            p.kill()
            clean = False
    for p in procs:
        p.wait()
        clean = clean and p.returncode == 0
    return clean


def wait_procs(procs, grace_secs: float = 60.0, stop=None):
    """Wait for all ranks; kill the gang if any rank fails (ref :256).

    A SIGTERM to the launcher (``stop`` flag set by the signal handler)
    or a Ctrl-C drains the gang gracefully — every rank gets SIGTERM and
    ``grace_secs`` to finish its coordinated emergency checkpoint —
    instead of orphaning ranks mid-save."""
    try:
        while True:
            if stop is not None and stop.get("signum") is not None:
                ok = drain_gang(procs, grace_secs)
                raise SystemExit(0 if ok else 1)
            alive = False
            for p in procs:
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    drain_gang(procs, grace_secs)
                    raise SystemExit(
                        f"rank process {p.pid} exited with {ret}")
            if not alive:
                return
            time.sleep(0.5)
    except KeyboardInterrupt:
        ok = drain_gang(procs, grace_secs)
        raise SystemExit(0 if ok else 1) from None


def launch(argv=None):
    args = _parse_args(argv)
    envs = get_cluster_env(args)
    procs, logs = start_procs(args, envs)
    # a scheduler preempts the LAUNCHER: forward + drain, don't die and
    # leave ranks checkpointing into a gang that can never commit
    stop = {"signum": None}
    old = None
    try:
        old = signal.signal(signal.SIGTERM,
                            lambda s, f: stop.__setitem__("signum", s))
    except ValueError:          # not the main thread (embedded use)
        pass
    try:
        wait_procs(procs, grace_secs=args.grace_secs, stop=stop)
    finally:
        if old is not None:
            signal.signal(signal.SIGTERM, old)
        for f in logs:
            f.close()


if __name__ == "__main__":
    launch()
