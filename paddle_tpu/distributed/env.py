"""Cluster environment + coordination bootstrap.

Reference: trainer env vars set by ``paddle.distributed.launch``
(``python/paddle/distributed/launch.py:147-281``:
PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS) and the NCCL-id RPC exchange
(``operators/collective/c_gen_nccl_id_op.cc``).

TPU mapping: the same env contract, with the ncclUniqueId exchange
replaced by ``jax.distributed.initialize`` — the coordination service at
the rank-0 endpoint hands every process the global device topology.
"""

from __future__ import annotations

import os
from typing import List, Optional


class Env:
    """Parsed trainer environment (≈ dygraph/parallel.py Env)."""

    def __init__(self):
        self.rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self.world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self.current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints: List[str] = eps.split(",") if eps else []

    @property
    def dev_id(self) -> int:
        return int(os.getenv("FLAGS_selected_tpus",
                             os.getenv("FLAGS_selected_gpus", "0")))


def get_rank() -> int:
    return Env().rank


def get_world_size() -> int:
    return Env().world_size


_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None) -> Env:
    """Bring up the multi-process runtime (≈ c_gen_nccl_id + c_comm_init).

    Rank 0's endpoint hosts the coordination service; every process learns
    the global TPU topology from it.  After this, ``jax.devices()`` spans
    all hosts and a Mesh over it scales collectives across DCN.
    No-op in single-process runs.
    """
    global _initialized
    env = Env()
    if _initialized:
        return env
    num_processes = num_processes if num_processes is not None \
        else env.world_size
    if num_processes <= 1:
        _initialized = True
        return env
    import jax
    coordinator_address = coordinator_address or (
        env.trainer_endpoints[0] if env.trainer_endpoints else None)
    process_id = process_id if process_id is not None else env.rank
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return env
