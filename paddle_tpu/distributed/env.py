"""Cluster environment + coordination bootstrap.

Reference: trainer env vars set by ``paddle.distributed.launch``
(``python/paddle/distributed/launch.py:147-281``:
PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT / PADDLE_TRAINERS_NUM /
PADDLE_TRAINER_ENDPOINTS) and the NCCL-id RPC exchange
(``operators/collective/c_gen_nccl_id_op.cc``).

TPU mapping: the same env contract, with the ncclUniqueId exchange
replaced by ``jax.distributed.initialize`` — the coordination service at
the rank-0 endpoint hands every process the global device topology.

This module also hosts :class:`GangRendezvous`, the file-based rank
rendezvous behind gang-level checkpoint commits: every rank announces
the steps it has durably checkpointed, and the rank-0 leader publishes a
``COMMITTED <step>`` manifest only when the whole gang agrees — the unit
of recovery is the gang, never a single rank (a torn multi-host save is
refused at resume).  The launcher exports ``PADDLE_GANG_DIR`` so all
ranks of one job rendezvous in the same directory (it must be on a
filesystem every rank can reach — shared FS on multi-host pods).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional


class Env:
    """Parsed trainer environment (≈ dygraph/parallel.py Env)."""

    def __init__(self):
        self.rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self.world_size = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self.current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self.trainer_endpoints: List[str] = eps.split(",") if eps else []

    @property
    def dev_id(self) -> int:
        return int(os.getenv("FLAGS_selected_tpus",
                             os.getenv("FLAGS_selected_gpus", "0")))


def get_rank() -> int:
    return Env().rank


def get_world_size() -> int:
    return Env().world_size


_initialized = False


def init_parallel_env(coordinator_address: Optional[str] = None,
                      num_processes: Optional[int] = None,
                      process_id: Optional[int] = None) -> Env:
    """Bring up the multi-process runtime (≈ c_gen_nccl_id + c_comm_init).

    Rank 0's endpoint hosts the coordination service; every process learns
    the global TPU topology from it.  After this, ``jax.devices()`` spans
    all hosts and a Mesh over it scales collectives across DCN.
    No-op in single-process runs.
    """
    global _initialized
    env = Env()
    if _initialized:
        return env
    num_processes = num_processes if num_processes is not None \
        else env.world_size
    if num_processes <= 1:
        _initialized = True
        return env
    import jax
    coordinator_address = coordinator_address or (
        env.trainer_endpoints[0] if env.trainer_endpoints else None)
    process_id = process_id if process_id is not None else env.rank
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True
    return env


# ---------------------------------------------------------------------------
# gang-commit rendezvous (file-based; see module docstring)
# ---------------------------------------------------------------------------

#: from_env's socket-backend client cache: {(address, rank): GangClient}
_SOCKET_CLIENTS: Dict[tuple, object] = {}

def format_manifest(step: int, world_size: int) -> str:
    """The ``COMMITTED <step>`` manifest body: a strict first line the
    parser keys on, plus a JSON metadata line for humans and tooling."""
    meta = {"world_size": int(world_size),
            "time": time.strftime("%Y-%m-%dT%H:%M:%S")}
    return f"COMMITTED {int(step)}\n{json.dumps(meta, sort_keys=True)}\n"


def parse_manifest(text: str) -> int:
    """Parse a manifest body back to its committed step.  Strict: anything
    that is not a well-formed ``COMMITTED <step>`` first line raises
    ``ValueError`` — a truncated or corrupted manifest must read as "no
    commit", never as a guessed step."""
    lines = (text or "").splitlines()
    if not lines:
        raise ValueError("empty gang manifest")
    parts = lines[0].split()
    if len(parts) != 2 or parts[0] != "COMMITTED":
        raise ValueError(
            f"malformed gang manifest first line: {lines[0]!r} "
            "(expected 'COMMITTED <step>')")
    try:
        step = int(parts[1])
    except ValueError:
        raise ValueError(
            f"malformed gang manifest step: {parts[1]!r}") from None
    if step < 0:
        raise ValueError(f"gang manifest step {step} is negative")
    return step


def _atomic_write(path: str, body: str) -> None:
    """fsync'd atomic publish: stage to a temp sibling, fsync the file,
    rename over the target, fsync the directory — a reader never sees a
    half-written file and the rename survives a crash."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        f.write(body)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    from ..io import _fsync_dir
    _fsync_dir(os.path.dirname(path) or ".")


class GangRendezvous:
    """File-based gang checkpoint-commit barrier.

    Layout under ``base_dir``::

        rank_0, rank_1, ...   per-rank announcements (JSON: the rank's
                              latest durably-committed step + the full
                              list of steps it still holds)
        MANIFEST              'COMMITTED <step>' — published by rank 0
                              only when every rank holds that step

    All writes are fsync'd atomic renames, so a reader (the resume path,
    the leader's poll) observes either the previous or the new content,
    never a torn file.  The protocol is crash-safe by construction: a
    rank dying mid-save simply never announces, and the manifest stays at
    the last step the whole gang agreed on.
    """

    MANIFEST_NAME = "MANIFEST"
    backend = "file"

    def __init__(self, base_dir: str, rank: Optional[int] = None,
                 world_size: Optional[int] = None):
        env = Env()
        self.base_dir = os.path.abspath(base_dir)
        self.rank = env.rank if rank is None else int(rank)
        self.world_size = env.world_size if world_size is None \
            else int(world_size)
        os.makedirs(self.base_dir, exist_ok=True)

    @classmethod
    def from_env(cls) -> Optional["GangRendezvous"]:
        """The launcher's contract, now a backend factory: with
        ``PADDLE_GANG_COORD`` (host:port) set, rendezvous goes through
        the socket coordinator (``coordinator.GangClient`` — same API,
        no shared-FS requirement, plus the liveness plane); otherwise
        ``PADDLE_GANG_DIR`` selects the file backend.  Single-rank runs
        get ``None`` (no gang — per-rank checkpoint semantics are
        already safe).

        An unreachable coordinator is an ERROR (after a short connect
        retry), not a fallback: PADDLE_GANG_COORD is exported by a
        launcher for the WHOLE gang, and one rank quietly switching to
        the file backend (or no gang) while its peers heartbeat splits
        the coordination plane — the silent rank reads as dead, every
        survivor parks for a respawn that never comes, and two writers
        race the manifest file.  A rank that dies loudly instead is
        respawned by ``--max_restarts`` and connects on its next try."""
        if Env().world_size <= 1:
            return None
        coord = os.getenv("PADDLE_GANG_COORD", "")
        base = os.getenv("PADDLE_GANG_DIR", "")
        if coord:
            from .coordinator import GangClient
            # ONE client (= one heartbeat plane) per coordinator+rank in
            # this process: the daemon, the guard, and resume_or_init
            # all default to from_env(), and a second progress-less
            # client's beats would interleave with (and overwrite) the
            # first one's fingerprint/progress at the coordinator
            key = (coord, Env().rank)
            cached = _SOCKET_CLIENTS.get(key)
            if cached is not None and not cached._hb_stop.is_set():
                return cached
            last: Optional[BaseException] = None
            for delay in (0.0, 0.5, 1.5):    # brief connect retry
                if delay:
                    time.sleep(delay)
                try:
                    client = GangClient(coord).connect().start_heartbeat()
                    _SOCKET_CLIENTS[key] = client
                    return client
                except (OSError, ConnectionError) as e:
                    last = e
            raise ConnectionError(
                f"gang coordinator at {coord} unreachable after "
                f"retries: {last} (PADDLE_GANG_COORD was exported for "
                "the whole gang — refusing to silently split the "
                "coordination plane; unset it to use the file "
                "rendezvous)") from last
        if not base:
            return None
        return cls(base)

    @property
    def is_leader(self) -> bool:
        return self.rank == 0

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.base_dir, self.MANIFEST_NAME)

    def _rank_path(self, rank: int) -> str:
        return os.path.join(self.base_dir, f"rank_{int(rank)}")

    # -- announcements -------------------------------------------------------
    def announce(self, step: int, steps=None) -> None:
        """Publish this rank's latest durably-committed checkpoint step
        (and the full set of steps it still holds, so the leader can pick
        a commit point every rank can actually restore)."""
        body = json.dumps({
            "rank": self.rank,
            "step": int(step),
            "steps": sorted(int(s) for s in (steps or [step])),
            "pid": os.getpid(),
        }, sort_keys=True)
        _atomic_write(self._rank_path(self.rank), body + "\n")

    def peer_announcements(self) -> Dict[int, dict]:
        """Parse every rank's announcement; malformed or missing files are
        simply absent (a rank mid-write or dead has not announced)."""
        out: Dict[int, dict] = {}
        for r in range(self.world_size):
            try:
                with open(self._rank_path(r)) as f:
                    d = json.loads(f.read())
                out[int(d["rank"])] = {
                    "step": int(d["step"]),
                    "steps": [int(s) for s in d.get("steps", [d["step"]])],
                }
            except (OSError, ValueError, KeyError, TypeError):
                continue
        return out

    # -- manifest ------------------------------------------------------------
    def committed_step(self) -> Optional[int]:
        """The gang's last committed step, or None when there is no (or a
        corrupt) manifest — corruption must read as 'nothing committed'."""
        try:
            with open(self.manifest_path) as f:
                return parse_manifest(f.read())
        except OSError:
            return None
        except ValueError:
            import warnings
            warnings.warn(
                f"gang manifest {self.manifest_path} is corrupt; treating "
                "as no committed checkpoint")
            return None

    def publish(self, step: int) -> None:
        """Leader-only: atomically publish ``COMMITTED <step>``."""
        if not self.is_leader:
            raise RuntimeError(
                f"rank {self.rank} tried to publish the gang manifest; "
                "only rank 0 commits")
        _atomic_write(self.manifest_path,
                      format_manifest(step, self.world_size))

    # -- commit protocols ----------------------------------------------------
    def commit_latest(self) -> Optional[int]:
        """Leader, non-blocking (steady-state cadence): publish the newest
        step EVERY rank has durably committed and still holds, if it
        advances the manifest.  Returns the published step or None."""
        if not self.is_leader:
            return None
        anns = self.peer_announcements()
        if len(anns) < self.world_size:
            return None
        common = set(anns[0]["steps"]) if 0 in anns else set()
        for d in anns.values():
            common &= set(d["steps"])
        if not common:
            return None
        best = max(common)
        cur = self.committed_step()
        if cur is not None and best <= cur:
            return None
        self.publish(best)
        return best

    def wait_commit(self, step: int, timeout_s: float,
                    poll_s: float = 0.05) -> bool:
        """Leader, blocking (emergency barrier): wait until every rank's
        LATEST announced step equals ``step``, then publish it.  Strict
        equality — ranks disagreeing on the emergency step means the gang
        tore, and the manifest must stay at the previous agreed step."""
        if not self.is_leader:
            raise RuntimeError("wait_commit is leader-only; other ranks "
                               "just announce and exit")
        deadline = time.monotonic() + float(timeout_s)
        while True:
            anns = self.peer_announcements()
            if len(anns) == self.world_size and \
                    all(d["step"] == int(step) for d in anns.values()):
                self.publish(step)
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)

    def wait_manifest(self, step: int, timeout_s: float,
                      poll_s: float = 0.05) -> bool:
        """Any rank: wait until the manifest commits ``step`` (or newer)."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            cur = self.committed_step()
            if cur is not None and cur >= int(step):
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(poll_s)
