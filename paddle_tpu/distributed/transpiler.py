"""Collective transpilers: rewrite a trained program for multi-process DP.

Reference: ``python/paddle/fluid/transpiler/collective.py`` —
``GradAllReduce`` (``:178-268``: scale loss 1/nranks + c_allreduce each
grad + sync streams) and ``LocalSGD`` (``:269``: per-step param averaging
against a snapshot), with comm bootstrap ``_init_communicator`` (``:99``)
inserting ``c_gen_nccl_id``/``c_comm_init`` into the startup program.

The rewritten program executes under the Executor's collective mode: the
whole block runs in one shard_map over the mesh's ``dp`` axis, feeds
sharded on the batch dim, params replicated — per-device compute with
explicit collective ops, exactly the reference's execution model, but the
collectives are XLA's.
"""

from __future__ import annotations

from typing import List, Optional

from ..framework import core
from ..framework.core import Program

# ops that consume a Param/Grad pair (ref collective.py OpRole.Optimize)
OPTIMIZE_OPS = {
    "sgd", "momentum", "lars_momentum", "adam", "adamw", "adamax",
    "adagrad", "decayed_adagrad", "adadelta", "rmsprop", "ftrl", "lamb",
    "dgc_momentum", "proximal_gd", "proximal_adagrad",
}


class Collective:
    """Base transpiler (ref collective.py:36)."""

    def __init__(self, nrings: int = 1):
        self.nrings = nrings
        self.nranks = 1
        self.rank = 0

    def transpile(self, startup_program: Optional[Program] = None,
                  main_program: Optional[Program] = None,
                  rank: int = 0, endpoints: str = "127.0.0.1:6174",
                  current_endpoint: str = "127.0.0.1:6174",
                  wait_port: bool = True):
        if isinstance(endpoints, str):
            endpoints = endpoints.split(",")
        self.rank = rank
        self.nranks = len(endpoints)
        startup = startup_program or core.default_startup_program()
        main = main_program or core.default_main_program()
        self._init_communicator(startup, rank, endpoints, current_endpoint)
        self._transpile_main(main)
        # execution hint: run this block under collective shard_map mode
        main._attrs["collective"] = {"nranks": self.nranks,
                                     "rank": self.rank}
        return main

    def _init_communicator(self, startup, rank, endpoints, current_endpoint):
        """ref collective.py:99 — gen id + comm init per ring."""
        block = startup.global_block()
        for ring_id in range(self.nrings):
            block.append_op("c_gen_nccl_id", attrs={
                "ring_id": ring_id, "rank": rank,
                "endpoint": current_endpoint,
                "other_endpoints": [e for e in endpoints
                                    if e != current_endpoint]})
            block.append_op("c_comm_init", attrs={
                "ring_id": ring_id, "nranks": len(endpoints),
                "rank": rank})

    def _transpile_main(self, main):
        raise NotImplementedError

    def _append_dense_allreduce(self, block, at, grads, compress=None):
        """scale 1/nranks + c_allreduce_sum per grad (ref collective.py
        :189,:208); shared by GradAllReduce and the DGC transpiler's
        non-compressed tail.

        ``compress="bf16"`` casts each gradient to bf16 around the
        allreduce — half the inter-host bytes for ~1e-3-relative noise on
        an already-averaged gradient (the XLA-native take on quantized
        allreduce, EQuARX arXiv:2506.17615; the reference's analog is
        fp16 allreduce in its DGC/LocalSGD family)."""
        ring = 0
        for g in grads:
            block.insert_op(at, "scale",
                            inputs={"X": [g]}, outputs={"Out": [g]},
                            attrs={"scale": 1.0 / self.nranks, "bias": 0.0,
                                   "bias_after_scale": False})
            at += 1
            if compress == "bf16":
                block.insert_op(at, "cast",
                                inputs={"X": [g]}, outputs={"Out": [g]},
                                attrs={"in_dtype": "float32",
                                       "out_dtype": "bfloat16"})
                at += 1
            block.insert_op(at, "c_allreduce_sum",
                            inputs={"X": [g]}, outputs={"Out": [g]},
                            attrs={"ring_id": ring % self.nrings,
                                   "use_calc_stream": True})
            at += 1
            if compress == "bf16":
                block.insert_op(at, "cast",
                                inputs={"X": [g]}, outputs={"Out": [g]},
                                attrs={"in_dtype": "bfloat16",
                                       "out_dtype": "float32"})
                at += 1
            ring += 1
        return at


class GradAllReduce(Collective):
    """Sync multi-process data parallel (ref collective.py:178).

    Scales every param gradient by 1/nranks and all-reduces it before the
    optimizer consumes it; with batch feeds sharded over ranks this makes
    the update the global-batch mean gradient — loss parity with a
    single-process run on the full batch.

    ``compress="bf16"`` halves the allreduce bytes (see
    ``_append_dense_allreduce``)."""

    def __init__(self, nrings: int = 1, compress=None):
        super().__init__(nrings)
        if compress not in (None, "bf16"):
            raise ValueError("compress must be None or 'bf16'")
        self._compress = compress

    def _transpile_main(self, main):
        block = main.global_block()
        grads = []           # (first_optimize_idx, grad_name)
        first_opt = None
        for i, op in enumerate(block.ops):
            if op.type in OPTIMIZE_OPS:
                if first_opt is None:
                    first_opt = i
                for g in op.input("Grad"):
                    if g and g not in grads:
                        grads.append(g)
        if first_opt is None or not grads:
            return
        self._append_dense_allreduce(block, first_opt, grads,
                                     compress=self._compress)


class LocalSGD(Collective):
    """Local SGD with periodic model averaging (ref collective.py:269).

    Each rank steps its optimizer independently; after the optimize ops,
    params are averaged across ranks (snapshot/delta form in the
    reference; direct averaging here — identical fixed point since the
    allreduce of (param - snap) with a shared snapshot equals direct
    param averaging).
    """

    def _transpile_main(self, main):
        block = main.global_block()
        params = []
        last_opt = None
        for i, op in enumerate(block.ops):
            if op.type in OPTIMIZE_OPS:
                last_opt = i
                for p in op.input("Param"):
                    if p and p not in params:
                        params.append(p)
        if last_opt is None:
            return
        at = last_opt + 1
        for ring, p in enumerate(params):
            block.insert_op(at, "c_allreduce_sum",
                            inputs={"X": [p]}, outputs={"Out": [p]},
                            attrs={"ring_id": ring % self.nrings})
            block.insert_op(at + 1, "scale",
                            inputs={"X": [p]}, outputs={"Out": [p]},
                            attrs={"scale": 1.0 / self.nranks, "bias": 0.0,
                                   "bias_after_scale": False})
            at += 2
