"""Parameter-server training (ref SURVEY §2.5 PS path).

Maps the reference's PS stack onto the native KV runtime
(``native/src/ps_server.cc``):

- ``DistributeTranspiler`` (ref ``transpiler/distribute_transpiler.py:212,
  476``): rewrites a trained program into a trainer program (optimizer ops
  removed; ``send`` grad / ``recv`` param host ops appended) and per-endpoint
  pserver programs (one ``listen_and_serv`` op carrying the param table +
  server-side optimizer config — ref ``listen_and_serv_op.cc`` runs optimize
  blocks; here the native server applies them in C++).
- ``send`` / ``recv`` ops (ref ``operators/distributed_ops/send_op.cc``,
  ``recv_op.cc``): lowered as ordered ``jax.experimental.io_callback``s so
  the host RPC happens inside the jitted step at the right point.
- ``Communicator`` (ref ``operators/distributed/communicator.h:162``):
  background async grad push / param pull; ``GeoCommunicator`` implements
  geo-SGD (ref ``DistributeTranspilerConfig geo_sgd_mode``): local steps,
  periodic param-delta push.
- sync semantics: the server accumulates each grad until every trainer
  pushed, applies the update once, and ``recv`` blocks until applied —
  the RunSyncLoop barrier structure (``listen_and_serv_op.cc:109-183``).

Params are placed round-robin by size (ref ``ps_dispatcher.py`` RoundRobin);
whole-param granularity (the reference's sub-block splitting exists to
balance very large embeddings — sparse tables here shard by ROW via
``split_ids``-style row routing instead).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

import numpy as np

from .. import monitor as _monitor
from .. import native
from .. import resilience as _resil
from ..framework import core
from ..framework.core import Program
from ..framework.registry import register_op
from ..framework.scope import global_scope
from ..ops.common import X, XS

OPTIM_IDS = {"sgd": 0, "momentum": 1, "adagrad": 2, "adam": 3}


# ---------------------------------------------------------------------------
# client registry
# ---------------------------------------------------------------------------

_clients: Dict[str, "PSClient"] = {}
_clients_lock = threading.Lock()


#: per-site retry policy cache: (retry_times, deadline_ms) -> policy, so
#: the no-failure hot path (every push/pull of every step) pays one flag
#: read + dict probe, not a RetryPolicy allocation per RPC
_policy_cache: Dict[tuple, "_resil.RetryPolicy"] = {}

#: per-endpoint RPC latency (the PS path's comms attribution — the
#: trainer-side analogue of paddle_tpu_collective_ms): wall time of the
#: whole _rpc envelope (native transport retries included), per endpoint
#: and op.  Failures observe too — a dying endpoint's deadline-long
#: calls are exactly the tail worth seeing.
_PS_RPC_HIST = _monitor.REGISTRY.histogram(
    "paddle_tpu_ps_rpc_ms",
    "parameter-server RPC wall time (ms) per endpoint and op (ps.put / "
    "ps.get / ps.push_dense / ps.push_sparse / ...), native transport "
    "retries included; circuit-open fail-fast rejections are excluded "
    "(they never touch the wire — see "
    "paddle_tpu_retry_circuit_open_total)",
    ("endpoint", "op"),
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
             100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0,
             30000.0, 120000.0, 300000.0))


def _rpc(site: str, fn, breaker: "Optional[_resil.CircuitBreaker]" = None):
    """Run one RPC attempt-function under the INJECTED-fault retry policy.

    Layering (deliberate — see native/src/ps_server.cc request_bytes):
    the NATIVE client owns transport retries.  It already implements the
    ``FLAGS_rpc_retry_times`` loop with exponential backoff + reconnect,
    and it alone can retry safely — it knows whether the request reached
    the wire (``sent``) and refuses to replay a possibly-applied
    non-idempotent push (``op_idempotent``), because re-sending an
    accumulate-op that WAS applied would double-count the gradient.  A
    Python-level retry of a native transport failure would both stack a
    second retry loop on top of that one (quadratic attempts) and replay
    exactly the pushes the native layer refused to.  So this wrapper
    retries ONLY transient faults raised ABOVE the transport — the
    ``FLAGS_fault_inject`` plane — while native errors (rc != 0) surface
    after the native budget is spent.

    ``breaker`` (the client's per-endpoint circuit breaker,
    ``FLAGS_rpc_circuit_break_secs``): once a call exhausts its whole
    retry budget on TRANSIENT failures, subsequent calls fail fast with
    ``CircuitOpenError`` for the cool-down instead of each re-paying the
    full backoff schedule against a dead endpoint; the half-open probe
    re-closes it.  Deterministic failures (server verdicts like an
    unknown table) close the breaker rather than trip it — the endpoint
    answered, it is not down."""
    from ..flags import get_flags
    if breaker is not None:
        breaker.check(site)
    fl = get_flags(["FLAGS_rpc_retry_times", "FLAGS_rpc_deadline"])
    key = (int(fl["FLAGS_rpc_retry_times"]), int(fl["FLAGS_rpc_deadline"]))
    policy = _policy_cache.get(key)
    if policy is None:
        # one derivation of the flag->policy mapping, shared with direct
        # retry_call('ps.*') users
        policy = _policy_cache[key] = _resil.RetryPolicy.from_flags(site)
    # per-endpoint latency attribution: the whole envelope (native
    # transport retries + injected-fault retries) observes into
    # paddle_tpu_ps_rpc_ms — failures included, because a dying
    # endpoint's deadline-long calls ARE the tail worth seeing.  The
    # breaker's fail-fast rejections above never reach here (no wire
    # time to attribute).
    endpoint = (breaker.name if breaker is not None and breaker.name
                else "local")
    t0 = time.perf_counter()
    try:
        out = _resil.retry_call(site, fn, policy=policy,
                                retryable=_resil.is_transient)
    except Exception as e:
        if breaker is not None:
            # a transient failure escaping retry_call IS a give-up (the
            # deadline wrapper chains the transient cause); anything
            # else is a verdict from a live endpoint
            if _resil.is_transient(e) or \
                    _resil.is_transient(getattr(e, "__cause__", None)
                                        or e):
                breaker.record_giveup()
            else:
                breaker.record_success()
        raise
    finally:
        _PS_RPC_HIST.observe((time.perf_counter() - t0) * 1e3,
                             endpoint=endpoint, op=site)
    if breaker is not None:
        breaker.record_success()
    return out


class PSClient:
    """ctypes wrapper over the native client (ref grpc_client.h RPCClient).

    Retry story: ``FLAGS_rpc_retry_times``/``FLAGS_rpc_deadline`` govern
    the NATIVE transport retry loop (connect-time/env-synced — see
    ``__init__`` and the flag side effects), which backs off, reconnects,
    and knows which ops are safe to replay.  On top of that, every RPC
    runs under ``_rpc`` so ``FLAGS_fault_inject`` sites (``ps.put``,
    ``ps.get``, ...) fire inside the attempt and injected-transient
    faults are absorbed by the same flag-sized budget."""

    def __init__(self, endpoint: str):
        lib = native._load()
        if lib is None:
            raise RuntimeError("native runtime unavailable: %s"
                               % native.build_error())
        host, port = endpoint.rsplit(":", 1)
        if host in ("localhost", ""):
            host = "127.0.0.1"
        self._lib = lib
        # plumb the registered flags to the native client: it reads
        # FLAGS_rpc_deadline from the env at connect time and
        # FLAGS_rpc_retry_times on EVERY request, so paddle_tpu.set_flags
        # governs the native transport retry loop (the flags' side
        # effects keep the env in sync after connect, too)
        import os
        from ..flags import get_flags
        fl = get_flags(["FLAGS_rpc_deadline", "FLAGS_rpc_retry_times"])
        os.environ["FLAGS_rpc_deadline"] = str(int(
            fl["FLAGS_rpc_deadline"]))
        os.environ["FLAGS_rpc_retry_times"] = str(int(
            fl["FLAGS_rpc_retry_times"]))
        self._h = lib.ps_client_connect(host.encode(), int(port))
        if not self._h:
            raise ConnectionError(f"cannot connect to pserver {endpoint}")
        # per-ENDPOINT circuit breaker (FLAGS_rpc_circuit_break_secs):
        # one dead pserver must not make every call to it re-pay the
        # full retry backoff — and must not poison calls to its peers
        self._breaker = _resil.CircuitBreaker(name=endpoint)

    @staticmethod
    def _check_dtype(dtype):
        if dtype is not None and np.dtype(dtype).itemsize != 4:
            raise ValueError(
                f"PS tables carry 4-byte elements; dtype={np.dtype(dtype)} "
                "cannot ride the wire format losslessly (use "
                "int32/uint32/float32)")

    def _buf(self, arr, dtype=None):
        import ctypes
        self._check_dtype(dtype)
        a = np.asarray(arr)
        if dtype is not None:
            # non-f32 4-byte tables (int32/uint32 counters, frequency
            # tables): bit-cast through the f32 wire format losslessly
            a = np.ascontiguousarray(a, dtype).view(np.float32)
        else:
            a = np.ascontiguousarray(a, np.float32)
        return a, a.ctypes.data_as(ctypes.c_void_p)

    def put(self, name: str, value, dtype=None) -> None:
        a, p = self._buf(value, dtype)    # dtype errors must NOT retry

        def _once():
            _resil.maybe_inject("ps.put")
            rc = self._lib.ps_client_put(self._h, name.encode(), p, a.size)
            if rc != 0:
                raise RuntimeError(
                    f"ps put({name}) failed (server down or "
                    "FLAGS_rpc_deadline exceeded?)")
        _rpc("ps.put", _once, breaker=self._breaker)

    def get(self, name: str, size: int, barrier: bool = True, dtype=None):
        import ctypes
        self._check_dtype(dtype)
        out = np.empty(size, np.float32)
        fn = self._lib.ps_client_get if barrier else \
            self._lib.ps_client_get_nobarrier

        def _once():
            _resil.maybe_inject("ps.get")
            n = fn(self._h, name.encode(),
                   out.ctypes.data_as(ctypes.c_void_p), size)
            if n == -2:
                # deterministic server verdict — _rpc never retries
                # native errors, so this fails fast by construction
                raise RuntimeError(f"ps get({name}): expected {size} "
                                   f"floats, got {n} (unknown table)")
            if n != size:
                raise RuntimeError(
                    f"ps get({name}): expected {size} floats, got {n} "
                    "(mis-sized table, server down, or FLAGS_rpc_deadline "
                    "exceeded?)")
        _rpc("ps.get", _once, breaker=self._breaker)
        if dtype is not None:
            return out.view(dtype)
        return out

    def push_dense(self, name: str, grad) -> None:
        a, p = self._buf(grad)

        def _once():
            _resil.maybe_inject("ps.push_dense")
            rc = self._lib.ps_client_push_dense(self._h, name.encode(), p,
                                                a.size)
            if rc != 0:
                raise RuntimeError(
                    f"ps push_dense({name}) failed — gradient would be "
                    "silently dropped (unknown table or server down)")
        _rpc("ps.push_dense", _once, breaker=self._breaker)

    def push_sparse(self, name: str, rows, grad) -> None:
        import ctypes
        r = np.ascontiguousarray(np.asarray(rows).ravel(), np.uint32)
        a, p = self._buf(grad)

        def _once():
            _resil.maybe_inject("ps.push_sparse")
            rc = self._lib.ps_client_push_sparse(
                self._h, name.encode(), r.ctypes.data_as(ctypes.c_void_p),
                len(r), p, a.size)
            if rc != 0:
                raise RuntimeError(
                    f"ps push_sparse({name}) failed — gradient would be "
                    "silently dropped (unknown table or server down)")
        _rpc("ps.push_sparse", _once, breaker=self._breaker)

    def get_rows(self, name: str, rows, width: int):
        import ctypes
        r = np.ascontiguousarray(np.asarray(rows).ravel(), np.uint32)
        out = np.empty(len(r) * width, np.float32)

        def _once():
            _resil.maybe_inject("ps.get_rows")
            n = self._lib.ps_client_get_rows(
                self._h, name.encode(), r.ctypes.data_as(ctypes.c_void_p),
                len(r), out.ctypes.data_as(ctypes.c_void_p), out.size)
            if n != out.size:
                raise RuntimeError(
                    f"ps get_rows({name}): expected {out.size} floats, got "
                    f"{n} (unknown table or wrong width?)")
        _rpc("ps.get_rows", _once, breaker=self._breaker)
        return out.reshape(len(r), width)

    def barrier(self) -> None:
        self._lib.ps_client_barrier(self._h)

    def stop_server(self) -> None:
        self._lib.ps_client_stop_server(self._h)

    # -- typed tables (ref VariableMessage.dtype, send_recv.proto.in:47):
    # bf16 embeddings ride the wire at half the bytes (f32 master on the
    # server); int64 tables (CTR show/click counters) are exact end to
    # end and accumulate on push.

    @staticmethod
    def _typed_code(dtype):
        import ml_dtypes
        d = np.dtype(dtype)
        if d == np.dtype(ml_dtypes.bfloat16):
            return 1, d
        if d == np.dtype(np.int64):
            return 2, d
        if d == np.dtype(np.float32):
            return 0, d
        raise ValueError(
            f"typed PS tables support float32/bfloat16/int64, got {d}")

    def put_typed(self, name: str, value, dtype) -> None:
        import ctypes
        code, d = self._typed_code(dtype)
        a = np.ascontiguousarray(np.asarray(value).ravel(), d)

        def _once():
            _resil.maybe_inject("ps.put_typed")
            rc = self._lib.ps_client_put_typed(
                self._h, name.encode(), a.ctypes.data_as(ctypes.c_void_p),
                a.size, code)
            if rc != 0:
                raise RuntimeError(f"ps put_typed({name}) failed")
        _rpc("ps.put_typed", _once, breaker=self._breaker)

    def get_typed(self, name: str, size: int, dtype):
        import ctypes
        code, d = self._typed_code(dtype)
        out = np.empty(size, d)

        def _once():
            _resil.maybe_inject("ps.get_typed")
            n = self._lib.ps_client_get_typed(
                self._h, name.encode(), out.ctypes.data_as(ctypes.c_void_p),
                size, code)
            if n == -2:
                raise RuntimeError(
                    f"ps get_typed({name}): expected {size} elems, got "
                    f"{n} (unknown table or dtype mismatch)")
            if n != size:
                raise RuntimeError(
                    f"ps get_typed({name}): expected {size} elems, got {n}")
        _rpc("ps.get_typed", _once, breaker=self._breaker)
        return out

    def push_typed(self, name: str, grad, dtype, rows=None) -> None:
        """int64 tables: accumulate-add (counters); bf16/f32 tables: run
        the table's optimizer against the f32 master.  ``rows`` selects
        per-row sparse application."""
        import ctypes
        code, d = self._typed_code(dtype)
        a = np.ascontiguousarray(np.asarray(grad).ravel(), d)
        if rows is None:
            rp, nr = None, 0
        else:
            r = np.ascontiguousarray(np.asarray(rows).ravel(), np.uint32)
            rp, nr = r.ctypes.data_as(ctypes.c_void_p), len(r)

        def _once():
            _resil.maybe_inject("ps.push_typed")
            rc = self._lib.ps_client_push_typed(
                self._h, name.encode(), rp, nr,
                a.ctypes.data_as(ctypes.c_void_p), a.size, code)
            if rc != 0:
                raise RuntimeError(f"ps push_typed({name}) failed")
        _rpc("ps.push_typed", _once, breaker=self._breaker)

    def close(self) -> None:
        if self._h:
            self._lib.ps_client_destroy(self._h)
            self._h = None


def get_client(endpoint: str) -> PSClient:
    with _clients_lock:
        c = _clients.get(endpoint)
        if c is None:
            c = PSClient(endpoint)
            _clients[endpoint] = c
        return c


def reset_clients() -> None:
    with _clients_lock:
        for c in _clients.values():
            try:
                c.close()
            except Exception:
                pass
        _clients.clear()


# ---------------------------------------------------------------------------
# server (ref listen_and_serv_op.cc + grpc_server.cc)
# ---------------------------------------------------------------------------

class PSServer:
    """Owns one native server process-wide; built from a pserver program's
    listen_and_serv op attrs + the initialized scope values."""

    def __init__(self, port: int, num_trainers: int, sync_mode: bool,
                 param_specs: List[dict], scope=None):
        lib = native._load()
        if lib is None:
            raise RuntimeError("native runtime unavailable: %s"
                               % native.build_error())
        self._lib = lib
        self._h = lib.ps_server_create(int(port), int(num_trainers),
                                       1 if sync_mode else 0)
        scope = scope or global_scope()
        import ctypes
        for spec in param_specs:
            init = scope.find_var(spec["name"])
            val = np.ascontiguousarray(
                np.asarray(init).ravel() if init is not None
                else np.zeros(spec["size"]), np.float32)
            lib.ps_server_add_param(
                self._h, spec["name"].encode(), val.size,
                val.ctypes.data_as(ctypes.c_void_p),
                OPTIM_IDS.get(spec.get("optimizer", "sgd"), 0),
                float(spec.get("lr", 0.01)), float(spec.get("hp1", 0.9)),
                float(spec.get("hp2", 0.999)),
                int(spec.get("rows", 0)))
        self.port = None

    def start(self) -> int:
        port = self._lib.ps_server_start(self._h)
        if port < 0:
            raise RuntimeError(f"pserver bind failed: {port}")
        self.port = port
        return port

    def wait(self) -> None:
        self._lib.ps_server_wait(self._h)

    def stop(self) -> None:
        self._lib.ps_server_stop(self._h)

    def get_param(self, name: str, size: int):
        import ctypes
        out = np.empty(size, np.float32)
        n = self._lib.ps_server_get(self._h, name.encode(),
                                    out.ctypes.data_as(ctypes.c_void_p), size)
        return out[:max(n, 0)]

    def destroy(self) -> None:
        self._lib.ps_server_destroy(self._h)
        self._h = None


def run_pserver(op, scope, wait: bool = True) -> PSServer:
    """Execute a listen_and_serv op host-side (called by Executor.run when a
    program contains one — the blocking server loop can't live under jit)."""
    attrs = op.attrs
    endpoint = attrs["endpoint"]
    port = int(endpoint.rsplit(":", 1)[1])
    server = PSServer(port, attrs.get("Fanin", 1),
                      attrs.get("sync_mode", True),
                      attrs.get("param_specs", []), scope)
    server.start()
    if wait:
        server.wait()
        server.destroy()
        return None
    return server


# ---------------------------------------------------------------------------
# trainer-side ops (ref operators/distributed_ops/send_op.cc, recv_op.cc,
# distributed_ops/distributed_lookup_table_op.cc)
# ---------------------------------------------------------------------------

@register_op("send", no_grad=True)
def _send(ctx, ins, attrs):
    import jax
    from jax.experimental import io_callback
    eps = attrs["epmap"]
    names = attrs["send_varnames"]
    is_sparse = attrs.get("is_sparse", [0] * len(names))
    xs = XS(ins, "X")
    rows_in = ins.get("Rows", [None] * len(xs))
    pad = int(attrs.get("padding_idx", -1))
    for x, ep, nm, sp, rows in zip(xs, eps, names, is_sparse, rows_in):
        if sp and rows is not None:
            def cb_sp(r, v, ep=ep, nm=nm):
                r = np.asarray(r).ravel()
                v = np.asarray(v, np.float32).reshape(len(r), -1)
                if pad >= 0:
                    keep = r != pad     # padding rows carry no gradient
                    r, v = r[keep], v[keep]
                if len(r):
                    get_client(ep).push_sparse(nm, r, v)
                return np.zeros((), np.float32)
            io_callback(cb_sp, jax.ShapeDtypeStruct((), np.float32),
                        rows, x, ordered=True)
        else:
            def cb(v, ep=ep, nm=nm):
                get_client(ep).push_dense(nm, np.asarray(v, np.float32))
                return np.zeros((), np.float32)
            io_callback(cb, jax.ShapeDtypeStruct((), np.float32), x,
                        ordered=True)
    return {}


@register_op("recv", no_grad=True)
def _recv(ctx, ins, attrs):
    import jax
    from jax.experimental import io_callback
    eps = attrs["epmap"]
    names = attrs["recv_varnames"]
    shapes = attrs["shapes"]
    barrier = attrs.get("with_barrier", True)
    outs = []
    for ep, nm, shape in zip(eps, names, shapes):
        size = int(np.prod(shape)) if shape else 1

        def cb(ep=ep, nm=nm, size=size, shape=tuple(shape)):
            v = get_client(ep).get(nm, size, barrier=barrier)
            return v.reshape(shape).astype(np.float32)

        outs.append(io_callback(
            cb, jax.ShapeDtypeStruct(tuple(shape), np.float32),
            ordered=True))
    return {"Out": outs}


@register_op("distributed_lookup_table", no_grad=True)
def _distributed_lookup_table(ctx, ins, attrs):
    """Sparse embedding pull (ref operators/distributed_ops/
    distributed_lookup_table_op.cc + parameter_prefetch.cc): fetch only the
    queried rows from the owning pserver."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import io_callback
    ids = X(ins, "Ids")
    ep = attrs["endpoint"]
    table = attrs["table_name"]
    width = attrs["emb_dim"]
    pad = int(attrs.get("padding_idx", -1))
    flat = ids.reshape(-1)
    safe = jnp.where(flat == pad, 0, flat) if pad >= 0 else flat

    def cb(rows, ep=ep, table=table, width=width):
        return get_client(ep).get_rows(
            table, np.asarray(rows, np.uint32), width).astype(np.float32)

    out = io_callback(
        cb, jax.ShapeDtypeStruct((flat.shape[0], width), np.float32),
        safe, ordered=True)
    if pad >= 0:
        # padding rows are zero, exactly as the local lookup_table kernel
        out = out * (flat != pad).astype(out.dtype)[:, None]
    # mirror lookup_table's trailing dim-1 squeeze so rewritten programs
    # keep the shapes they were built with
    shape = tuple(ids.shape)
    if len(shape) >= 2 and shape[-1] == 1:
        shape = shape[:-1]
    return {"Outputs": [out.reshape(shape + (width,))]}


@register_op("fetch_barrier", no_grad=True)
def _fetch_barrier(ctx, ins, attrs):
    import jax
    from jax.experimental import io_callback
    eps = attrs.get("endpoints", [])

    def cb():
        for ep in eps:
            get_client(ep).barrier()
        return np.zeros((), np.float32)

    io_callback(cb, jax.ShapeDtypeStruct((), np.float32), ordered=True)
    return {}


register_op("send_barrier", lambda ctx, ins, attrs: {}, no_grad=True)


@register_op("listen_and_serv", no_grad=True)
def _listen_and_serv(ctx, ins, attrs):
    raise RuntimeError(
        "listen_and_serv is a host-side blocking op; Executor.run handles "
        "it before jit — reaching this lowering means the pserver program "
        "was embedded in a larger traced block")


# ---------------------------------------------------------------------------
# transpiler (ref transpiler/distribute_transpiler.py)
# ---------------------------------------------------------------------------

class DistributeTranspilerConfig:
    """ref distribute_transpiler.py:131."""

    slice_var_up = True
    split_method = "RoundRobin"
    min_block_size = 8192
    sync_mode = True
    runtime_split_send_recv = False
    geo_sgd_mode = False
    geo_sgd_need_push_nums = 100

    def __init__(self, **kw):
        for k, v in kw.items():
            setattr(self, k, v)


#: optimizer op types the transpiler moves to the pserver
PS_OPTIMIZER_OPS = {"sgd", "momentum", "adagrad", "adam"}


class DistributeTranspiler:
    """ref transpiler/distribute_transpiler.py DistributeTranspiler.

    ``transpile`` → ``get_trainer_program`` / ``get_pserver_program`` /
    ``get_startup_program``, same call protocol as the reference.
    """

    def __init__(self, config: Optional[DistributeTranspilerConfig] = None):
        self.config = config or DistributeTranspilerConfig()
        self._param_eps: Dict[str, str] = {}     # param -> endpoint
        self._param_specs: Dict[str, dict] = {}
        self._grad_of: Dict[str, str] = {}       # param -> grad var
        self._sparse_tables: Dict[str, list] = {}  # table -> lookup sites
        self._origin_program: Optional[Program] = None

    def transpile(self, trainer_id: int, program: Optional[Program] = None,
                  pservers: str = "127.0.0.1:6174", trainers: int = 1,
                  sync_mode: Optional[bool] = None, startup_program=None,
                  current_endpoint: str = ""):
        self.trainer_id = trainer_id
        self.trainer_num = trainers
        self.eps = pservers.split(",")
        if sync_mode is not None:
            self.config.sync_mode = sync_mode
        program = program or core.default_main_program()
        self._origin_program = program
        self._startup = startup_program or core.default_startup_program()
        block = program.global_block()

        # collect (param, grad, optimizer) triples from the optimize ops
        lr_value = self._find_lr_value()
        for op in block.ops:
            if op.type not in PS_OPTIMIZER_OPS:
                continue
            pname = op.input("Param")[0]
            gname = op.input("Grad")[0]
            pvar = block.var(pname)
            size = int(np.prod([d for d in pvar.shape if d > 0]))
            spec = {"name": pname, "size": size, "optimizer": op.type,
                    "lr": lr_value, "shape": [d for d in pvar.shape],
                    "rows": 0}
            if op.type == "momentum":
                spec["hp1"] = op.attrs.get("mu", 0.9)
            if op.type == "adam":
                spec["hp1"] = op.attrs.get("beta1", 0.9)
                spec["hp2"] = op.attrs.get("beta2", 0.999)
            self._param_specs[pname] = spec
            self._grad_of[pname] = gname
        # sparse embedding tables: a lookup_table marked is_sparse /
        # is_distributed becomes a row-sharded server table pulled by id
        # (ref distribute_transpiler.py sparse-update path +
        # parameter_prefetch.cc); the trainer never holds the full table.
        # A table may be looked up at several sites (shared embedding) —
        # every site is recorded and every site's row grads are pushed.
        self._sparse_tables = {}
        for op in block.ops:
            if op.type != "lookup_table" or not (
                    op.attrs.get("is_sparse") or
                    op.attrs.get("is_distributed")):
                continue
            w = op.input("W")[0]
            if w not in self._param_specs:
                continue
            wvar = block.var(w)
            self._param_specs[w]["rows"] = int(wvar.shape[0])
            self._sparse_tables.setdefault(w, []).append({
                "ids": op.input("Ids")[0],
                "out": op.output("Out")[0],
                "emb_dim": int(wvar.shape[1]),
                "padding_idx": op.attrs.get("padding_idx", -1),
            })
        # round-robin placement (ref ps_dispatcher.py RoundRobinDispatcher)
        for i, pname in enumerate(sorted(self._param_specs)):
            self._param_eps[pname] = self.eps[i % len(self.eps)]

    def _find_lr_value(self) -> float:
        for op in self._startup.global_block().ops \
                if self._startup is not None else []:
            if op.type == "fill_constant":
                out = op.output("Out")
                if out and "learning_rate" in out[0]:
                    return float(op.attrs.get("value", 0.01))
        return 0.01

    # -- trainer side --------------------------------------------------------
    def get_trainer_program(self, wait_port: bool = True) -> Program:
        """ref :814 — strip optimizer ops; append send(grad) + recv(param).

        geo-SGD mode keeps local optimizer ops (the GeoCommunicator pushes
        deltas outside the step)."""
        prog = self._origin_program.clone()
        block = prog.global_block()
        sparse = self._sparse_tables
        if not self.config.geo_sgd_mode:
            grad_prefixes = tuple(core.grad_var_name(w) for w in sparse)

            def _is_dense_table_grad(op):
                # drop the dense full-table grad of sparse params (and the
                # sum op merging multi-site @RENAME@ pieces): row grads are
                # pushed instead, and a real table's dense grad would be
                # GBs of wasted scatter per step
                outs = op.output_arg_names()
                return bool(outs) and all(
                    o.startswith(grad_prefixes) for o in outs)

            block.ops = [
                op for op in block.ops
                if op.type not in PS_OPTIMIZER_OPS and
                not (grad_prefixes and _is_dense_table_grad(op))]
            # sparse tables: rewrite each lookup site to a row pull from
            # the owning pserver (ref §3.4 'lookup_table w/ remote
            # prefetch') and push only the touched rows' gradients
            for w, sites in sparse.items():
                ep = self._param_eps[w]
                for site in sites:
                    for op in block.ops:
                        if op.type == "lookup_table" and \
                                op.input("W") == [w] and \
                                op.input("Ids") == [site["ids"]] and \
                                op.output("Out") == [site["out"]]:
                            op.type = "distributed_lookup_table"
                            op.inputs = {"Ids": [site["ids"]]}
                            op.outputs = {"Outputs": [site["out"]]}
                            op.attrs = {"endpoint": ep, "table_name": w,
                                        "emb_dim": site["emb_dim"],
                                        "padding_idx": site["padding_idx"]}
                            break
                    # d loss / d out rows ARE the per-id row grads; sync
                    # mode scales by 1/trainers client-side (the dense path
                    # divides server-side on apply; sparse rows apply as
                    # they arrive — the reference's async sparse recorder
                    # semantics, mid-round row visibility included)
                    gname = core.grad_var_name(site["out"])
                    if self.trainer_num > 1:
                        block.append_op(
                            "scale", inputs={"X": [gname]},
                            outputs={"Out": [gname]},
                            attrs={"scale": 1.0 / self.trainer_num,
                                   "bias": 0.0,
                                   "bias_after_scale": False})
                    block.append_op(
                        "send",
                        inputs={"X": [gname], "Rows": [site["ids"]]},
                        outputs={},
                        attrs={"epmap": [ep], "send_varnames": [w],
                               "is_sparse": [1],
                               "padding_idx": site["padding_idx"]})
            by_ep: Dict[str, List[str]] = {}
            for pname, ep in self._param_eps.items():
                if pname in sparse:
                    continue
                by_ep.setdefault(ep, []).append(pname)
            for ep, pnames in sorted(by_ep.items()):
                block.append_op(
                    "send",
                    inputs={"X": [self._grad_of[p] for p in pnames]},
                    outputs={},
                    attrs={"epmap": [ep] * len(pnames),
                           "send_varnames": pnames})
                block.append_op(
                    "recv", inputs={},
                    outputs={"Out": pnames},
                    attrs={"epmap": [ep] * len(pnames),
                           "recv_varnames": pnames,
                           "shapes": [self._param_specs[p]["shape"]
                                      for p in pnames],
                           "with_barrier": self.config.sync_mode})
        prog._attrs["is_distributed"] = True
        return prog

    # -- pserver side --------------------------------------------------------
    def get_pserver_program(self, endpoint: str) -> Program:
        """ref :948 — one listen_and_serv op with this endpoint's shard."""
        prog = Program()
        specs = [self._param_specs[p]
                 for p, ep in sorted(self._param_eps.items())
                 if ep == endpoint]
        if self.config.geo_sgd_mode:
            # geo: trainers push param DELTAS; the server just adds them
            # (SGD with lr=1 on grad=-delta → value += delta)
            specs = [dict(s, optimizer="sgd", lr=1.0) for s in specs]
        prog.global_block().append_op(
            "listen_and_serv", inputs={}, outputs={},
            attrs={"endpoint": endpoint,
                   "Fanin": self.trainer_num,
                   "sync_mode": self.config.sync_mode and
                   not self.config.geo_sgd_mode,
                   "param_specs": specs})
        return prog

    def get_pserver_programs(self, endpoint: str):
        p = self.get_pserver_program(endpoint)
        return p, self.get_startup_program(endpoint, p)

    def get_startup_program(self, endpoint: str,
                            pserver_program: Optional[Program] = None
                            ) -> Program:
        """Startup for one pserver: create + init only its params."""
        prog = Program()
        blk = prog.global_block()
        src = self._startup.global_block()
        mine = {p for p, ep in self._param_eps.items() if ep == endpoint}
        for name in mine:
            v = src.var(name) if src.has_var(name) else None
            blk.create_var(name=name,
                           shape=v.shape if v else
                           self._param_specs[name]["shape"],
                           dtype=v.dtype if v else "float32",
                           persistable=True)
        for op in src.ops:
            outs = op.output_arg_names()
            if outs and all(o in mine for o in outs):
                blk.append_op(op.type, inputs=dict(op.inputs),
                              outputs=dict(op.outputs), attrs=dict(op.attrs))
        return prog


# ---------------------------------------------------------------------------
# async / geo communicators (ref operators/distributed/communicator.h,
# python/paddle/fluid/communicator.py)
# ---------------------------------------------------------------------------

class Communicator:
    """Async-mode background param PULLER (the RecvThread half of ref
    ``communicator.h``; the push half lives in the in-graph ``send`` op,
    which applies immediately in async mode).

    Use with a trainer program transpiled WITHOUT recv ops (async mode may
    drop them: pulls are decoupled from steps) — with in-graph recv, the
    step's own write-back would race these background scope writes."""

    def __init__(self, transpiler: DistributeTranspiler, scope=None,
                 send_interval_s: float = 0.01):
        self.t = transpiler
        self.scope = scope or global_scope()
        self.interval = send_interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.error: Optional[Exception] = None

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        # a dead puller must not be silent: record the failure so check()/
        # stop() surface it instead of the trainer reading stale params
        # forever (ref communicator.h RecvThread glog-fatals on RPC error)
        try:
            while not self._stop.is_set():
                for pname, ep in self.t._param_eps.items():
                    spec = self.t._param_specs[pname]
                    fresh = get_client(ep).get(pname, spec["size"],
                                               barrier=False)
                    self.scope.set_var(pname, fresh.reshape(spec["shape"]))
                self._stop.wait(self.interval)
        except Exception as e:   # noqa: BLE001 — any RPC failure
            self.error = e

    def check(self):
        """Raise if the background puller died."""
        if self.error is not None:
            raise RuntimeError(
                "Communicator recv thread died; trainer was reading stale "
                "params") from self.error

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
        self.check()


class GeoCommunicator:
    """geo-SGD (ref distribute_transpiler geo_sgd_mode + communicator_py):
    train locally; every ``push_nums`` steps push param deltas (server adds
    them: SGD with lr=-1 on -delta ≡ +=delta) and pull the merged params.

    Two properties make geo the *cheapest* PS mode (its purpose — ref
    geo_sgd_communicator.cc runs send/recv in background threads over
    recorded sparse ids):

    - **row recording**: the trainer reports the table rows each batch fed
      via :meth:`record_rows`; at a push boundary only those rows are
      diffed/pushed — no full-table delta scan.  Without recording, a
      sparse table falls back to the scan (and, when a local *dense*
      optimizer such as Adam has drifted ≥ half the rows, to one dense
      round trip — cheaper than per-row applies at that density).
    - **async round trips** (``async_push=True``): the TCP push/pull runs
      in a background thread; the boundary step only gathers deltas and
      applies the previous round's merged rows.  Local updates made while
      a round is in flight are preserved (``new = fresh + (cur − cur@push)``)
      and re-pushed next round — the documented geo staleness window is
      ≤ one push interval.
    """

    def __init__(self, transpiler: DistributeTranspiler, scope=None,
                 async_push: bool = False):
        self.t = transpiler
        self.scope = scope or global_scope()
        self.push_nums = transpiler.config.geo_sgd_need_push_nums
        self.async_push = async_push
        self._step = 0
        self._snapshots: Dict[str, np.ndarray] = {}
        self._touched: Dict[str, List[np.ndarray]] = {}
        self._worker: Optional[threading.Thread] = None
        self._worker_exc: Optional[BaseException] = None
        self._inflight: List[dict] = []

    def init_snapshots(self):
        for pname, spec in self.t._param_specs.items():
            v = np.asarray(self.scope.find_var(pname), np.float32)
            self._snapshots[pname] = v.copy()
            # seed the server with the initial value
            get_client(self.t._param_eps[pname]).put(pname, v.ravel())

    def record_rows(self, pname: str, rows) -> None:
        """Report the rows of sparse table ``pname`` fed this step (ref
        geo_sgd_communicator.cc sparse-id recording from the send queue).
        Deltas are then computed only on recorded rows at the boundary."""
        if pname not in self.t._param_specs:
            raise KeyError(
                f"record_rows({pname!r}): not a transpiled parameter "
                f"(known: {sorted(self.t._param_specs)})")
        self._touched.setdefault(pname, []).append(
            np.asarray(rows, np.int64).ravel())

    def step(self):
        self._step += 1
        if self._step % self.push_nums:
            return
        self._join_and_apply()             # previous round (async mode)
        work = self._collect_deltas()
        if not work:
            return
        if self.async_push:
            def _run():
                try:
                    self._round_trip(work)
                except BaseException as e:   # surfaced at the next join
                    self._worker_exc = e
            self._worker = threading.Thread(target=_run, daemon=True)
            self._worker.start()
        else:
            self._round_trip(work)
            self._join_and_apply()

    def flush(self):
        """Drain the in-flight round and push any remaining local delta
        synchronously (call once at the end of training)."""
        self._join_and_apply()
        work = self._collect_deltas()
        if work:
            self._round_trip(work)
            self._join_and_apply()

    # -- boundary phases (all scope access happens on the caller's thread;
    #    the worker only moves bytes) --------------------------------------

    def _collect_deltas(self) -> List[dict]:
        work = []
        n = self.t.trainer_num
        for pname, ep in self.t._param_eps.items():
            spec = self.t._param_specs[pname]
            cur = np.asarray(self.scope.find_var(pname), np.float32)
            snap = self._snapshots[pname]
            recorded = self._touched.pop(pname, None)
            if spec.get("rows") and cur.ndim == 2:
                if recorded is not None:
                    rows = np.unique(np.concatenate(recorded))
                else:
                    # no recording: full scan ((cur != snap).any is ~3×
                    # cheaper than abs(delta).max and allocates no temp)
                    rows = np.flatnonzero((cur != snap).any(axis=1))
                if rows.size == 0:
                    continue
                if rows.size * 2 < cur.shape[0]:
                    cur_rows = cur[rows].astype(np.float32, copy=True)
                    delta = (cur_rows - snap[rows]) / n
                    work.append({"pname": pname, "ep": ep, "rows": rows,
                                 "cur_at_push": cur_rows, "delta": delta,
                                 "width": cur.shape[1]})
                    continue
            # dense param — or a HOT sparse interval (≥ half the rows
            # moved: one dense round trip beats per-row applies)
            delta = (cur - snap) / n
            work.append({"pname": pname, "ep": ep, "rows": None,
                         "cur_at_push": cur.copy(), "delta": delta,
                         "spec": spec})
        return work

    def _round_trip(self, work: List[dict]) -> None:
        # append each param as it completes (not all-at-once at the end):
        # on a mid-list failure the already-pushed params are applied —
        # and their snapshots advanced — at the next join, so a caller
        # that survives the raised error cannot re-push a delta the
        # server has already merged
        for w in work:
            cli = get_client(w["ep"])
            if w["rows"] is not None:
                cli.push_sparse(w["pname"], w["rows"],
                                (-w["delta"]).astype(np.float32))
                w["fresh"] = np.asarray(
                    cli.get_rows(w["pname"], w["rows"], width=w["width"]),
                    np.float32)
            else:
                cli.push_dense(w["pname"], -w["delta"].ravel())
                fresh = cli.get(w["pname"], w["spec"]["size"], barrier=False)
                w["fresh"] = fresh.reshape(w["spec"]["shape"]).astype(
                    np.float32)
            self._inflight.append(w)

    def _join_and_apply(self) -> None:
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        exc, self._worker_exc = self._worker_exc, None
        # apply whatever completed BEFORE surfacing a failure: those
        # deltas are already merged server-side, so their snapshots must
        # advance or a surviving caller would push them twice
        work, self._inflight = self._inflight, []
        for w in work:
            pname = w["pname"]
            cur = np.asarray(self.scope.find_var(pname), np.float32)
            if w["rows"] is not None:
                rows, fresh = w["rows"], w["fresh"]
                new = np.array(cur, np.float32)       # writable copy
                if self.async_push:
                    # merged rows + local drift made while the round was
                    # in flight (drift is still unpushed: snapshot :=
                    # fresh keeps it in the next round's delta)
                    new[rows] = fresh + (cur[rows] - w["cur_at_push"])
                else:
                    # synchronous boundary: no steps ran since the push,
                    # drift is structurally zero — assign exactly
                    new[rows] = fresh
                self._snapshots[pname][rows] = fresh
                self.scope.set_var(pname, new)
            else:
                if self.async_push:
                    new = (w["fresh"] + (cur - w["cur_at_push"])).astype(
                        np.float32)
                else:
                    new = w["fresh"]
                self._snapshots[pname] = w["fresh"].copy()
                self.scope.set_var(pname, new)
        if exc is not None:
            raise RuntimeError("geo background push/pull failed") from exc
