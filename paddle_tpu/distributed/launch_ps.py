"""Parameter-server job launcher:
``python -m paddle_tpu.distributed.launch_ps train.py``.

Reference: ``python/paddle/distributed/launch_ps.py`` — spawns
``--server_num`` pserver processes and ``--worker_num`` trainer processes
on this node with the PS env contract (TRAINING_ROLE, PADDLE_PSERVER_ID /
PADDLE_TRAINER_ID, PADDLE_PSERVER_ENDPOINTS, PADDLE_TRAINERS_NUM), streams
logs, and tears the gang down if any process fails.  The training script
uses ``paddle_tpu.distributed.ps_fleet`` to pick its role from the env.
"""

from __future__ import annotations

import argparse
import os
import sys

from .launch import start_procs, wait_procs


def _parse_args(argv=None):
    p = argparse.ArgumentParser(
        description="paddle_tpu PS launcher (ref launch_ps.py)")
    p.add_argument("--server_num", type=int, default=2)
    p.add_argument("--worker_num", type=int, default=2)
    p.add_argument("--servers", default=None,
                   help="comma-separated server endpoints (overrides "
                        "--server_num, for multi-node jobs)")
    p.add_argument("--workers", default=None,
                   help="comma-separated worker endpoints")
    p.add_argument("--started_port", type=int, default=6270)
    p.add_argument("--log_dir", default=None)
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def get_ps_cluster_env(args):
    """Per-process env dicts: servers first, then workers."""
    if args.servers:
        server_eps = args.servers.split(",")
    else:
        server_eps = [f"127.0.0.1:{args.started_port + i}"
                      for i in range(args.server_num)]
    if args.workers:
        worker_eps = args.workers.split(",")
    else:
        worker_eps = [f"127.0.0.1:{args.started_port + 1000 + i}"
                      for i in range(args.worker_num)]
    common = {
        "PADDLE_PSERVER_ENDPOINTS": ",".join(server_eps),
        "PADDLE_TRAINER_ENDPOINTS": ",".join(worker_eps),
        "PADDLE_TRAINERS_NUM": str(len(worker_eps)),
    }
    envs = []
    for i in range(len(server_eps)):
        envs.append(dict(common, TRAINING_ROLE="PSERVER",
                         PADDLE_PSERVER_ID=str(i),
                         PADDLE_CURRENT_ENDPOINT=server_eps[i],
                         PADDLE_TRAINER_ID=str(i),
                         PADDLE_LOG_NAME=f"server.{i}"))
    for i in range(len(worker_eps)):
        envs.append(dict(common, TRAINING_ROLE="TRAINER",
                         PADDLE_TRAINER_ID=str(i),
                         PADDLE_CURRENT_ENDPOINT=worker_eps[i],
                         PADDLE_LOG_NAME=f"worker.{i}"))
    return envs


def launch(argv=None):
    args = _parse_args(argv)
    envs = get_ps_cluster_env(args)
    procs, logs = start_procs(args, envs)
    try:
        wait_procs(procs)
    finally:
        for f in logs:
            f.close()


if __name__ == "__main__":
    launch()
