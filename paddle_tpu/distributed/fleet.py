"""Fleet: the unified distributed-training facade.

Reference: ``python/paddle/fluid/incubate/fleet/base/fleet_base.py:38,222``
(``fleet.init(role_maker)`` / ``fleet.distributed_optimizer(...)``
``.minimize()`` / ``init_worker`` / ``init_server``) with the Collective
backend (``incubate/fleet/collective/__init__.py:41,140``) and RoleMakers
(``incubate/fleet/base/role_maker.py``).

Collective mode here = GradAllReduce transpile + the executor's shard_map
collective mode (XLA collectives over the dp mesh axis); multi-host
bootstrap = jax.distributed via ``init_parallel_env``.
"""

from __future__ import annotations

import os
from typing import List, Optional

from .env import Env, init_parallel_env
from .transpiler import GradAllReduce, LocalSGD


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints: List[str] = []
        self._server_endpoints: List[str] = []
        self._role = Role.WORKER
        self._current_id = 0

    def is_worker(self) -> bool:
        return self._role == Role.WORKER

    def is_server(self) -> bool:
        return self._role == Role.SERVER

    def is_first_worker(self) -> bool:
        return self.is_worker() and self._current_id == 0

    def worker_index(self) -> int:
        return self._current_id

    def server_index(self) -> int:
        return self._current_id

    def worker_num(self) -> int:
        return max(1, len(self._worker_endpoints))

    def server_num(self) -> int:
        return len(self._server_endpoints)

    def get_trainer_endpoints(self) -> List[str]:
        return self._worker_endpoints

    def get_pserver_endpoints(self) -> List[str]:
        return self._server_endpoints

    def get_current_endpoint(self) -> str:
        """This process's own endpoint (ref role_maker get_current_endpoint):
        a server serves its slot of the pserver list; a worker reports its
        trainer endpoint."""
        eps = self._server_endpoints if self.is_server() \
            else self._worker_endpoints
        if not eps:
            return ""
        return eps[min(self._current_id, len(eps) - 1)]

    def generate_role(self):
        pass


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-var role maker (ref role_maker.py PaddleCloudRoleMaker): reads
    the PADDLE_* contract that ``paddle_tpu.distributed.launch`` emits."""

    def __init__(self, is_collective: bool = False):
        super().__init__()
        self._is_collective = is_collective

    def generate_role(self):
        env = Env()
        training_role = os.getenv("TRAINING_ROLE", "TRAINER")
        if training_role == "PSERVER" and not self._is_collective:
            self._role = Role.SERVER
            self._current_id = int(os.getenv("PADDLE_PSERVER_ID", "0"))
            eps = os.getenv("PADDLE_PSERVER_ENDPOINTS", "")
            self._server_endpoints = eps.split(",") if eps else []
            # servers must still know the trainer count (sync Fanin)
            teps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
            self._worker_endpoints = teps.split(",") if teps else \
                [""] * int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        else:
            self._role = Role.WORKER
            self._current_id = env.rank
            self._worker_endpoints = env.trainer_endpoints or \
                ["127.0.0.1:6174"]
            eps = os.getenv("PADDLE_PSERVER_ENDPOINTS", "")
            self._server_endpoints = eps.split(",") if eps else []


class UserDefinedRoleMaker(RoleMakerBase):
    """ref role_maker.py UserDefinedRoleMaker."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._server_endpoints = server_endpoints or []
        self._worker_endpoints = worker_endpoints or \
            [f"127.0.0.1:{6170 + i}" for i in range(worker_num)]


class DistributedStrategy:
    """ref ``incubate/fleet/collective/__init__.py:94`` DistributedStrategy.

    TPU mapping notes: nccl_comm_num / hierarchical allreduce are XLA's
    job (multi-stream + ICI/DCN hierarchy come from the compiler); the
    knobs are kept for API parity and recorded on the program.
    """

    def __init__(self):
        self.mode = "collective"          # or "local_sgd"
        self.nccl_comm_num = 1
        self.use_local_sgd = False
        self.local_sgd_steps = 1
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.fuse_all_reduce_ops = True   # XLA fuses; parity knob
        self.forward_recompute = False
        self.recompute_checkpoints = []


class Fleet:
    """Singleton facade (ref fleet_base.py:38 Fleet)."""

    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._is_initialized = False
        self._strategy: Optional[DistributedStrategy] = None

    # -- lifecycle -----------------------------------------------------------
    def init(self, role_maker: Optional[RoleMakerBase] = None):
        self._role_maker = role_maker or \
            PaddleCloudRoleMaker(is_collective=True)
        self._role_maker.generate_role()
        if self._role_maker.is_worker() and self._role_maker.worker_num() > 1:
            # multi-host: bring up the coordination service (≈ gen_nccl_id)
            init_parallel_env()
        self._is_initialized = True

    def _assert_init(self):
        if not self._is_initialized:
            raise RuntimeError("call fleet.init(role_maker) first "
                               "(ref fleet_base.py:268)")

    # -- role queries ---------------------------------------------------------
    def is_worker(self):
        self._assert_init()
        return self._role_maker.is_worker()

    def is_server(self):
        self._assert_init()
        return self._role_maker.is_server()

    def is_first_worker(self):
        self._assert_init()
        return self._role_maker.is_first_worker()

    def worker_index(self):
        self._assert_init()
        return self._role_maker.worker_index()

    def worker_num(self):
        self._assert_init()
        return self._role_maker.worker_num()

    def server_num(self):
        self._assert_init()
        return self._role_maker.server_num()

    def worker_endpoints(self, to_string=False):
        self._assert_init()
        eps = self._role_maker.get_trainer_endpoints()
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        self._assert_init()
        eps = self._role_maker.get_pserver_endpoints()
        return ",".join(eps) if to_string else eps

    # -- training surface ------------------------------------------------------
    def distributed_optimizer(self, optimizer,
                              strategy: Optional[DistributedStrategy] = None):
        self._assert_init()
        self._strategy = strategy or DistributedStrategy()
        return CollectiveOptimizer(optimizer, self._strategy, self)

    def init_worker(self):
        pass

    def init_server(self, *args, **kwargs):
        pass

    def run_server(self):
        raise RuntimeError("collective fleet has no servers; use the "
                           "parameter-server fleet for PS mode")

    def stop_worker(self):
        pass

    def barrier_worker(self):
        if self.worker_num() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices("fleet_barrier_worker")

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .. import io
        return io.save_inference_model(dirname, feeded_var_names,
                                       target_vars, executor,
                                       main_program=main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from .. import io
        return io.save_persistables(executor, dirname,
                                    main_program=main_program)


class CollectiveOptimizer:
    """ref ``incubate/fleet/collective/__init__.py:140`` CollectiveOptimizer:
    wraps a regular optimizer; minimize() then rewrites the program with
    the collective transpiler for multi-process data parallelism."""

    def __init__(self, optimizer, strategy, fleet_: Fleet):
        self._optimizer = optimizer
        self._strategy = strategy
        self._fleet = fleet_

    def backward(self, *args, **kwargs):
        return self._optimizer.backward(*args, **kwargs)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        optimize_ops, params_grads = self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
        f = self._fleet
        nranks = f.worker_num()
        if nranks > 1:
            eps = f.worker_endpoints()
            current = eps[f.worker_index()] if f.worker_index() < len(eps) \
                else eps[0]
            cls = LocalSGD if (self._strategy.use_local_sgd or
                               self._strategy.mode == "local_sgd") \
                else GradAllReduce
            cls(self._strategy.nccl_comm_num).transpile(
                startup_program=startup_program,
                main_program=loss.block.program if hasattr(loss, "block")
                else None,
                rank=f.worker_index(), endpoints=",".join(eps),
                current_endpoint=current)
        return optimize_ops, params_grads


fleet = Fleet()


# ---------------------------------------------------------------------------
# parameter-server fleet (ref incubate/fleet/parameter_server/
# distribute_transpiler/__init__.py DistributedTranspiler fleet)
# ---------------------------------------------------------------------------

class TranspilerOptimizer:
    """ref parameter_server/distribute_transpiler __init__.py
    TranspilerOptimizer: minimize() then transpile for PS."""

    def __init__(self, optimizer, strategy, fleet):
        self._optimizer = optimizer
        self._strategy = strategy
        self._fleet = fleet

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set)
        from .ps import DistributeTranspiler, DistributeTranspilerConfig
        cfg = self._strategy if isinstance(
            self._strategy, DistributeTranspilerConfig) else None
        t = DistributeTranspiler(cfg)
        f = self._fleet
        t.transpile(trainer_id=max(f.worker_index(), 0),
                    pservers=f.server_endpoints(to_string=True),
                    trainers=max(f.worker_num(), 1))
        f._transpiler = t
        if f.is_server():
            ep = f._role_maker.get_current_endpoint()
            f._main_program, f._startup_program = t.get_pserver_programs(ep)
        else:
            f._main_program = t.get_trainer_program()
            from ..framework import core
            f._startup_program = core.default_startup_program()
        return result


class PSFleet(Fleet):
    """PS-mode fleet facade: workers train with send/recv programs, servers
    block in run_server() (ref fleet_base + PS fleet impls)."""

    def __init__(self):
        super().__init__()
        self._transpiler = None
        self._main_program = None
        self._startup_program = None

    def init(self, role_maker: Optional[RoleMakerBase] = None):
        # PS mode: trainers are independent processes wired by the RPC
        # plane, not a jax.distributed SPMD group — skip the coordination
        # service (ref: PS fleet never runs gen_nccl_id; that bootstrap
        # belongs to collective mode)
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        self._is_initialized = True

    @property
    def main_program(self):
        return self._main_program

    @property
    def startup_program(self):
        return self._startup_program

    def distributed_optimizer(self, optimizer, strategy=None):
        self._assert_init()
        self._strategy = strategy
        return TranspilerOptimizer(optimizer, strategy, self)

    def init_server(self, *args, **kwargs):
        from ..framework import Executor
        Executor().run(self._startup_program)

    def run_server(self):
        from ..framework import Executor
        Executor().run(self._main_program)     # blocks until STOP

    def stop_worker(self):
        from . import ps as ps_mod
        if self._transpiler is not None:
            for ep in self._transpiler.eps:
                try:
                    ps_mod.get_client(ep).barrier()
                except Exception:
                    pass
        ps_mod.reset_clients()


ps_fleet = PSFleet()
