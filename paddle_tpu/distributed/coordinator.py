"""Socket-based gang coordination: liveness plane + elastic rank recovery.

The file-based :class:`~paddle_tpu.distributed.env.GangRendezvous` (PR 4)
made gang checkpoint commits crash-safe, but it assumes a shared
filesystem, cannot tell a slow rank from a dead one, and gives surviving
ranks no signal at all when a peer is SIGKILLed — they hang inside the
next collective until something external reaps the job.  This module is
the live half of the coordination plane, modeled on the Fluid fleet/PS
endpoint design (every distributed mode there runs through a rank-0
endpoint + heartbeat model, not a shared directory):

- :class:`GangCoordinator` — a TCP server (stdlib sockets only, hosted by
  the launcher or any rank-0 side process) holding the gang's state:
  per-rank heartbeat tables, the committed-step manifest, step barriers,
  and the collective-fingerprint registry.
- :class:`GangClient` — one per rank.  A background thread heartbeats
  ``(rank, committed-step list, current step, collective fingerprint)``
  every ``FLAGS_gang_heartbeat_interval_s``; the same object implements
  the full ``GangRendezvous`` protocol (``announce`` / ``commit_latest``
  / ``wait_commit`` / ``committed_step`` / ``wait_manifest``) over the
  socket, so ``CheckpointDaemon``, ``PreemptionGuard`` and
  ``resume_or_init`` run unchanged on either backend.

Wire protocol
-------------
Length-prefixed JSON frames: a 4-byte big-endian unsigned length followed
by that many bytes of UTF-8 JSON (one object per frame, 16 MiB cap).
Every request carries ``op`` and (usually) ``rank``; every response
carries ``ok``.  Cheap ops ride one persistent connection per client
(serialized by a lock); blocking ops (``wait_commit``, ``wait_ready``,
``step_barrier``, ``wait_manifest``) each open a one-shot connection so a
parked rank's heartbeats and daemon announces never queue behind them.

Liveness
--------
A rank missing heartbeats for ``FLAGS_gang_heartbeat_timeout_s`` is
declared dead: the coordinator marks the gang ``degraded``, wakes every
barrier waiter (they get a ``degraded`` refusal instead of hanging inside
a collective), and reports the dead ranks in every heartbeat response —
survivors observe ``client.degraded``, drain in-flight steps through the
existing ``PreemptionGuard``/``Executor.drain`` machinery, and park in
``client.wait_ready()``.  When the launcher (``--max_restarts``) respawns
the rank, its ``hello`` re-admits it, the gang returns to ``ok``, and the
parked survivors resume.  The manifest protocol is unchanged — the gang
never commits a step past the last all-rank-durable one, so the rejoining
rank's ``resume_or_init`` lands exactly where the survivors' trajectory
is still consistent with it.

Fingerprints
------------
The PR-5 verifier's collective fingerprint rides every heartbeat and
every ``step_barrier`` arrival.  Two ranks disagreeing turn the silent
cross-rank divergence hang into an immediate
:class:`GangFingerprintError` naming both ranks and both fingerprints:
the barrier is refused for everyone, and the passive heartbeat check
latches the mismatch into ``client.check()``.

Durability note: the coordinator keeps gang state in memory (it outlives
any rank when hosted by the launcher).  Pass ``manifest_dir`` to also
persist the ``COMMITTED`` manifest through the same fsync'd-atomic file
the file backend uses, so a full job restart still refuses torn saves.

High availability (PR 18)
-------------------------
A coordinator constructed with ``standby_of="host:port"`` runs as a
WARM STANDBY: it serves read-only ops, and instead of the liveness scan
it runs a mirror loop pulling the primary's replicated log (``repl_sync``
frames over the same socket plane) — the durable events (hello
role/endpoint, announce, manifest publish, goodbye) replay into its own
tables.  When the primary goes silent past ``heartbeat_timeout_s`` the
standby PROMOTES: it bumps the leadership ``epoch``, reloads the shared
``MANIFEST`` file (replication lag must never regress the durable
record), grants every mirrored rank a fresh heartbeat grace, and starts
the liveness scan.  Epoch fencing kills split-brain twice over: every
request/response carries the epoch (a coordinator receiving a NEWER
epoch than its own knows it is a zombie and refuses with ``fenced``),
and the manifest mirror path writes through an ``EPOCH`` file in
``manifest_dir`` — a zombie primary's mirror write observes the
promoted standby's higher fence and is dropped, so the manifest can
never be torn backward across a failover.  Clients accept a
comma-separated multi-address ``PADDLE_GANG_COORD`` and replace the old
fail-loud two-attempt ConnectionError with a bounded, backed-off
re-dial that rotates addresses on transport errors and on
``standby``/``fenced`` refusals.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

from .. import monitor as _monitor
from .env import Env, format_manifest, parse_manifest, _atomic_write

__all__ = [
    "GangCoordinator", "GangClient", "GangDegradedError",
    "GangFingerprintError", "send_frame", "recv_frame",
]

#: one JSON frame may not exceed this (a gang control message is tiny;
#: anything bigger is a protocol error, not a bigger buffer)
MAX_FRAME_BYTES = 16 << 20


class GangDegradedError(RuntimeError):
    """A gang operation was refused because a rank is dead (missed
    ``FLAGS_gang_heartbeat_timeout_s`` of heartbeats).  Survivors should
    drain and park in ``wait_ready()`` until the launcher respawns the
    rank — not retry the refused collective."""

    def __init__(self, msg: str, dead=()):
        super().__init__(msg)
        self.dead = sorted(int(r) for r in dead)


class GangFingerprintError(RuntimeError):
    """Two ranks entered the gang with different collective fingerprints
    (the PR-5 verifier signature over the dependency-ordered collective
    sequence + fetch list).  Without this check the mismatch manifests as
    a cross-rank hang inside the first unpaired collective; with it, the
    step barrier fails immediately, naming both ranks."""


# ---------------------------------------------------------------------------
# framing
# ---------------------------------------------------------------------------

def send_frame(sock: socket.socket, obj: Any) -> None:
    """Serialize ``obj`` as one length-prefixed JSON frame."""
    body = json.dumps(obj, sort_keys=True).encode("utf-8")
    if len(body) > MAX_FRAME_BYTES:
        raise ValueError(
            f"gang frame of {len(body)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte cap")
    sock.sendall(struct.pack(">I", len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("gang peer closed the connection")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> Any:
    """Read one length-prefixed JSON frame (raises ``ConnectionError`` on
    a closed peer, ``ValueError`` on an oversized or malformed frame)."""
    (n,) = struct.unpack(">I", _recv_exact(sock, 4))
    if n > MAX_FRAME_BYTES:
        raise ValueError(
            f"gang frame announces {n} bytes, over the "
            f"{MAX_FRAME_BYTES}-byte cap (corrupt stream?)")
    return json.loads(_recv_exact(sock, n).decode("utf-8"))


# ---------------------------------------------------------------------------
# coordinator (server)
# ---------------------------------------------------------------------------

class GangCoordinator:
    """Rank-0 gang coordinator: heartbeat tables + manifest + barriers.

    Hosted by the launcher (which survives any rank's death — the natural
    place for elastic recovery) or embedded in a rank-0 side thread.  All
    state lives under one condition variable; blocking requests wait on
    it, so a rank death or a barrier release wakes every waiter at once.
    """

    #: how many replicated-log entries the primary retains — a standby
    #: further behind than this gets a full snapshot instead (repl_sync)
    REPL_LOG_KEEP = 512

    #: ops a STANDBY serves (read-only + the replication pull itself);
    #: everything else is refused with ``standby`` so clients rotate to
    #: the primary instead of split-braining state onto the mirror
    _STANDBY_OPS = ("status", "manifest", "repl_sync")

    def __init__(self, world_size: int, host: str = "127.0.0.1",
                 port: int = 0,
                 heartbeat_timeout_s: Optional[float] = None,
                 manifest_dir: Optional[str] = None,
                 standby_of: Optional[str] = None):
        from ..flags import get_flags
        if heartbeat_timeout_s is None:
            heartbeat_timeout_s = float(
                get_flags("FLAGS_gang_heartbeat_timeout_s")
                ["FLAGS_gang_heartbeat_timeout_s"])
        self.world_size = int(world_size)
        self.host = host
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.manifest_dir = manifest_dir
        #: the primary this coordinator mirrors ("host:port"), None for
        #: a primary.  Fixed at construction; the live role is _role.
        self.standby_of = standby_of
        self._requested_port = int(port)
        #: the actually-bound port, set by start() (an ephemeral request
        #: gets a fresh port on every (re)start)
        self.port: Optional[int] = None
        self._cv = threading.Condition(threading.Lock())
        self._ranks: Dict[int, dict] = {}       # guarded-by: _cv
        self._manifest: Optional[int] = None    # guarded-by: _cv
        self._barriers: Dict[int, dict] = {}    # guarded-by: _cv
        self._comm_gates: Dict[int, dict] = {}  # guarded-by: _cv
        #: leadership role + epoch fence (HA): the epoch bumps on every
        #: standby promotion and rides every request/response; the
        #: manifest mirror writes through the EPOCH file against it
        self._role = "standby" if standby_of else "primary"  # guarded-by: _cv
        self._epoch = 0                         # guarded-by: _cv
        #: replicated log of durable events (hello role/endpoint,
        #: announce, manifest publish, goodbye) the standby replays;
        #: _log_base is the seq of _log[0] after pruning
        self._log: List[dict] = []              # guarded-by: _cv
        self._log_seq = 0                       # guarded-by: _cv
        self._log_base = 0                      # guarded-by: _cv
        #: optional scrape surface (FLAGS_coordinator_metrics_port /
        #: start_metrics_http) — stopped with the coordinator
        self._metrics_http = None
        self._mismatch: Optional[dict] = None   # guarded-by: _cv
        #: pluggable status sections (attach_status_section): name ->
        #: zero-arg callable whose snapshot rides status_snapshot() —
        #: how the fleet autoscaler's TGT/SIZE view reaches gangtop
        self._status_sections: Dict[str, Any] = {}  # guarded-by: _cv
        self._stopping = False                  # guarded-by: _cv
        self._conns: List[socket.socket] = []   # guarded-by: _cv
        self._mirror_mu = threading.Lock()      # manifest-file writes
        self._lsock: Optional[socket.socket] = None
        self._threads: List[threading.Thread] = []
        if manifest_dir:
            self._manifest = self._load_manifest(manifest_dir)

    @staticmethod
    def _load_manifest(manifest_dir: str) -> Optional[int]:
        try:
            with open(os.path.join(manifest_dir, "MANIFEST")) as f:
                return parse_manifest(f.read())
        except (OSError, ValueError):
            return None

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "GangCoordinator":
        if self._lsock is not None:
            return self
        with self._cv:
            self._stopping = False      # restartable after stop()
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((self.host, self._requested_port))
        s.listen(128)
        self._lsock = s
        self.port = s.getsockname()[1]
        with self._cv:
            standby = self._role == "standby"
        # a standby runs the mirror loop INSTEAD of the liveness scan
        # (it must not declare anyone dead off tables it only mirrors);
        # promotion starts the liveness thread when it takes over
        loops = ((self._accept_loop, "pt-gang-accept"),
                 (self._mirror_loop, "pt-gang-mirror") if standby
                 else (self._liveness_loop, "pt-gang-liveness"))
        for target, name in loops:
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._threads.append(t)
        return self

    @property
    def address(self) -> str:
        if self.port is None:
            raise RuntimeError("coordinator not started")
        return f"{self.host}:{self.port}"

    def stop(self) -> None:
        http, self._metrics_http = self._metrics_http, None
        if http is not None:
            try:
                http.stop()
            except Exception:
                pass
        with self._cv:
            self._stopping = True
            conns, self._conns = self._conns, []
            self._cv.notify_all()
        if self._lsock is not None:
            # close() alone does NOT wake a thread blocked in accept():
            # the in-flight syscall keeps the LISTEN socket alive in the
            # kernel, which keeps completing handshakes nobody serves —
            # a "stopped" coordinator that still looks connectable hangs
            # dialing clients until timeout instead of refusing fast
            # (the failover ladder in GangClient depends on the refusal)
            try:
                self._lsock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._lsock.close()
            except OSError:
                pass
            self._lsock = None
        for c in conns:
            try:
                c.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads.clear()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    # -- accept / serve ------------------------------------------------------
    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _addr = self._lsock.accept()
            except (OSError, AttributeError):
                return                     # listener closed: shutting down
            with self._cv:
                if self._stopping:
                    conn.close()
                    return
                self._conns.append(conn)
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True, name="pt-gang-conn")
            t.start()

    def _serve(self, conn: socket.socket) -> None:
        from .. import resilience as _resil
        try:
            while True:
                req = recv_frame(conn)
                # chaos site: an injected fault here drops the
                # connection mid-exchange (the client sent a frame and
                # never gets its response — the torn-frame drill); hang
                # mode wedges this one conn's service thread
                _resil.maybe_inject("coordinator.frame")
                try:
                    resp = self._fenced_handle(req)
                except Exception as e:   # a bad request must not kill the
                    resp = {"ok": False,  # coordinator
                            "error": "internal",
                            "detail": repr(e)[:300]}
                # every response carries the leadership epoch + role so
                # clients track the newest leader and fence zombies
                with self._cv:
                    resp.setdefault("epoch", self._epoch)
                    resp.setdefault("role", self._role)
                send_frame(conn, resp)
        except (ConnectionError, OSError, ValueError,
                _resil.InjectedFault):
            pass                           # client went away / bad frame
        finally:
            try:
                conn.close()
            except OSError:
                pass
            with self._cv:
                if conn in self._conns:
                    self._conns.remove(conn)

    # -- state helpers (all hold _cv; the `# guarded-by-caller: _cv`
    # annotations make the lint VERIFY that every same-module call site
    # actually holds it, instead of per-line lint-ok suppressions) -----------
    def _entry_locked(self, rank: int) -> dict:  # guarded-by-caller: _cv
        e = self._ranks.get(rank)
        if e is None:
            # 'step'/'steps' are the DURABLE record — written only by
            # announce (after the rank's checkpoint is fsync-durable),
            # read by commit_latest/wait_commit/peers.  'cur_step' is
            # heartbeat-borne training progress, observability only: a
            # manifest must never commit on the strength of a heartbeat
            # (the step a rank is TRAINING is exactly the step it has
            # not durably saved).
            e = {"alive": True, "finished": False,
                 "last_hb": time.monotonic(),
                 "step": None, "steps": [], "cur_step": None,
                 "hb_steps": [], "fingerprint": None,
                 # latest heartbeat metrics digest (step-time estimate, MFU,
                 # queue, in-flight; byte-capped) — observability only,
                 # like cur_step: never feeds commit decisions
                 "digest": None,
                 "pid": None, "deaths": 0, "joins": 0,
                 # fleet role ("trainer"/"replica"/"router", from hello)
                 # + the serving endpoint a replica registered — the
                 # router's discovery surface, replicated to the standby
                 "role": "trainer", "endpoint": None,
                 # server-side barrier sequence: the k-th step_barrier
                 # arrival of every rank pairs with the k-th of its
                 # peers (see _op_step_barrier)
                 "bseq": 0,
                 # server-side comm-gate sequence (the pre-collective
                 # timestamp exchange pairs the same way)
                 "cseq": 0}
            self._ranks[rank] = e
        return e

    def _touch_locked(self, rank: int, pid: Optional[int] = None,
                      hello: bool = False) -> dict:  # guarded-by-caller: _cv
        """A frame from a live rank refreshes its liveness; a frame from
        a rank previously declared dead is a REJOIN (the elastic path).
        A rank that said goodbye is DEPARTED: only an explicit ``hello``
        (a respawn introducing itself) re-admits it — its trailing
        frames (a final announce, a heartbeat racing the goodbye) must
        not resurrect it into a death sentence at process exit."""
        e = self._entry_locked(rank)
        if e["finished"] and not hello:
            return e
        rejoined = not e["alive"] and not e["finished"]
        e["alive"] = True
        e["finished"] = False
        e["last_hb"] = time.monotonic()
        if pid is not None:
            e["pid"] = int(pid)
        if rejoined:
            # the respawn prunes torn steps before it re-announces, so
            # the pre-death durable record may overstate what is on
            # disk NOW — a manifest committed from it could name a
            # pruned step.  Clear it; the rank re-announces its real
            # post-prune holdings from _resume_gang.
            e["step"] = None
            e["steps"] = []
            e["digest"] = None     # pre-death metrics are stale too
            e["joins"] += 1
            # barrier resync: the respawn's executor restarts its local
            # barrier count while survivors kept counting — reset EVERY
            # rank's server-side sequence (and drop stale barriers) so
            # post-rejoin arrivals pair from zero on both sides.  Safe:
            # any pre-death waiter was already refused with `degraded`
            # (survivors drain and park, they never sit in a barrier
            # across a rejoin).
            for other in self._ranks.values():
                other["bseq"] = 0
                other["cseq"] = 0
            self._barriers.clear()
            self._comm_gates.clear()
            _monitor.GANG_REJOIN_CTR.inc()
            if _monitor.TRACER.enabled:
                _monitor.TRACER.instant(
                    "gang.rejoin", "gang", {"rank": int(rank)})
            if not self._dead_locked():
                _monitor.GANG_DEGRADED_GAUGE.set(0)
            self._cv.notify_all()
        return e

    def _dead_locked(self) -> List[int]:
        """Ranks that went silent WITHOUT an orderly goodbye — a rank
        that finished its work and said goodbye is done, not dead (its
        peers must keep training, not park for a respawn that will
        never come)."""
        return sorted(r for r, e in self._ranks.items()
                      if not e["alive"] and not e["finished"])

    def _status_locked(self) -> str:
        if self._dead_locked():
            return "degraded"
        present = sum(1 for e in self._ranks.values()
                      if e["alive"] or e["finished"])
        return "ok" if present >= self.world_size else "forming"

    def _publish_locked(self, step: int) -> None:  # guarded-by-caller: _cv
        """In-memory commit + waiter wakeup.  The durable file mirror is
        the CALLER's job after releasing ``_cv`` (:meth:`_mirror_manifest`)
        — an fsync inside the one coordinator lock would stall every
        heartbeat, announce, and the liveness scan behind disk I/O."""
        self._manifest = int(step)
        self._log_locked({"ev": "manifest", "step": int(step)})
        self._cv.notify_all()

    def _log_locked(self, entry: dict) -> None:  # guarded-by-caller: _cv
        """Append a durable event to the replicated log (bounded; a
        standby further behind than the retained window re-syncs from a
        full snapshot instead)."""
        self._log.append(dict(entry, seq=self._log_seq))
        self._log_seq += 1
        overflow = len(self._log) - self.REPL_LOG_KEEP
        if overflow > 0:
            del self._log[:overflow]
            self._log_base += overflow

    def _mirror_manifest(self) -> None:
        """Persist the CURRENT manifest to ``manifest_dir`` (same
        fsync'd-atomic file the file backend writes).  Called outside
        the lock; re-reads the step under it, so a racing later publish
        just makes this write the newer step."""
        if not self.manifest_dir:
            return
        with self._cv:
            step = self._manifest
        if step is None:
            return
        # serialize mirror writes: _atomic_write stages to a PER-PROCESS
        # temp name, and two serve threads mirroring concurrently (e.g.
        # a zombie wait_commit waiter racing a fresh commit_latest)
        # would truncate each other's staging file mid-fsync
        with self._cv:
            epoch = self._epoch
        with self._mirror_mu:
            os.makedirs(self.manifest_dir, exist_ok=True)
            # epoch fencing folded into the manifest write path: the
            # EPOCH file is the durable fence token.  A zombie primary
            # (SIGKILL-survivor scheduling delay, partitioned host)
            # reaching this point AFTER a standby promoted observes the
            # newer fence and DROPS its write — the manifest can never
            # be torn backward by a stale leader.
            epath = os.path.join(self.manifest_dir, "EPOCH")
            try:
                with open(epath) as f:
                    fence = int(f.read().strip() or 0)
            except (OSError, ValueError):
                fence = 0
            if fence > epoch:
                _monitor.COORD_FENCED_CTR.inc(1, path="manifest")
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.instant(
                        "gang.manifest_fenced", "gang",
                        {"epoch": epoch, "fence": fence})
                return
            if epoch > fence:
                _atomic_write(epath, f"{epoch}\n")
            _atomic_write(os.path.join(self.manifest_dir, "MANIFEST"),
                          format_manifest(step, self.world_size))

    @staticmethod
    def _gspmd_rules_of(fingerprint) -> Optional[str]:
        """GSPMD rule-table name from a fingerprint's ``#rules=<table>``
        suffix (the verifier's partition fold stamps it) — None for
        unpartitioned programs.  Surfaced per-rank in the status payload
        so gangtop shows a mixed-table gang BEFORE the step-barrier
        refusal fires."""
        f = str(fingerprint) if fingerprint is not None else ""
        return f.split("#rules=", 1)[1] if "#rules=" in f else None

    @staticmethod
    def _find_mismatch(named, where: str) -> Optional[dict]:
        """First disagreeing (rank, fingerprint) pair in a sorted list
        of non-None fingerprints, as a diagnostic record naming both
        ranks — None when all agree.  Shared by the passive heartbeat
        check and the step-barrier refusal; counts the mismatch."""
        if len({f for _, f in named}) <= 1:
            return None
        (r1, f1) = named[0]
        (r2, f2) = next((r, f) for r, f in named[1:] if f != f1)
        detail = (f"collective fingerprint mismatch{where}: "
                  f"rank {r1} reports {f1!r} but rank {r2} "
                  f"reports {f2!r} — divergent programs would "
                  "deadlock inside the first unpaired collective")
        # GSPMD-partitioned fingerprints carry a "#rules=<table>" suffix
        # (verifier partition fold): when both sides have one, name the
        # rule tables outright — "mp_hidden vs replicated" is actionable
        # in a way two hex digests are not
        t1, t2 = (f.split("#rules=", 1)[1] if "#rules=" in str(f) else None
                  for f in (f1, f2))
        if t1 is not None and t2 is not None and t1 != t2:
            detail += (f" (divergent GSPMD rule tables: rank {r1} "
                       f"chose {t1!r}, rank {r2} chose {t2!r})")
        elif t1 is not None and t2 is not None:
            # same rule-table NAME but still divergent: compare the
            # "#resh=<edges>x<sha8>" reshard-plan tokens (the sharding
            # analysis's traffic multiset) — two ranks running the same
            # table over different programs are named by PLAN, so the
            # operator sees "24x1a2b3c4d vs 30x5e6f7a8b" instead of two
            # opaque digests
            p1, p2 = (f.split("#resh=", 1)[1].split("#", 1)[0]
                      if "#resh=" in str(f) else None for f in (f1, f2))
            if p1 is not None and p2 is not None and p1 != p2:
                detail += (f" (same rule table {t1!r} but divergent "
                           f"GSPMD reshard plans: rank {r1} plans "
                           f"{p1}, rank {r2} plans {p2} — the programs "
                           "move different collective traffic)")
        mm = {"ranks": [int(r1), int(r2)],
              "fingerprints": [f1, f2],
              "detail": detail}
        _monitor.GANG_FP_CTR.inc()
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant("gang.fingerprint_mismatch", "gang",
                                    dict(mm))
        return mm

    def _check_fingerprints_locked(self) -> None:  # guarded-by-caller: _cv
        """Passive cross-rank fingerprint exchange: latch the first pair
        of live ranks whose heartbeat fingerprints disagree.  The barrier
        path enforces; this path makes the mismatch visible in every
        heartbeat response (``client.check()``)."""
        named = sorted((r, e["fingerprint"])
                       for r, e in self._ranks.items()
                       if e["alive"] and e["fingerprint"] is not None)
        if len({f for _, f in named}) <= 1:
            self._mismatch = None
            return
        if self._mismatch is not None:
            return
        self._mismatch = self._find_mismatch(named, "")
        self._cv.notify_all()

    def _gang_view_locked(self) -> dict:
        return {"status": self._status_locked(),
                "dead": self._dead_locked(),
                "manifest": self._manifest,
                "mismatch": self._mismatch}

    # -- liveness scan -------------------------------------------------------
    def _liveness_loop(self) -> None:
        poll = max(min(self.heartbeat_timeout_s / 4.0, 0.5), 0.02)
        while True:
            newly_dead: List[int] = []
            with self._cv:
                if self._stopping:
                    return
                now = time.monotonic()
                for r, e in self._ranks.items():
                    if e["alive"] and not e["finished"] and \
                            now - e["last_hb"] > self.heartbeat_timeout_s:
                        e["alive"] = False
                        e["deaths"] += 1
                        newly_dead.append(r)
                if newly_dead:
                    # wake barrier/ready waiters: survivors must get the
                    # degraded refusal NOW, not at their next timeout
                    self._cv.notify_all()
                self._cv.wait(timeout=poll)
            for r in newly_dead:
                _monitor.GANG_DEATH_CTR.inc()
                _monitor.GANG_DEGRADED_GAUGE.set(1)
                # a dead rank's digest series retire (counter totals
                # fold to rank="retired", gauges drop — PR-2 semantics);
                # the aggregate skew/straggler gauges recompute over the
                # survivors only
                _monitor.retire_gang_rank_series(r)
                if _monitor.TRACER.enabled:
                    _monitor.TRACER.instant(
                        "gang.rank_dead", "gang",
                        {"rank": int(r),
                         "timeout_s": self.heartbeat_timeout_s})
            if newly_dead:
                self._refresh_gang_gauges()

    # -- standby mirror / promotion ------------------------------------------
    def _mirror_loop(self) -> None:
        """Standby-side replication: poll the primary's ``repl_sync`` op
        over a one-shot connection, absorb the snapshot/entry stream,
        and promote when the primary stays silent past the heartbeat
        timeout (the same staleness budget ranks get)."""
        poll = max(min(self.heartbeat_timeout_s / 4.0, 0.5), 0.05)
        since = 0
        peer_epoch = 0
        last_ok = time.monotonic()
        host, _, port = str(self.standby_of).rpartition(":")
        while True:
            with self._cv:
                if self._stopping or self._role != "standby":
                    return
            try:
                with socket.create_connection((host, int(port)),
                                              timeout=poll * 2) as s:
                    send_frame(s, {"op": "repl_sync", "since": since})
                    resp = recv_frame(s)
                if isinstance(resp, dict) and resp.get("ok"):
                    since = self._absorb_repl(resp)
                    pe = resp.get("epoch")
                    if isinstance(pe, int) and not isinstance(pe, bool):
                        peer_epoch = max(peer_epoch, pe)
                    last_ok = time.monotonic()
            except (OSError, ConnectionError, ValueError):
                pass                       # primary unreachable this round
            if time.monotonic() - last_ok > self.heartbeat_timeout_s:
                self._promote(peer_epoch)
                return
            with self._cv:
                if self._stopping:
                    return
                self._cv.wait(timeout=poll)

    def _absorb_repl(self, resp: dict) -> int:
        """Fold a ``repl_sync`` response into the local tables; returns
        the next log cursor.  Snapshot responses rebuild the rank table
        wholesale; entry responses replay the durable event stream."""
        with self._cv:
            snap = resp.get("snapshot")
            if isinstance(snap, dict):
                mf = snap.get("manifest")
                if mf is not None:
                    self._manifest = (mf if self._manifest is None
                                      else max(self._manifest, int(mf)))
                for r, d in (snap.get("ranks") or {}).items():
                    e = self._entry_locked(int(r))
                    e["step"] = d.get("step")
                    e["steps"] = list(d.get("steps") or [])
                    e["role"] = d.get("role") or e["role"]
                    e["endpoint"] = d.get("endpoint")
                    e["pid"] = d.get("pid")
            for entry in resp.get("entries") or ():
                if isinstance(entry, dict):
                    self._apply_repl_locked(entry)
            return int(resp.get("next") or 0)

    def _apply_repl_locked(self, entry: dict) -> None:  # guarded-by-caller: _cv
        ev = entry.get("ev")
        if ev == "hello":
            e = self._entry_locked(int(entry["rank"]))
            e["pid"] = entry.get("pid")
            e["role"] = entry.get("role") or e["role"]
            e["endpoint"] = entry.get("endpoint")
        elif ev == "announce":
            e = self._entry_locked(int(entry["rank"]))
            e["step"] = entry.get("step")
            e["steps"] = list(entry.get("steps") or [])
        elif ev == "manifest":
            step = int(entry["step"])
            self._manifest = (step if self._manifest is None
                              else max(self._manifest, step))
        elif ev == "goodbye":
            e = self._entry_locked(int(entry["rank"]))
            e["alive"] = False
            e["finished"] = True

    def _promote(self, peer_epoch: int) -> None:
        """Standby → primary takeover.  Epoch-fenced: the new epoch
        strictly exceeds anything the old primary could have stamped, so
        a zombie survivor is refused at both the frame layer (clients
        carry the newer epoch) and the manifest write path (EPOCH file).
        Ranks get a fresh heartbeat grace window — the standby only
        mirrored their liveness, it never measured it."""
        t0 = time.monotonic()
        with self._cv:
            if self._stopping or self._role != "standby":
                return
            self._role = "primary"
            self._epoch = max(self._epoch, int(peer_epoch)) + 1
            epoch = self._epoch
            now = time.monotonic()
            for e in self._ranks.values():
                e["last_hb"] = now
                if not e["finished"]:
                    e["alive"] = True
            self._cv.notify_all()
        # durable catch-up: both coordinators share manifest_dir, and the
        # replication stream may lag the primary's last fsync — the
        # on-disk record must never regress across a failover
        if self.manifest_dir:
            disk = self._load_manifest(self.manifest_dir)
            if disk is not None:
                with self._cv:
                    self._manifest = (disk if self._manifest is None
                                      else max(self._manifest, disk))
        _monitor.COORD_FAILOVER_CTR.inc()
        _monitor.COORD_EPOCH_GAUGE.set(epoch)
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant("gang.coord_failover", "gang",
                                    {"epoch": epoch})
        self._mirror_manifest()            # stamps the EPOCH fence token
        t = threading.Thread(target=self._liveness_loop, daemon=True,
                             name="pt-gang-liveness")
        t.start()
        self._threads.append(t)
        _monitor.FLEET_FAILOVER_HIST.observe(
            (time.monotonic() - t0) * 1e3)

    def _op_repl_sync(self, req: dict) -> dict:
        since = int(req.get("since", 0))
        with self._cv:
            if since < self._log_base:
                # cursor fell off the bounded log — full snapshot resync
                ranks = {str(r): {"step": e["step"],
                                  "steps": list(e["steps"]),
                                  "role": e["role"],
                                  "endpoint": e["endpoint"],
                                  "pid": e["pid"]}
                         for r, e in self._ranks.items()}
                return {"ok": True, "next": self._log_seq,
                        "snapshot": {"manifest": self._manifest,
                                     "ranks": ranks}}
            return {"ok": True, "next": self._log_seq,
                    "entries": list(self._log[since - self._log_base:])}

    # -- request dispatch ----------------------------------------------------
    def _fenced_handle(self, req: dict) -> dict:
        """Epoch fence + standby gate in front of the op table.  A
        request carrying a NEWER epoch than ours proves a newer leader
        exists — this coordinator is a zombie and must refuse (the
        client rotates to the real primary); a standby refuses every
        state-mutating op the same way."""
        op = req.get("op")
        peer_epoch = req.get("epoch")
        with self._cv:
            epoch, role = self._epoch, self._role
        if isinstance(peer_epoch, int) and not isinstance(peer_epoch, bool) \
                and peer_epoch > epoch:
            _monitor.COORD_FENCED_CTR.inc(1, path="frame")
            return {"ok": False, "error": "fenced", "epoch": epoch}
        if role == "standby" and op not in self._STANDBY_OPS:
            return {"ok": False, "error": "standby",
                    "primary": self.standby_of, "epoch": epoch}
        return self._handle(req)

    def _handle(self, req: dict) -> dict:
        op = req.get("op")
        fn = getattr(self, f"_op_{op}", None)
        if fn is None:
            return {"ok": False, "error": "unknown_op", "detail": str(op)}
        return fn(req)

    def _op_hello(self, req: dict) -> dict:
        with self._cv:
            e = self._touch_locked(int(req["rank"]), pid=req.get("pid"),
                                   hello=True)
            if e["joins"] == 0:
                e["joins"] = 1
            if req.get("role"):
                e["role"] = str(req["role"])
            if req.get("endpoint"):
                e["endpoint"] = str(req["endpoint"])
            self._log_locked({"ev": "hello", "rank": int(req["rank"]),
                              "pid": e["pid"], "role": e["role"],
                              "endpoint": e["endpoint"]})
            return {"ok": True, "world_size": self.world_size,
                    **self._gang_view_locked()}

    def _op_heartbeat(self, req: dict) -> dict:
        rank = int(req["rank"])
        digest = req.get("digest")
        digest_ok = False
        digest_capped = False
        if isinstance(digest, dict):
            # server-side byte-cap enforcement: an oversized digest is
            # CAPPED with the same priority-ordered key dropping the
            # client applies (counted; the beat itself always refreshes
            # liveness) — refusing the whole digest would blind the
            # skew/straggler/NaN plane to exactly the rank whose client
            # mis-sized its payload
            if len(json.dumps(digest, sort_keys=True)) \
                    > _monitor.DIGEST_MAX_BYTES:
                digest = _monitor.capped_digest(digest) or None
                digest_capped = True
            digest_ok = digest is not None
        else:
            # a beat WITHOUT a digest CLEARS the stored one: a rank
            # whose executor retired (metrics_digest() now empty) must
            # drop out of straggler/skew math, not haunt it with its
            # last reading forever.  Old digest-less clients simply
            # keep the field at its initial None.
            digest = None
        with self._cv:
            e = self._touch_locked(rank)
            # heartbeat progress is observability + fingerprint
            # exchange ONLY — the durable step/steps record is
            # announce's to write (see _entry_locked)
            if req.get("step") is not None:
                e["cur_step"] = int(req["step"])
            if req.get("steps") is not None:
                # observability echo of the rank's committed list (the
                # DURABLE record stays announce-only — see _entry_locked)
                e["hb_steps"] = sorted(int(s) for s in req["steps"])
            if req.get("fingerprint") is not None:
                # never let a fingerprint-less beat (another client in
                # the same process, a rank before its first verify)
                # erase a known fingerprint — that would un-latch a
                # genuine mismatch between beats
                e["fingerprint"] = req["fingerprint"]
            digest_changed = e["digest"] != digest
            e["digest"] = digest
            self._check_fingerprints_locked()
            view = self._gang_view_locked()
        _monitor.GANG_HB_CTR.inc(1, role="coordinator")
        if digest_capped:
            _monitor.GANG_DIGEST_OVERSIZE_CTR.inc()
        if digest_ok:
            self._fold_digest(rank, digest)
        if req.get("step") is not None or digest_changed:
            self._refresh_gang_gauges()
        return {"ok": True, **view}

    #: digest key -> the per-rank gauge family it lands in.  The
    #: serving keys (srv_q/occ/slots/tps) are the per-replica load
    #: signal the fleet router/autoscaler consumes — published here so
    #: the coordinator host's /metrics (or file export) carries the
    #: whole fleet's serving load.
    _DIGEST_GAUGES = {
        "step_ms": _monitor.GANG_RANK_STEP_MS,
        "mfu": _monitor.GANG_RANK_MFU,
        "queue": _monitor.GANG_RANK_QUEUE,
        "inflight": _monitor.GANG_RANK_INFLIGHT,
        "srv_q": _monitor.GANG_RANK_SRVQ,
        "occ": _monitor.GANG_RANK_OCC,
        "slots": _monitor.GANG_RANK_FREE_SLOTS,
        "tps": _monitor.GANG_RANK_TPS,
        # numerics plane: grad-norm + cumulative non-finite count — the
        # "which rank is NaN'ing" columns gangtop renders
        "gnorm": _monitor.GANG_RANK_GNORM,
        "nanf": _monitor.GANG_RANK_NANF,
        # comms plane: per-step measured comm time (wait + wire), its
        # straggler-wait part, and the bus-bandwidth gauge — gangtop's
        # COMM/BW% columns, and comm_wait feeds the net-of-wait
        # straggler selection below
        "comm_ms": _monitor.GANG_RANK_COMM_MS,
        "comm_wait": _monitor.GANG_RANK_COMM_WAIT,
        "comm_bw": _monitor.GANG_RANK_COMM_BW,
        # hbm plane: measured live bytes + headroom (budget - live) —
        # gangtop's HBM/HDRM% columns and OOM-RISK flag, and the
        # fleet-wide headroom surface the GSPMD sharding chooser and an
        # autoscaler consume
        "hbm": _monitor.GANG_RANK_HBM,
        "hdrm": _monitor.GANG_RANK_HDRM,
    }

    def _fold_digest(self, rank: int, digest: dict) -> None:
        """Per-rank digest values → per-rank registry series (exported
        by monitor.export on the coordinator host).  Runs OUTSIDE _cv:
        gauge cells have their own locks, and metric work must never
        stall the liveness scan."""
        _monitor.GANG_DIGEST_CTR.inc(1, rank=str(rank))
        for key, fam in self._DIGEST_GAUGES.items():
            v = digest.get(key)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                fam.set(float(v), rank=str(rank))
            else:
                # the key stopped riding the digest (server stopped, or
                # shed under the byte cap): DROP the rank's series — a
                # frozen last value would read as live load to a router
                # doing least-loaded placement on it
                fam.fold({"rank": str(rank)}, None)

    def _aggregates_locked(self) -> dict:  # guarded-by-caller: _cv
        """Gang-level aggregates over the LIVE ranks' heartbeat state —
        the ONE place the selection rules live (gauges, the status
        payload, and therefore gangtop all read this).  Degraded-aware
        by construction: dead and departed ranks drop out of the
        snapshot, so a degraded gang's skew reflects only the
        survivors still training.  A straggler is a COMPARISON — with
        fewer than two live digests there is nobody to be slower than,
        and the skews reset to 0 rather than freeze (a gauge latched
        at its pre-death maximum would keep an alert firing against a
        healthy solo survivor forever)."""
        live = {r: e for r, e in self._ranks.items()
                if e["alive"] and not e["finished"]}
        steps = [e["cur_step"] for e in live.values()
                 if e["cur_step"] is not None]
        step_ms = {r: e["digest"]["step_ms"]
                   for r, e in live.items()
                   if isinstance(e.get("digest"), dict)
                   and isinstance(e["digest"].get("step_ms"),
                                  (int, float))}
        # straggler selection is NET of comm wait (digest 'comm_wait',
        # the comms plane's measured peer-arrival skew): a rank whose
        # step is long because it sat WAITING for a slow peer is the
        # victim, not the straggler — blaming it would point the
        # runbook at exactly the wrong chip
        def _net(r):
            d = live[r].get("digest") or {}
            w = d.get("comm_wait")
            if isinstance(w, (int, float)) and not isinstance(w, bool):
                return max(float(step_ms[r]) - float(w), 0.0)
            return float(step_ms[r])
        agg = {"step_skew": (max(steps) - min(steps)
                             if len(steps) >= 2 else 0),
               "step_time_skew_ms": 0.0,
               "straggler": -1, "straggler_step_ms": 0.0}
        if len(step_ms) >= 2:
            slow = max(step_ms, key=_net)
            agg["straggler"] = int(slow)
            agg["straggler_step_ms"] = float(step_ms[slow])
            agg["straggler_net_ms"] = round(_net(slow), 3)
            agg["step_time_skew_ms"] = \
                max(step_ms.values()) - min(step_ms.values())
        # distinct GSPMD rule tables among live ranks: >1 means the
        # planners diverged and the NEXT step barrier will refuse —
        # surfacing it here makes the condition visible in gangtop /
        # /statusz while the gang is still running
        tables = sorted({t for t in (
            self._gspmd_rules_of(e["fingerprint"]) for e in live.values())
            if t is not None})
        if tables:
            agg["gspmd_rule_tables"] = tables
        return agg

    def _refresh_gang_gauges(self) -> None:
        """Publish the aggregates as registry gauges (exported by
        monitor.export on the coordinator host)."""
        with self._cv:
            agg = self._aggregates_locked()
        _monitor.GANG_STEP_SKEW_GAUGE.set(agg["step_skew"])
        _monitor.GANG_STEP_TIME_SKEW_GAUGE.set(agg["step_time_skew_ms"])
        _monitor.GANG_STRAGGLER_GAUGE.set(agg["straggler"])
        _monitor.GANG_STRAGGLER_MS_GAUGE.set(agg["straggler_step_ms"])

    def _op_announce(self, req: dict) -> dict:
        rank = int(req["rank"])
        with self._cv:
            e = self._touch_locked(rank)
            e["step"] = int(req["step"])
            e["steps"] = sorted(int(s) for s in
                                (req.get("steps") or [req["step"]]))
            self._log_locked({"ev": "announce", "rank": rank,
                              "step": e["step"],
                              "steps": list(e["steps"])})
            # announcements move the wait_commit barrier
            self._cv.notify_all()
        return {"ok": True}

    def _op_goodbye(self, req: dict) -> dict:
        """Orderly departure (clean exit / preemption drain finished):
        the rank stops heartbeating ON PURPOSE.  It is excluded from the
        liveness scan and never degrades the gang — the opposite of a
        SIGKILL, which says nothing and IS a death."""
        with self._cv:
            e = self._entry_locked(int(req["rank"]))
            e["alive"] = False
            e["finished"] = True
            self._log_locked({"ev": "goodbye", "rank": int(req["rank"])})
            if not self._dead_locked():
                # a rank declared dead that then departs cleanly must
                # not leave the degraded gauge latched on a healthy,
                # completed gang (the runbook keys on it)
                _monitor.GANG_DEGRADED_GAUGE.set(0)
            self._cv.notify_all()
        # an orderly departure retires its digest series exactly like a
        # death: the rank is gone either way, and the skew/straggler
        # aggregates must track only the ranks still training
        _monitor.retire_gang_rank_series(int(req["rank"]))
        self._refresh_gang_gauges()
        return {"ok": True}

    def _op_peers(self, req: dict) -> dict:
        with self._cv:
            peers = {int(r): {"step": e["step"], "steps": list(e["steps"])}
                     for r, e in self._ranks.items()
                     if e["step"] is not None}
        return {"ok": True, "peers": {str(r): d for r, d in peers.items()}}

    def _op_manifest(self, req: dict) -> dict:
        with self._cv:
            return {"ok": True, "step": self._manifest}

    def _op_publish(self, req: dict) -> dict:
        if int(req["rank"]) != 0:
            return {"ok": False, "error": "not_leader",
                    "detail": f"rank {req['rank']} tried to publish the "
                              "gang manifest; only rank 0 commits"}
        with self._cv:
            self._publish_locked(int(req["step"]))
        self._mirror_manifest()
        return {"ok": True}

    def _op_commit_latest(self, req: dict) -> dict:
        """Non-blocking steady-state commit: publish the newest step every
        rank has durably announced (dead ranks count with their LAST
        announcement — what they durably hold on disk is exactly what
        they last announced), if it advances the manifest."""
        if int(req["rank"]) != 0:
            return {"ok": True, "published": None}
        published = None
        with self._cv:
            if len([e for e in self._ranks.values() if e["steps"]]) \
                    >= self.world_size:
                common = None
                for e in self._ranks.values():
                    s = set(e["steps"])
                    common = s if common is None else (common & s)
                if common:
                    best = max(common)
                    if self._manifest is None or best > self._manifest:
                        self._publish_locked(best)
                        published = best
        if published is not None:
            self._mirror_manifest()
        return {"ok": True, "published": published}

    def _op_wait_commit(self, req: dict) -> dict:
        """Blocking emergency barrier: wait until every rank's LATEST
        announced step equals ``step``, then publish (strict equality —
        the file backend's contract)."""
        if int(req["rank"]) != 0:
            return {"ok": False, "error": "not_leader",
                    "detail": "wait_commit is leader-only"}
        step = int(req["step"])
        deadline = time.monotonic() + float(req.get("timeout_s", 30.0))
        committed = False
        with self._cv:
            while True:
                anns = [e for e in self._ranks.values()
                        if e["step"] is not None]
                if len(anns) >= self.world_size and \
                        all(e["step"] == step for e in anns):
                    self._publish_locked(step)
                    committed = True
                    break
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._cv.wait(timeout=min(left, 0.25))
        if committed:
            self._mirror_manifest()
        return {"ok": True, "committed": committed}

    def _op_wait_manifest(self, req: dict) -> dict:
        step = int(req["step"])
        deadline = time.monotonic() + float(req.get("timeout_s", 30.0))
        with self._cv:
            while True:
                if self._manifest is not None and self._manifest >= step:
                    return {"ok": True, "reached": True}
                left = deadline - time.monotonic()
                if left <= 0:
                    return {"ok": True, "reached": False}
                self._cv.wait(timeout=min(left, 0.25))

    def _op_wait_ready(self, req: dict) -> dict:
        """Park until the whole gang is alive (the elastic rejoin
        barrier) — or report the still-dead ranks at the deadline."""
        deadline = time.monotonic() + float(req.get("timeout_s", 300.0))
        with self._cv:
            while True:
                if self._status_locked() == "ok":
                    return {"ok": True, "ready": True}
                left = deadline - time.monotonic()
                if left <= 0:
                    return {"ok": True, "ready": False,
                            "dead": self._dead_locked()}
                self._cv.wait(timeout=min(left, 0.25))

    def _op_step_barrier(self, req: dict) -> dict:
        """Per-step gang barrier with fingerprint enforcement: released
        only when every rank arrived with the SAME collective
        fingerprint.  A mismatch refuses the barrier for everyone,
        naming both ranks; a dead rank refuses it with ``degraded``
        (survivors park instead of hanging inside a collective).

        Pairing is by SERVER-SIDE arrival order, not the client's step
        value: each rank's k-th arrival pairs with every peer's k-th.
        A client-supplied key would desynchronize after an elastic
        respawn (the fresh process's executor restarts its local count
        while survivors kept counting — every barrier would then time
        out); the rejoin path resets all sequences to re-pair from
        zero, and the client's ``step`` stays in the diagnostics."""
        rank = int(req["rank"])
        step = int(req["step"])
        fp = req.get("fingerprint")
        deadline = time.monotonic() + float(req.get("timeout_s", 60.0))
        with self._cv:
            e = self._touch_locked(rank)
            seq = e["bseq"]
            e["bseq"] = seq + 1
            b = self._barriers.setdefault(
                seq, {"fps": {}, "error": None})
            b["fps"][rank] = fp
            if b["error"] is None:
                named = sorted((r, f) for r, f in b["fps"].items()
                               if f is not None)
                mm = self._find_mismatch(
                    named, f" at the step-{step} barrier")
                if mm is not None:
                    b["error"] = f"step {step} barrier refused: " \
                                 + mm["detail"]
            self._cv.notify_all()
            while True:
                if b["error"] is not None:
                    return {"ok": False, "error": "fingerprint",
                            "detail": b["error"]}
                dead = self._dead_locked()
                if dead:
                    return {"ok": False, "error": "degraded",
                            "dead": dead,
                            "detail": f"rank(s) {dead} died while the "
                                      f"gang was at the step-{step} "
                                      "barrier"}
                gone = sorted(r for r, e in self._ranks.items()
                              if e["finished"] and r not in b["fps"])
                if gone:
                    # an orderly departed rank can never arrive: refuse
                    # NOW with the real reason instead of stalling the
                    # full timeout and mis-diagnosing a slow rank
                    return {"ok": False, "error": "degraded",
                            "dead": gone,
                            "detail": f"rank(s) {gone} departed "
                                      "(finished their run) before the "
                                      f"step-{step} barrier; it can "
                                      "never release"}
                if len(b["fps"]) >= self.world_size:
                    for s in [s for s in self._barriers
                              if s < seq - 8]:    # bounded history
                        del self._barriers[s]
                    return {"ok": True, "released": True}
                left = deadline - time.monotonic()
                if left <= 0:
                    # withdraw the un-released arrival so a RETRY pairs
                    # at the same sequence the late peers will reach
                    # (consuming it would leave the gang permanently
                    # off by one); only when this rank hasn't already
                    # arrived at a later barrier concurrently
                    if e["bseq"] == seq + 1:
                        e["bseq"] = seq
                        b["fps"].pop(rank, None)
                    return {"ok": False, "error": "timeout",
                            "detail": f"step {step} barrier timed out "
                                      f"with {len(b['fps'])}/"
                                      f"{self.world_size} ranks arrived"}
                self._cv.wait(timeout=min(left, 0.25))

    def _op_comm_gate(self, req: dict) -> dict:
        """Pre-collective timestamp exchange (the comms-observability
        "timestamp allgather" over the socket plane): each rank posts
        its host wall-clock arrival at the k-th collective launch and
        waits (bounded) for every live peer's, so each rank can
        decompose the collective's measured wall time into
        straggler-wait (max peer arrival minus its own) vs wire time.

        Pairing is by server-side arrival order, exactly like
        ``_op_step_barrier`` (and reset with it on an elastic rejoin).
        Unlike the barrier this op NEVER refuses: telemetry must not
        fail a step — a timeout, a dead or departed peer just returns
        the partial timestamp view (``released=False``), and a timed-out
        arrival is withdrawn so a retry re-pairs at the same sequence."""
        rank = int(req["rank"])
        ts = float(req["ts"])
        deadline = time.monotonic() + float(req.get("timeout_s", 10.0))
        with self._cv:
            e = self._touch_locked(rank)
            seq = e["cseq"]
            e["cseq"] = seq + 1
            g = self._comm_gates.setdefault(seq, {"ts": {}})
            g["ts"][rank] = ts
            # bounded history pruned on ENTRY, not on release: the
            # partial-return paths below (dead peer, timeout) are the
            # steady state of a degraded-but-running gang, and pruning
            # only on full release would leak one entry per collective
            # step forever.  A straggler later arriving at a pruned seq
            # just re-creates it and gets a partial view — the same
            # contract a timeout gives it.
            for s in [s for s in self._comm_gates if s < seq - 8]:
                del self._comm_gates[s]
            self._cv.notify_all()
            while True:
                view = {str(r): t for r, t in g["ts"].items()}
                if len(g["ts"]) >= self.world_size:
                    return {"ok": True, "released": True, "ts": view}
                blocked = sorted(
                    r for r, o in self._ranks.items()
                    if r not in g["ts"]
                    and (not o["alive"] or o["finished"]))
                if blocked:
                    # a dead/departed peer can never arrive: return the
                    # partial view NOW instead of stalling the step for
                    # the whole timeout
                    return {"ok": True, "released": False, "ts": view,
                            "missing": blocked}
                left = deadline - time.monotonic()
                if left <= 0:
                    # withdraw the un-released arrival so a retry pairs
                    # at the sequence the late peers will reach (the
                    # step-barrier discipline)
                    if e["cseq"] == seq + 1:
                        e["cseq"] = seq
                        g["ts"].pop(rank, None)
                    return {"ok": True, "released": False, "ts": view}
                self._cv.wait(timeout=min(left, 0.25))

    def status_snapshot(self) -> dict:
        """The full gang view (rank table + aggregates) — one payload
        shared by the ``status`` socket op, gangtop, and the
        ``/statusz`` scrape endpoint, so the three can never disagree."""
        with self._cv:
            ranks = {str(r): {"alive": e["alive"],
                              "finished": e["finished"],
                              "step": e["step"],
                              "steps": list(e["steps"]),
                              "cur_step": e["cur_step"],
                              "hb_steps": list(e["hb_steps"]),
                              "fingerprint": e["fingerprint"],
                              "gspmd_rules": self._gspmd_rules_of(
                                  e["fingerprint"]),
                              "digest": (dict(e["digest"])
                                         if e["digest"] else None),
                              "pid": e["pid"], "deaths": e["deaths"],
                              "joins": e["joins"],
                              "role": e["role"],
                              "endpoint": e["endpoint"],
                              "age_s": round(
                                  time.monotonic() - e["last_hb"], 3)}
                     for r, e in self._ranks.items()}
            out = {"ranks": ranks,
                   "aggregates": self._aggregates_locked(),
                   "epoch": self._epoch,
                   "coord_role": self._role,
                   **self._gang_view_locked()}
            sections = dict(self._status_sections)
        # section callables run OUTSIDE _cv: they take their own locks
        # (the autoscaler's status() does), and a status scrape must
        # never be able to deadlock the coordination plane
        for name, fn in sections.items():
            try:
                out[name] = fn()
            except Exception as e:   # a broken section must not break
                out[name] = {"error": repr(e)[:200]}   # the whole view
        return out

    def attach_status_section(self, name: str, fn) -> None:
        """Register a zero-arg callable whose dict snapshot appears as
        ``name`` in every ``status_snapshot()`` (and hence the status
        socket op, ``/statusz``, and gangtop).  Re-attaching a name
        replaces it; the fleet autoscaler attaches as ``autoscaler``."""
        with self._cv:
            self._status_sections[str(name)] = fn

    def _op_status(self, req: dict) -> dict:
        return {"ok": True, **self.status_snapshot()}

    # -- scrape surface ------------------------------------------------------
    def start_metrics_http(self, port: int, host: str = "0.0.0.0"):
        """Serve ``/metrics`` ``/healthz`` ``/statusz`` off this
        coordinator's process registry (the launcher folds every rank's
        heartbeat digest into per-rank gauges here, so one scrape covers
        the whole gang — no serving stack required).  Reuses the serving
        plane's :class:`~paddle_tpu.serving.httpd.MetricsHTTPServer`;
        ``/healthz`` answers 503 while the gang is degraded, so the same
        probe a load balancer uses works for a training gang.  Stopped
        with the coordinator."""
        from ..serving.httpd import MetricsHTTPServer

        def health():
            with self._cv:
                status = self._status_locked()
            return status != "degraded", status

        self._metrics_http = MetricsHTTPServer(
            port=int(port), host=host, health_fn=health,
            status_fn=self.status_snapshot).start()
        return self._metrics_http


# ---------------------------------------------------------------------------
# client (one per rank; GangRendezvous-compatible)
# ---------------------------------------------------------------------------

class GangClient:
    """A rank's connection to the :class:`GangCoordinator`.

    Implements the same protocol surface as the file-based
    :class:`~paddle_tpu.distributed.env.GangRendezvous` (so the
    checkpoint daemon, the preemption guard, and ``resume_or_init`` are
    backend-agnostic) plus the liveness plane: a heartbeat thread, the
    ``degraded``/``dead_ranks`` view, ``wait_ready`` parking, and the
    fingerprint-enforcing ``step_barrier``.
    """

    backend = "socket"

    def __init__(self, address: Optional[str] = None,
                 rank: Optional[int] = None,
                 world_size: Optional[int] = None,
                 heartbeat_interval_s: Optional[float] = None,
                 role: str = "trainer",
                 endpoint: Optional[str] = None):
        from ..flags import get_flags
        env = Env()
        address = address or os.getenv("PADDLE_GANG_COORD", "")
        if not address or ":" not in address:
            raise ValueError(
                f"gang coordinator address {address!r} is not host:port "
                "(set PADDLE_GANG_COORD or pass address=)")
        # comma-separated address list: primary first, warm standby
        # after (launch.py exports both when --coordinator_standby);
        # the client rotates through them on redial
        self._addrs: List[tuple] = []
        for a in address.split(","):
            a = a.strip()
            if not a:
                continue
            host, _, port = a.rpartition(":")
            self._addrs.append((host, int(port)))
        self.address = address
        self._host, self._port = self._addrs[0]
        self.role = str(role)
        self.endpoint = endpoint
        self.rank = env.rank if rank is None else int(rank)
        self.world_size = env.world_size if world_size is None \
            else int(world_size)
        if heartbeat_interval_s is None:
            heartbeat_interval_s = float(
                get_flags("FLAGS_gang_heartbeat_interval_s")
                ["FLAGS_gang_heartbeat_interval_s"])
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self._mu = threading.Lock()
        self._sock: Optional[socket.socket] = None  # guarded-by: _mu
        self._state_mu = threading.Lock()
        self._progress: Dict[str, Any] = {          # guarded-by: _state_mu
            "step": None, "steps": [], "fingerprint": None}
        self._view: Dict[str, Any] = {              # guarded-by: _state_mu
            "status": "forming", "dead": [], "manifest": None,
            "mismatch": None}
        self._hb_stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        #: the heartbeat thread's live socket, mirrored here so close()
        #: can interrupt a blocking send/recv (a beat mid-flight when
        #: the client is closed would otherwise outlive the 2 s join —
        #: and a zombie beat can re-dial an EPHEMERAL PORT a newer
        #: coordinator has since reused, injecting a stale rank entry
        #: into a foreign gang: the in-suite flake PR 9 noted)
        self._hb_sock: Optional[socket.socket] = None  # guarded-by: _state_mu
        #: which of _addrs the next dial targets — advanced by
        #: _rotate_addr when the current coordinator is unreachable,
        #: a standby, or fenced
        self._addr_idx = 0                # guarded-by: _state_mu
        #: highest leadership epoch observed in any response; stamped
        #: into every request so a zombie primary fences itself
        self._seen_epoch = 0              # guarded-by: _state_mu
        # bounded redial budget per RPC: enough to visit every address
        # twice plus a grace attempt (failover completes within one
        # backoff ladder instead of failing loud on the first drop)
        self._redial_attempts = max(4, 2 * len(self._addrs) + 1)
        self._degraded_noted = False
        #: None = auto-collect monitor.metrics_digest() per beat;
        #: a dict = fixed override (tests, foreign runners)
        self._digest_override: Optional[Dict[str, Any]] = None  # guarded-by: _state_mu

    # -- connection plumbing -------------------------------------------------
    def _dial(self, timeout_s: float = 10.0) -> socket.socket:
        with self._state_mu:
            host, port = self._addrs[self._addr_idx]
        s = socket.create_connection((host, port), timeout=timeout_s)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return s

    def _rotate_addr(self) -> None:
        """Advance the dial target to the next coordinator address
        (no-op with a single address — the redial loop just re-dials)."""
        with self._state_mu:
            if len(self._addrs) > 1:
                self._addr_idx = (self._addr_idx + 1) % len(self._addrs)

    def _absorb_epoch(self, resp: dict) -> Optional[dict]:
        """Track the newest leadership epoch; map the two failover
        refusals (``standby``, ``fenced``) to ``None`` so the caller
        rotates and retries instead of raising them at the user."""
        ep = resp.get("epoch")
        if isinstance(ep, int) and not isinstance(ep, bool):
            with self._state_mu:
                if ep > self._seen_epoch:
                    self._seen_epoch = ep
        if not resp.get("ok") and resp.get("error") in ("standby", "fenced"):
            return None
        return resp

    def _rpc(self, req: dict, timeout_s: float = 30.0,
             oneshot: bool = False) -> dict:
        """One request/response with bounded failover.  Cheap ops share
        the persistent connection (lock-serialized); blocking ops
        (``oneshot=True``) dial their own so a parked ``wait_ready``
        never queues the daemon's announces or the heartbeat behind it.
        Transport errors and standby/fenced refusals redial through the
        address list on a deterministic backoff ladder (PR-3 engine)
        before the fail-loud ConnectionError — long enough for a warm
        standby to promote, short enough that a truly dead plane still
        fails fast."""
        req = dict(req)
        req.setdefault("rank", self.rank)
        with self._state_mu:
            req.setdefault("epoch", self._seen_epoch)
        if oneshot:
            return self._failover_oneshot(req, timeout_s)
        with self._mu:
            return self._failover_persistent(req, timeout_s)

    def _failover_oneshot(self, req: dict, timeout_s: float) -> dict:
        from .. import resilience as _resil
        delays = _resil.backoff_schedule(
            self._redial_attempts, base_delay_s=0.05, max_delay_s=1.0,
            seed=zlib.crc32(b"gang.oneshot") & 0xFFFFFFFF)
        last: Optional[BaseException] = None
        t_fail: Optional[float] = None
        for attempt in range(self._redial_attempts):
            try:
                s = self._dial()
                try:
                    s.settimeout(timeout_s)
                    send_frame(s, req)
                    resp = self._absorb_epoch(recv_frame(s))
                finally:
                    try:
                        s.close()
                    except OSError:
                        pass
                if resp is None:          # standby/fenced: rotate + retry
                    last = ConnectionError("coordinator is standby/fenced")
                    t_fail = t_fail or time.monotonic()
                    self._rotate_addr()
                elif t_fail is not None:
                    self._note_failover(t_fail)
                    return self._checked(resp)
                else:
                    return self._checked(resp)
            except (OSError, ConnectionError, ValueError) as e:
                last = e
                t_fail = t_fail or time.monotonic()
                if attempt >= 1:          # first retry is a free re-dial
                    self._rotate_addr()
            if attempt < self._redial_attempts - 1:
                time.sleep(delays[attempt])
        raise ConnectionError(
            f"gang coordinator(s) at {self.address} unreachable after "
            f"{self._redial_attempts} attempts: {last}") from last

    def _failover_persistent(self, req: dict,  # guarded-by-caller: _mu
                             timeout_s: float) -> dict:
        from .. import resilience as _resil
        delays = _resil.backoff_schedule(
            self._redial_attempts, base_delay_s=0.05, max_delay_s=1.0,
            seed=zlib.crc32(b"gang.persistent") & 0xFFFFFFFF)
        last: Optional[BaseException] = None
        t_fail: Optional[float] = None
        for attempt in range(self._redial_attempts):
            try:
                if self._sock is None:
                    self._sock = self._dial()
                self._sock.settimeout(timeout_s)
                send_frame(self._sock, req)
                resp = self._absorb_epoch(recv_frame(self._sock))
                if resp is None:          # standby/fenced: rotate + retry
                    last = ConnectionError("coordinator is standby/fenced")
                    t_fail = t_fail or time.monotonic()
                    self._close_sock_locked()
                    self._rotate_addr()
                else:
                    if t_fail is not None:
                        self._note_failover(t_fail)
                    return self._checked(resp)
            except (OSError, ConnectionError, ValueError) as e:
                last = e
                t_fail = t_fail or time.monotonic()
                self._close_sock_locked()
                if attempt >= 1:          # first retry is a free reconnect
                    self._rotate_addr()
            if attempt < self._redial_attempts - 1:
                # bounded sleep (ladder caps at ~0.75 s total) under _mu:
                # only other _rpc callers queue behind it, and they would
                # hit the same dead coordinator anyway  # lint-ok: bounded backoff while the coordinator plane fails over
                time.sleep(delays[attempt])
        raise ConnectionError(
            f"gang coordinator(s) at {self.address} unreachable after "
            f"{self._redial_attempts} attempts: {last}") from last

    def _close_sock_locked(self) -> None:  # guarded-by-caller: _mu
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._sock = None

    def _note_failover(self, t_fail: float) -> None:
        """An RPC that failed and then succeeded crossed a coordinator
        failover (or blip) — record how long the client was dark."""
        ms = (time.monotonic() - t_fail) * 1e3
        _monitor.FLEET_FAILOVER_HIST.observe(ms)
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant(
                "gang.client_failover", "gang",
                {"rank": self.rank, "ms": round(ms, 3)})

    @staticmethod
    def _checked(resp: dict) -> dict:
        if resp.get("ok"):
            return resp
        err = resp.get("error")
        detail = resp.get("detail", "")
        if err == "fingerprint":
            raise GangFingerprintError(detail)
        if err == "degraded":
            raise GangDegradedError(detail, dead=resp.get("dead", ()))
        if err == "timeout":
            raise TimeoutError(detail)
        raise RuntimeError(f"gang coordinator refused request: "
                           f"{err}: {detail}")

    def connect(self) -> "GangClient":
        resp = self._rpc({"op": "hello", "pid": os.getpid(),
                          "role": self.role, "endpoint": self.endpoint})
        self._absorb_view(resp)
        return self

    def goodbye(self) -> None:
        """Tell the coordinator this rank is departing ON PURPOSE (work
        done / preemption drain complete).  Without it, the rank's
        silence reads as a death and degrades the gang — a crashed or
        SIGKILLed rank never says this, which is exactly how the
        coordinator tells a departure from a death (the PreemptionGuard
        sends it only on a CLEAN exit of the guarded block).  Stops the
        heartbeat thread first so no trailing beat races the departure.
        Best-effort: a dead coordinator at shutdown is not an error."""
        self._hb_stop.set()
        try:
            self._rpc({"op": "goodbye"}, timeout_s=5.0, oneshot=True)
        except (OSError, ConnectionError, RuntimeError):
            pass

    def close(self, goodbye: bool = True) -> None:
        self._hb_stop.set()
        # interrupt a beat blocked in send/recv (socket timeouts run to
        # 5 s, longer than the join below) — closing the socket makes
        # the blocking call raise NOW, so the thread reliably dies
        # inside this close() instead of beating once more afterwards
        self._drop_hb_sock()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=2.0)
            self._hb_thread = None
        if goodbye:
            self.goodbye()
        with self._mu:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
            self._sock = None

    # -- liveness plane ------------------------------------------------------
    def start_heartbeat(self) -> "GangClient":
        if self._hb_thread is None or not self._hb_thread.is_alive():
            self._hb_stop.clear()
            self._hb_thread = threading.Thread(
                target=self._hb_loop, daemon=True,
                name=f"pt-gang-hb-r{self.rank}")
            self._hb_thread.start()
        return self

    def _absorb_view(self, resp: dict) -> None:
        view = {"status": resp.get("status", "forming"),
                "dead": list(resp.get("dead") or []),
                "manifest": resp.get("manifest"),
                "mismatch": resp.get("mismatch")}
        with self._state_mu:
            self._view = view
        if view["status"] == "degraded" and not self._degraded_noted:
            self._degraded_noted = True
            if _monitor.TRACER.enabled:
                _monitor.TRACER.instant(
                    "gang.degraded", "gang",
                    {"rank": self.rank, "dead": view["dead"]})
        elif view["status"] == "ok":
            self._degraded_noted = False

    def _drop_hb_sock(self) -> None:
        with self._state_mu:
            sock, self._hb_sock = self._hb_sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def _hb_loop(self) -> None:
        from .. import resilience as _resil
        fails = 0          # consecutive beat failures (loop-local)
        while not self._hb_stop.is_set():
            try:
                with self._state_mu:
                    sock = self._hb_sock
                if sock is None:
                    # dial FIRST, publish under the lock after — close()
                    # observing None here is fine: the stop flag is
                    # checked again before the next beat is sent
                    sock = self._dial()
                    sock.settimeout(
                        max(4.0 * self.heartbeat_interval_s, 5.0))
                    with self._state_mu:
                        self._hb_sock = sock
                with self._state_mu:
                    payload = {"op": "heartbeat", "rank": self.rank,
                               "epoch": self._seen_epoch,
                               **self._progress}
                    override = self._digest_override
                digest = override
                if digest is None:
                    # auto-collect this rank's runtime digest (a few
                    # targeted registry reads — the beat stays cheap);
                    # digest failure must never cost a heartbeat
                    try:
                        digest = _monitor.metrics_digest()
                    except Exception:
                        digest = None
                if digest:
                    payload["digest"] = _monitor.capped_digest(digest)
                if self._hb_stop.is_set():
                    break        # close() raced the dial: never beat
                # chaos site: a dropped/hung beat exercises the
                # coordinator's liveness scan + the standby's promotion
                _resil.maybe_inject("replica.heartbeat")
                send_frame(sock, payload)
                resp = self._absorb_epoch(recv_frame(sock))
                _monitor.GANG_HB_CTR.inc(1, role="client")
                if resp is None:
                    # beating a standby (or a fenced zombie): rotate to
                    # the real primary and re-hello so the new leader
                    # learns this rank's role/endpoint
                    self._drop_hb_sock()
                    self._rotate_addr()
                    try:
                        self.connect()
                    except (OSError, ConnectionError, RuntimeError):
                        pass
                elif resp.get("ok"):
                    fails = 0
                    self._absorb_view(resp)
            except (OSError, ConnectionError, ValueError,
                    _resil.InjectedFault):
                self._drop_hb_sock()      # reconnect on the next beat
                fails += 1
                if fails >= 2:
                    # two straight dead beats: the primary is likely
                    # gone — try the next coordinator address
                    self._rotate_addr()
                    fails = 0
            self._hb_stop.wait(self.heartbeat_interval_s)
        self._drop_hb_sock()

    def set_progress(self, step: Optional[int] = None,
                     steps=None, fingerprint: Optional[str] = None) -> None:
        """Update what the next heartbeat carries: the rank's current
        step, its durably-committed step list, and its collective
        fingerprint.  ``None`` leaves a field unchanged."""
        with self._state_mu:
            if step is not None:
                self._progress["step"] = int(step)
            if steps is not None:
                self._progress["steps"] = sorted(int(s) for s in steps)
            if fingerprint is not None:
                self._progress["fingerprint"] = str(fingerprint)

    def set_digest(self, digest: Optional[Dict[str, Any]]) -> None:
        """Override the metrics digest the heartbeat carries (``None``
        returns to auto-collection from the monitor registry).  For
        runners whose metrics live outside this process's registry."""
        with self._state_mu:
            self._digest_override = dict(digest) if digest else None

    @property
    def degraded(self) -> bool:
        with self._state_mu:
            return self._view["status"] == "degraded"

    @property
    def dead_ranks(self) -> List[int]:
        with self._state_mu:
            return list(self._view["dead"])

    def check(self) -> None:
        """Raise the latched cross-rank fingerprint mismatch, if any —
        the passive (heartbeat-borne) form of the barrier refusal."""
        with self._state_mu:
            mm = self._view.get("mismatch")
        if mm:
            raise GangFingerprintError(mm["detail"])

    def wait_ready(self, timeout_s: Optional[float] = None) -> bool:
        """Park until every rank of the gang is alive again (the elastic
        rejoin barrier).  Returns False if the deadline passes with ranks
        still dead."""
        if timeout_s is None:
            from ..flags import get_flags
            timeout_s = float(get_flags("FLAGS_gang_rejoin_timeout_s")
                              ["FLAGS_gang_rejoin_timeout_s"])
        with _monitor.TRACER.span("gang.wait_ready", "gang",
                                  rank=self.rank):
            resp = self._rpc({"op": "wait_ready", "timeout_s": timeout_s},
                             timeout_s=timeout_s + 10.0, oneshot=True)
        return bool(resp.get("ready"))

    def step_barrier(self, step: int, fingerprint: Optional[str] = None,
                     timeout_s: float = 60.0) -> None:
        """Gang step barrier with collective-fingerprint enforcement.
        Raises :class:`GangFingerprintError` (mismatch, naming both
        ranks), :class:`GangDegradedError` (a rank died — drain and
        ``wait_ready`` instead of entering the collective), or
        ``TimeoutError``."""
        if fingerprint is None:
            with self._state_mu:
                fingerprint = self._progress["fingerprint"]
        with _monitor.TRACER.span("gang.step_barrier", "gang",
                                  rank=self.rank, step=int(step)):
            self._rpc({"op": "step_barrier", "step": int(step),
                       "fingerprint": fingerprint,
                       "timeout_s": timeout_s},
                      timeout_s=timeout_s + 10.0, oneshot=True)

    def comm_gate(self, ts: float, timeout_s: float = 10.0) -> dict:
        """Pre-collective timestamp exchange (comms observability): post
        this rank's collective-launch arrival timestamp (epoch seconds)
        and collect every live peer's, pairing by server-side arrival
        order.  Returns ``{"released": bool, "ts": {rank: epoch_s}}`` —
        ``released=False`` means the view is partial (timeout, or a
        dead/departed peer).  Never raises a gang refusal: this is
        telemetry, not coordination — transport errors do propagate so
        the caller can latch the gate off."""
        resp = self._rpc({"op": "comm_gate", "ts": float(ts),
                          "timeout_s": float(timeout_s)},
                         timeout_s=float(timeout_s) + 10.0, oneshot=True)
        return {"released": bool(resp.get("released")),
                "ts": resp.get("ts") or {}}

    # -- GangRendezvous protocol (socket transport) --------------------------
    @property
    def is_leader(self) -> bool:
        return self.rank == 0

    def announce(self, step: int, steps=None) -> None:
        steps = sorted(int(s) for s in (steps or [step]))
        # the heartbeat echoes this list as OBSERVABILITY (the
        # coordinator stores it as hb_steps; the durable record the
        # manifest commits on is this announce alone).  The heartbeat's
        # 'step' field stays the CURRENT training step —
        # set_progress(step=...) is the training loop's to call.
        self.set_progress(steps=steps)
        self._rpc({"op": "announce", "step": int(step), "steps": steps})

    def peer_announcements(self) -> Dict[int, dict]:
        resp = self._rpc({"op": "peers"})
        return {int(r): {"step": int(d["step"]),
                         "steps": [int(s) for s in d["steps"]]}
                for r, d in resp["peers"].items()}

    def committed_step(self) -> Optional[int]:
        step = self._rpc({"op": "manifest"})["step"]
        return None if step is None else int(step)

    def publish(self, step: int) -> None:
        if not self.is_leader:
            raise RuntimeError(
                f"rank {self.rank} tried to publish the gang manifest; "
                "only rank 0 commits")
        self._rpc({"op": "publish", "step": int(step)})

    def commit_latest(self) -> Optional[int]:
        if not self.is_leader:
            return None
        pub = self._rpc({"op": "commit_latest"}).get("published")
        return None if pub is None else int(pub)

    def wait_commit(self, step: int, timeout_s: float,
                    poll_s: float = 0.05) -> bool:
        if not self.is_leader:
            raise RuntimeError("wait_commit is leader-only; other ranks "
                               "just announce and exit")
        resp = self._rpc({"op": "wait_commit", "step": int(step),
                          "timeout_s": float(timeout_s)},
                         timeout_s=float(timeout_s) + 10.0, oneshot=True)
        return bool(resp.get("committed"))

    def wait_manifest(self, step: int, timeout_s: float,
                      poll_s: float = 0.05) -> bool:
        resp = self._rpc({"op": "wait_manifest", "step": int(step),
                          "timeout_s": float(timeout_s)},
                         timeout_s=float(timeout_s) + 10.0, oneshot=True)
        return bool(resp.get("reached"))

    def status(self) -> dict:
        """Full coordinator-side gang view (debugging / tests)."""
        return self._rpc({"op": "status"})
