"""Legacy Downpour PS Python API (ref ``python/paddle/fluid/distributed/``:
downpour.py DownpourSGD, node.py DownpourServer/DownpourWorker descriptor
builders, ps_instance.py PaddlePSInstance).

The reference builds pslib protobuf (`ps_pb2.PSParameter`) consumed by
Baidu's closed-source brpc parameter server.  Here the same descriptor
shapes are plain dataclasses, and the runtime they configure is this
package's native TCP KV parameter server (paddle_tpu.distributed.ps) with
row-sharded sparse tables — the open equivalent of the DownpourSparseTable
accessor stack.  Role bootstrap uses the launcher's env contract instead of
MPI (ref ps_instance uses mpi4py ranks)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..framework.backward import append_backward

__all__ = ["DownpourSGD", "DownpourServer", "DownpourWorker",
           "PaddlePSInstance"]


# -- table descriptors (ref node.py TableParameter shapes) -------------------
@dataclass
class SparseTable:
    table_id: int
    learning_rate: float
    slot_key_vars: List[str]
    slot_value_vars: List[str]
    table_class: str = "DownpourSparseTable"
    accessor_class: str = "DownpourFeatureValueAccessor"


@dataclass
class DenseTable:
    table_id: int
    learning_rate: float
    param_vars: List[str]
    grad_vars: List[str]
    table_class: str = "DownpourDenseTable"
    accessor_class: str = "DownpourDenseValueAccessor"


@dataclass
class ServerDesc:
    server_class: str = "PaddleTpuKvServer"      # native TCP KV server
    client_class: str = "PaddleTpuKvClient"
    sparse_tables: List[SparseTable] = field(default_factory=list)
    dense_tables: List[DenseTable] = field(default_factory=list)


@dataclass
class WorkerDesc:
    window: int = 1
    sparse_tables: List[SparseTable] = field(default_factory=list)
    dense_tables: List[DenseTable] = field(default_factory=list)


@dataclass
class PSParameter:
    """ref ps_pb2.PSParameter — the full job descriptor."""
    server_param: ServerDesc = field(default_factory=ServerDesc)
    worker_param: WorkerDesc = field(default_factory=WorkerDesc)
    program_configs: List[Dict] = field(default_factory=list)


class DownpourServer:
    """Server-side descriptor builder (ref node.py:35)."""

    def __init__(self):
        self.server_ = ServerDesc()

    def add_sparse_table(self, table_id, learning_rate, slot_key_vars,
                         slot_value_vars):
        self.server_.sparse_tables.append(SparseTable(
            table_id, learning_rate,
            [v.name if hasattr(v, "name") else v for v in slot_key_vars],
            [v.name if hasattr(v, "name") else v for v in slot_value_vars]))

    def add_dense_table(self, table_id, learning_rate, param_vars, grad_vars):
        self.server_.dense_tables.append(DenseTable(
            table_id, learning_rate,
            [v.name if hasattr(v, "name") else v for v in param_vars],
            [v.name if hasattr(v, "name") else v for v in grad_vars]))

    def get_desc(self) -> ServerDesc:
        return self.server_


class DownpourWorker:
    """Worker-side descriptor builder (ref node.py:122)."""

    def __init__(self, window=1):
        self.window = window
        self.worker_ = WorkerDesc(window=window)

    def add_sparse_table(self, table_id, learning_rate, slot_key_vars,
                         slot_value_vars):
        self.worker_.sparse_tables.append(SparseTable(
            table_id, learning_rate,
            [v.name if hasattr(v, "name") else v for v in slot_key_vars],
            [v.name if hasattr(v, "name") else v for v in slot_value_vars]))

    def add_dense_table(self, table_id, learning_rate, param_vars, grad_vars):
        self.worker_.dense_tables.append(DenseTable(
            table_id, learning_rate,
            [v.name if hasattr(v, "name") else v for v in param_vars],
            [v.name if hasattr(v, "name") else v for v in grad_vars]))

    def get_desc(self) -> WorkerDesc:
        return self.worker_


def _find_lookup_tables(program) -> Dict[str, Dict[str, List[str]]]:
    """Sparse-embedding sites: table param → {ids inputs, emb outputs}
    (ref helper.py find_distributed_lookup_table*)."""
    tables: Dict[str, Dict[str, List[str]]] = {}
    for op in program.global_block().ops:
        if op.type in ("lookup_table", "distributed_lookup_table") and \
                (op.attrs.get("is_sparse") or op.attrs.get("is_distributed")
                 or op.type == "distributed_lookup_table"):
            w = op.input("W")[0]
            entry = tables.setdefault(w, {"ids": [], "embs": []})
            entry["ids"] += op.input("Ids")
            entry["embs"] += op.output("Out")
    return tables


class DownpourSGD:
    """Legacy distributed optimizer (ref downpour.py:24): appends backward,
    splits params into one sparse table per embedding + one dense table for
    the rest, and returns the PS job descriptor plus the optimizer ops the
    worker must skip (the server applies the updates)."""

    def __init__(self, learning_rate=0.001, window=1):
        self.learning_rate_ = learning_rate
        self.window_ = window
        self.type = "downpour"

    def minimize(self, losses, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if not isinstance(losses, list):
            raise ValueError("losses is a list, like [model.cost]")
        program = losses[0].block.program
        tables = _find_lookup_tables(program)

        ps_param = PSParameter()
        server = DownpourServer()
        worker = DownpourWorker(self.window_)
        table_id = 0
        for w, io in tables.items():
            server.add_sparse_table(table_id, self.learning_rate_,
                                    io["ids"], io["embs"])
            worker.add_sparse_table(table_id, self.learning_rate_,
                                    io["ids"], io["embs"])
            table_id += 1

        param_grads_list = []
        for loss in losses:
            params_grads = sorted(
                append_backward(loss, parameter_list, no_grad_set),
                key=lambda x: x[0].name)
            param_grads_list.append(params_grads)
            dense = [(p, g) for p, g in params_grads
                     if p.name not in tables]
            server.add_dense_table(table_id, self.learning_rate_,
                                   [p for p, _ in dense],
                                   [g for _, g in dense])
            worker.add_dense_table(table_id, self.learning_rate_,
                                   [p for p, _ in dense],
                                   [g for _, g in dense])
            ps_param.program_configs.append({
                "program_id": str(id(loss.block.program)),
                "pull_sparse_table_id": list(range(len(tables))),
                "push_sparse_table_id": list(range(len(tables))),
                "pull_dense_table_id": [table_id],
                "push_dense_table_id": [table_id]})
            table_id += 1

        ps_param.server_param = server.get_desc()
        ps_param.worker_param = worker.get_desc()
        # server applies the updates; the worker skips its local optimizer
        worker_skipped_ops = ["lookup_table_grad", "sgd"]
        return [ps_param, worker_skipped_ops]


class PaddlePSInstance:
    """Role bootstrap (ref ps_instance.py:17, MPI-rank based).  Here roles
    come from the launcher env contract (paddle_tpu.distributed.launch_ps):
    TRAINING_ROLE, PADDLE_TRAINER_ID / current endpoint index."""

    def __init__(self, server_worker_mode=1, proc_per_node=2):
        self.server_worker_mode = server_worker_mode
        self.proc_per_node = proc_per_node
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._is_server = role == "PSERVER"
        if self._is_server:
            eps = os.environ.get("PADDLE_PSERVER_ENDPOINTS", "").split(",")
            cur = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
            self._rank = eps.index(cur) if cur in eps else 0
        else:
            self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._nodes = int(os.environ.get(
            "PADDLE_TRAINERS_NUM",
            os.environ.get("PADDLE_TRAINERS", "1")))

    def is_server(self):
        return self._is_server

    def is_worker(self):
        return not self._is_server

    def is_first_worker(self):
        return self.is_worker() and self._rank == 0

    def get_worker_index(self):
        return self._rank

    def get_server_index(self):
        return self._rank

    def get_worker_num(self):
        return self._nodes

    def get_node_cnt(self):
        return self._nodes

    def barrier_all(self):
        """MPI barrier analog — the launcher's gang start/stop covers it."""

    def finalize(self):
        pass
