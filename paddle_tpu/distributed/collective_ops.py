"""``c_*`` collective ops — graph-level collectives on named mesh axes.

Reference: ``operators/collective/`` (24 files): CUDA kernels calling
``ncclAllReduce`` etc. on an ``NCCLCommContext`` ring selected by
``ring_id`` (``c_allreduce_op.h:58,105``).  Here each op lowers to the
matching XLA collective (``lax.psum/pmax/pmin/all_gather/psum_scatter``)
over a mesh axis — XLA lays the collective onto ICI/DCN.  The ops are
meaningful when the enclosing block executes under the executor's
collective shard_map mode (``ctx.collective_axis`` set); outside it the
world size is 1 and they are the identity, so the same program runs
unchanged on one chip.

Ring bootstrap ops (``c_gen_nccl_id``, ``c_comm_init``...) are no-op
markers: the jax.distributed coordination service plays the role of the
reference's RPC ncclUniqueId exchange (see ``env.init_parallel_env``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..framework.registry import register_op
from ..ops.common import X


def _axis(ctx, attrs):
    """Mesh axis for this ring: collective mode maps ring_id -> axis."""
    ax = getattr(ctx, "collective_axis", None)
    if isinstance(ax, dict):
        return ax.get(int(attrs.get("ring_id", 0) or 0))
    return ax


def _allreduce(kind):
    def lower(ctx, ins, attrs):
        x = X(ins, "X")
        ax = _axis(ctx, attrs)
        if ax is None:
            return {"Out": [x]}
        fn = {"sum": lax.psum, "max": lax.pmax, "min": lax.pmin,
              "prod": _pprod}[kind]
        return {"Out": [fn(x, ax)]}
    return lower


def _pprod(x, axis):
    # XLA has no native pprod; all_gather + prod reduction
    g = lax.all_gather(x, axis)
    return jnp.prod(g, axis=0)


for _kind in ("sum", "max", "min", "prod"):
    register_op(f"c_allreduce_{_kind}", _allreduce(_kind))


@register_op("c_broadcast")
def _c_broadcast(ctx, ins, attrs):
    x = X(ins, "X")
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    root = int(attrs.get("root", 0) or 0)
    return {"Out": [lax.all_gather(x, ax)[root]]}


@register_op("c_allgather")
def _c_allgather(ctx, ins, attrs):
    x = X(ins, "X")
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    g = lax.all_gather(x, ax)            # [nranks, ...]
    return {"Out": [g.reshape((-1,) + x.shape[1:])]}


@register_op("c_reducescatter")
def _c_reducescatter(ctx, ins, attrs):
    x = X(ins, "X")
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    return {"Out": [lax.psum_scatter(x, ax, tiled=True)]}


def _identity(ctx, ins, attrs):
    x = X(ins, "X")
    return {"Out": [x]} if x is not None else {}


# stream sync is implicit in XLA's dataflow ordering
register_op("c_sync_calc_stream", _identity)
register_op("c_sync_comm_stream", _identity)


def _noop(ctx, ins, attrs):
    return {}


# comm bootstrap: the jax.distributed coordination service replaces the
# reference's RPC ncclUniqueId exchange (gen_nccl_id_op.cc)
register_op("c_gen_nccl_id", _noop, no_grad=True)
register_op("c_comm_init", _noop, no_grad=True)
register_op("c_comm_init_all", _noop, no_grad=True)
register_op("gen_nccl_id", _noop, no_grad=True)


@register_op("c_split")
def _c_split(ctx, ins, attrs):
    """Each rank keeps its slice of dim 0 (inverse of c_allgather)."""
    x = X(ins, "X")
    ax = _axis(ctx, attrs)
    if ax is None:
        return {"Out": [x]}
    n = lax.psum(1, ax)
    idx = lax.axis_index(ax)
    return {"Out": [lax.dynamic_slice_in_dim(x, idx * (x.shape[0] // n),
                                             x.shape[0] // n, 0)]}
