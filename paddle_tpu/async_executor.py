"""Legacy AsyncExecutor shim (ref ``framework/async_executor.h:62``).

The reference's Python ``AsyncExecutor`` was already folded into
``Executor.train_from_dataset`` by Fluid 1.5 (only the C++ header
survives in the snapshot); this shim keeps the old call shape alive and
routes it to the modern path — same policy the reference took.
"""

from __future__ import annotations

from .flags import warn_noop
from .framework.executor import Executor


class AsyncExecutor:
    """ref AsyncExecutor(place): thread-pool dataset training.  On TPU the
    step is one XLA computation, so the thread pool degenerates to the
    sequential feed loop of ``train_from_dataset`` (the reference's own
    successor API)."""

    def __init__(self, place=None, run_mode=""):
        warn_noop("AsyncExecutor",
                  "superseded by Executor.train_from_dataset; routing there")
        self._exe = Executor(place)
        self.run_mode = run_mode

    def run(self, program, data_feed, filelist, thread_num=1,
            fetch=None, mode="", debug=False):
        """Legacy signature: dataset described by ``data_feed`` (a
        DataFeedDesc) + a filelist, ``thread_num`` parallel workers."""
        from .data.slot_dataset import QueueDataset
        from .framework import default_main_program
        prog = program or default_main_program()
        blk = prog.global_block()
        dataset = QueueDataset()
        slots = data_feed._slots() if hasattr(data_feed, "_slots") else []
        dataset.set_batch_size(getattr(
            getattr(data_feed, "proto_desc", None), "batch_size", 1))
        names = [s["name"] for s in slots if s.get("is_used")] or \
            [s["name"] for s in slots]
        dataset.set_use_var([blk.var(n) for n in names if blk.has_var(n)])
        dataset.set_thread(thread_num)
        dataset.set_filelist(list(filelist))
        fetch_list = [f.name if hasattr(f, "name") else f
                      for f in (fetch or [])]
        return self._exe.train_from_dataset(
            program=program, dataset=dataset, fetch_list=fetch_list,
            debug=debug)
