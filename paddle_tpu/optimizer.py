"""Optimizers: append_backward + per-param optimize ops.

ref ``python/paddle/fluid/optimizer.py:50`` — base ``Optimizer`` creates
accumulators (startup-program-initialized persistables), appends one optimize
op per parameter, and ``minimize`` = append_backward → (regularize, clip) →
apply_gradients.  All 12 reference optimizers are here (SGD:631 Momentum:701
LarsMomentum:1068 Adagrad:1168 Adam:1271 Adamax:1452 DecayedAdagrad:1606
Adadelta:1698 RMSProp:1796 Ftrl:1969 Lamb:2113 + wrappers ExponentialMovingAverage:2457,
ModelAverage:2267).  The whole update lowers into the same XLA step as the
grads, so "fused optimizer" (ref fuse_all_optimizer_ops pass) is automatic.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .framework import unique_name
from .framework.backward import append_backward
from .framework.core import (Program, Variable, default_main_program,
                             default_startup_program, program_guard)
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper
from .clip import append_gradient_clip_ops, error_clip_callback
from .regularizer import append_regularization_ops


class Optimizer:
    def __init__(self, learning_rate, regularization=None, name=None,
                 grad_clip=None, parameter_list=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name
        self._parameter_list = parameter_list  # dygraph mode (VarBases)
        self._accumulators: Dict[str, Dict[str, Variable]] = {}
        self._learning_rate_var: Optional[Variable] = None
        self.helper: Optional[LayerHelper] = None

    # -- learning rate -------------------------------------------------------
    def _create_global_learning_rate(self):
        if isinstance(self._learning_rate, Variable):
            self._learning_rate_var = self._learning_rate
            return
        if self._learning_rate_var is not None:
            return
        from .layers.tensor import create_global_var
        self._learning_rate_var = create_global_var(
            shape=[1], value=float(self._learning_rate), dtype="float32",
            persistable=True,
            name=unique_name.generate("learning_rate"))

    def _global_learning_rate(self):
        return self._learning_rate_var

    @property
    def learning_rate(self):
        return self._learning_rate

    # -- accumulators --------------------------------------------------------
    def _add_accumulator(self, name, param, dtype=None, fill_value=0.0,
                         shape=None):
        if name in self._accumulators and \
                param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        from .dygraph import base as _dy_base
        if _dy_base.in_dygraph_mode():
            from .dygraph.tracer import VarBase as _VB
            shp = list(shape if shape is not None else param.shape)
            acc = _VB(np.full(shp, fill_value,
                              np.dtype(dtype or param.dtype)),
                      name=f"{param.name}_{name}", persistable=True,
                      trainable=False)
            acc.stop_gradient = True
            self._accumulators.setdefault(name, {})[param.name] = acc
            return acc
        block = default_main_program().global_block()
        shape = list(shape if shape is not None else param.shape)
        var = block.create_var(
            name=unique_name.generate(f"{param.name}_{name}"),
            shape=shape, dtype=dtype or param.dtype, persistable=True,
            stop_gradient=True)
        # accumulators shard like their parameter — resolved LAZILY at
        # sharding-build time (compiler.var_shard) so TP annotations applied
        # after minimize() still propagate
        var.shard_like = param.name
        sb = default_startup_program().global_block()
        sb.create_var(name=var.name, shape=shape, dtype=var.dtype,
                      persistable=True)
        sb.append_op("fill_constant", outputs={"Out": [var.name]},
                     attrs={"shape": shape, "dtype": var.dtype,
                            "value": float(fill_value)})
        self._accumulators.setdefault(name, {})[param.name] = var
        return var

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- hooks ---------------------------------------------------------------
    def _create_accumulators(self, block, parameters):
        pass

    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _finish_update(self, block, parameters_and_grads):
        pass

    # -- public API ----------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        return append_backward(loss, parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        block = default_main_program().global_block()
        # clip/regularization/LR-decay/update ops are all training-only:
        # tag them so clone(for_test=True) prunes the optimize tail
        # (ref OpRole::kOptimize / _optimized_guard)
        with block.program._op_role_guard("optimize"):
            params_grads = append_gradient_clip_ops(params_grads,
                                                    self._grad_clip)
            params_grads = append_regularization_ops(params_grads,
                                                     self.regularization)
            self._create_global_learning_rate()
            self._create_accumulators(block, [p for p, _ in params_grads])
            for pg in params_grads:
                self._append_optimize_op(block, pg)
            self._finish_update(block, params_grads)
        return []

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        if grad_clip is not None:
            self._grad_clip = grad_clip
        from .dygraph import base as _dy_base
        if _dy_base.in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # -- dygraph (eager) path ------------------------------------------------
    # The reference shares Optimizer between static and dygraph (the tracer
    # executes the same optimize ops, imperative/tracer.cc).  We do the same:
    # _append_optimize_op runs against an eager block shim that executes the
    # op's registered lowering on the VarBase values immediately.

    def _dygraph_lr_value(self) -> float:
        lr = self._learning_rate
        if callable(lr) and not isinstance(lr, (int, float)):
            lr = lr()  # dygraph LearningRateDecay
        if hasattr(lr, "numpy"):
            lr = float(np.asarray(lr.numpy()).reshape(-1)[0])
        return float(lr)

    def _dygraph_minimize(self, loss, parameter_list=None):
        from .dygraph.eager_apply import EagerBlock, eager_clip_grads
        params = parameter_list if parameter_list is not None \
            else self._parameter_list
        if params is None:
            raise ValueError(
                "dygraph minimize needs parameter_list (pass it to minimize "
                "or the optimizer constructor)")
        if loss is not None and getattr(loss, "grad", None) is None and \
                all(p.grad is None for p in params):
            loss.backward()
        params_grads = [(p, p.grad) for p in params
                        if p.grad is not None and p.trainable]
        params_grads = eager_clip_grads(params_grads, self._grad_clip)
        # regularization as grad += coeff * d(penalty)/d(param); per-param
        # regularizer takes precedence over the global one, matching
        # append_regularization_ops (regularizer.py:62)
        from .regularizer import L2DecayRegularizer
        new_pg = []
        for p, g in params_grads:
            reg = getattr(p, "regularizer", None) or self.regularization
            if reg is not None:
                coeff = getattr(reg, "_coeff", 0.0)
                if coeff:
                    if isinstance(reg, L2DecayRegularizer):
                        g = g + coeff * p.value
                    else:
                        g = g + coeff * np.sign(np.asarray(p.value))
            new_pg.append((p, g))
        params_grads = new_pg
        block = EagerBlock(self._dygraph_lr_value())
        self._eager_block = block
        self._create_accumulators(block, [p for p, _ in params_grads])
        for pg in params_grads:
            self._append_optimize_op(block, pg)
        self._finish_update(block, params_grads)
        self._eager_block = None
        return [], params_grads


class SGDOptimizer(Optimizer):
    """ref optimizer.py:631."""

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op("sgd",
                        inputs={"Param": [p], "Grad": [g],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p]})


class MomentumOptimizer(Optimizer):
    """ref optimizer.py:701."""

    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        block.append_op("momentum",
                        inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p], "VelocityOut": [v]},
                        attrs={"mu": self._momentum,
                               "use_nesterov": self._use_nesterov})


class LarsMomentumOptimizer(Optimizer):
    """ref optimizer.py:1068."""

    def __init__(self, learning_rate, momentum, lars_coeff=1e-3,
                 lars_weight_decay=5e-4, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        v = self._get_accumulator("velocity", p)
        block.append_op("lars_momentum",
                        inputs={"Param": [p], "Grad": [g], "Velocity": [v],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p], "VelocityOut": [v]},
                        attrs={"mu": self._momentum,
                               "lars_coeff": self._lars_coeff,
                               "lars_weight_decay": self._lars_weight_decay})


class AdamOptimizer(Optimizer):
    """ref optimizer.py:1271.

    ``fused_flat=True`` replaces the ~N per-param ``adam`` ops with ONE
    ``fused_adam`` op over all params (flat-concat update, one shared
    beta-pow pair) — measured lever from BERT_ABLATION.md: the per-param
    form pays per-array kernel overhead on hundreds of small tensors."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_mode=False, fused_flat=False,
                 fused_max_numel=None, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._fused_flat = fused_flat
        # only params up to this size join the flat group: concatenating
        # the big matrices materializes full copies (measured +27 ms on
        # BERT-base), while the per-kernel overhead the fusion removes
        # lives in the hundreds of tiny LN scales/biases
        self._fused_max_numel = fused_max_numel
        self._pending_fused = []

    def _use_fused(self, block):
        from .dygraph import base as _dy_base
        return self._fused_flat and not _dy_base.in_dygraph_mode()

    def _in_flat_group(self, p):
        if self._fused_max_numel is None:
            return True
        n = 1
        for d in (p.shape or ()):
            n *= max(int(d), 1)
        return n <= self._fused_max_numel

    def _create_accumulators(self, block, parameters):
        flat_first = next((p for p in parameters
                           if self._in_flat_group(p)), None)
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            if self._use_fused(block) and self._in_flat_group(p) and \
                    flat_first is not None:
                # one shared beta-pow pair: every param steps together
                self._accumulators.setdefault("beta1_pow_acc", {})[p.name] = \
                    self._add_accumulator("beta1_pow_acc", flat_first,
                                          fill_value=self._beta1, shape=[1])
                self._accumulators.setdefault("beta2_pow_acc", {})[p.name] = \
                    self._add_accumulator("beta2_pow_acc", flat_first,
                                          fill_value=self._beta2, shape=[1])
            else:
                self._add_accumulator("beta1_pow_acc", p,
                                      fill_value=self._beta1, shape=[1])
                self._add_accumulator("beta2_pow_acc", p,
                                      fill_value=self._beta2, shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        if self._use_fused(block) and self._in_flat_group(p):
            self._pending_fused.append((p, g))
            return
        block.append_op(
            "adam",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._global_learning_rate()],
                    "Moment1": [self._get_accumulator("moment1", p)],
                    "Moment2": [self._get_accumulator("moment2", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                    "Beta2Pow": [self._get_accumulator("beta2_pow_acc", p)]},
            outputs={"ParamOut": [p],
                     "Moment1Out": [self._get_accumulator("moment1", p)],
                     "Moment2Out": [self._get_accumulator("moment2", p)],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow_acc", p)],
                     "Beta2PowOut": [self._get_accumulator("beta2_pow_acc", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block, parameters_and_grads):
        if not self._pending_fused:
            return
        pending, self._pending_fused = self._pending_fused, []
        ps = [p for p, _ in pending]
        gs = [g for _, g in pending]
        m1 = [self._get_accumulator("moment1", p) for p in ps]
        m2 = [self._get_accumulator("moment2", p) for p in ps]
        b1p = self._get_accumulator("beta1_pow_acc", ps[0])
        b2p = self._get_accumulator("beta2_pow_acc", ps[0])
        block.append_op(
            "fused_adam",
            inputs={"Param": ps, "Grad": gs,
                    "LearningRate": [self._global_learning_rate()],
                    "Moment1": m1, "Moment2": m2,
                    "Beta1Pow": [b1p], "Beta2Pow": [b2p]},
            outputs={"ParamOut": ps, "Moment1Out": m1, "Moment2Out": m2,
                     "Beta1PowOut": [b1p], "Beta2PowOut": [b2p]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})


class AdamWOptimizer(AdamOptimizer):
    """Decoupled weight decay (TPU-era addition; ref lamb weight_decay)."""

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._coeff = weight_decay

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "adamw",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._global_learning_rate()],
                    "Moment1": [self._get_accumulator("moment1", p)],
                    "Moment2": [self._get_accumulator("moment2", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                    "Beta2Pow": [self._get_accumulator("beta2_pow_acc", p)]},
            outputs={"ParamOut": [p],
                     "Moment1Out": [self._get_accumulator("moment1", p)],
                     "Moment2Out": [self._get_accumulator("moment2", p)],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow_acc", p)],
                     "Beta2PowOut": [self._get_accumulator("beta2_pow_acc", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "coeff": self._coeff})


class AdamaxOptimizer(Optimizer):
    """ref optimizer.py:1452."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1,
                                  shape=[1])

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "adamax",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._global_learning_rate()],
                    "Moment": [self._get_accumulator("moment", p)],
                    "InfNorm": [self._get_accumulator("inf_norm", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)]},
            outputs={"ParamOut": [p],
                     "MomentOut": [self._get_accumulator("moment", p)],
                     "InfNormOut": [self._get_accumulator("inf_norm", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon})

    def _finish_update(self, block, parameters_and_grads):
        for p, _ in parameters_and_grads:
            b1 = self._get_accumulator("beta1_pow_acc", p)
            block.append_op("scale", inputs={"X": [b1]},
                            outputs={"Out": [b1]},
                            attrs={"scale": self._beta1})


class AdagradOptimizer(Optimizer):
    """ref optimizer.py:1168."""

    def __init__(self, learning_rate, epsilon=1e-6,
                 initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._init_acc)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        block.append_op("adagrad",
                        inputs={"Param": [p], "Grad": [g], "Moment": [m],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p], "MomentOut": [m]},
                        attrs={"epsilon": self._epsilon})


class DecayedAdagradOptimizer(Optimizer):
    """ref optimizer.py:1606."""

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        m = self._get_accumulator("moment", p)
        block.append_op("decayed_adagrad",
                        inputs={"Param": [p], "Grad": [g], "Moment": [m],
                                "LearningRate": [self._global_learning_rate()]},
                        outputs={"ParamOut": [p], "MomentOut": [m]},
                        attrs={"decay": self._decay, "epsilon": self._epsilon})


class AdadeltaOptimizer(Optimizer):
    """ref optimizer.py:1698."""

    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("_avg_squared_grad", p)
            self._add_accumulator("_avg_squared_update", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        sq = self._get_accumulator("_avg_squared_grad", p)
        up = self._get_accumulator("_avg_squared_update", p)
        block.append_op("adadelta",
                        inputs={"Param": [p], "Grad": [g],
                                "AvgSquaredGrad": [sq],
                                "AvgSquaredUpdate": [up]},
                        outputs={"ParamOut": [p], "AvgSquaredGradOut": [sq],
                                 "AvgSquaredUpdateOut": [up]},
                        attrs={"epsilon": self._epsilon, "rho": self._rho})


class RMSPropOptimizer(Optimizer):
    """ref optimizer.py:1796."""

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "rmsprop",
            inputs={"Param": [p], "Grad": [g],
                    "Moment": [self._get_accumulator("momentum", p)],
                    "MeanSquare": [self._get_accumulator("mean_square", p)],
                    "MeanGrad": [self._get_accumulator("mean_grad", p)],
                    "LearningRate": [self._global_learning_rate()]},
            outputs={"ParamOut": [p],
                     "MomentOut": [self._get_accumulator("momentum", p)],
                     "MeanSquareOut": [self._get_accumulator("mean_square", p)],
                     "MeanGradOut": [self._get_accumulator("mean_grad", p)]},
            attrs={"decay": self._rho, "epsilon": self._epsilon,
                   "momentum": self._momentum, "centered": self._centered})


class FtrlOptimizer(Optimizer):
    """ref optimizer.py:1969."""

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        block.append_op(
            "ftrl",
            inputs={"Param": [p], "Grad": [g],
                    "SquaredAccumulator": [self._get_accumulator("squared", p)],
                    "LinearAccumulator": [self._get_accumulator("linear", p)],
                    "LearningRate": [self._global_learning_rate()]},
            outputs={"ParamOut": [p],
                     "SquaredAccumOut": [self._get_accumulator("squared", p)],
                     "LinearAccumOut": [self._get_accumulator("linear", p)]},
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power})


class LambOptimizer(AdamOptimizer):
    """ref optimizer.py:2113 — layer-adaptive large-batch optimizer."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6,
                 exclude_from_weight_decay_fn=None, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2,
                         epsilon=epsilon, **kw)
        self._weight_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _append_optimize_op(self, block, param_and_grad):
        p, g = param_and_grad
        wd = self._weight_decay
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        block.append_op(
            "lamb",
            inputs={"Param": [p], "Grad": [g],
                    "LearningRate": [self._global_learning_rate()],
                    "Moment1": [self._get_accumulator("moment1", p)],
                    "Moment2": [self._get_accumulator("moment2", p)],
                    "Beta1Pow": [self._get_accumulator("beta1_pow_acc", p)],
                    "Beta2Pow": [self._get_accumulator("beta2_pow_acc", p)]},
            outputs={"ParamOut": [p],
                     "Moment1Out": [self._get_accumulator("moment1", p)],
                     "Moment2Out": [self._get_accumulator("moment2", p)],
                     "Beta1PowOut": [self._get_accumulator("beta1_pow_acc", p)],
                     "Beta2PowOut": [self._get_accumulator("beta2_pow_acc", p)]},
            attrs={"beta1": self._beta1, "beta2": self._beta2,
                   "epsilon": self._epsilon, "weight_decay": wd})


class DGCMomentumOptimizer(MomentumOptimizer):
    """ref optimizer.py:809 — deep gradient compression.  Single-process
    semantics equal Momentum; under ``parallel.dgc.DGCGradAllReduce`` the
    tagged momentum ops are rewritten into dgc_allreduce (top-k sparse
    sync with momentum correction) + dgc_momentum."""

    def __init__(self, learning_rate, momentum, rampup_begin_step=0,
                 rampup_step=1, sparsity=(0.999,),
                 local_grad_clip_norm=None, **kw):
        super().__init__(learning_rate, momentum, **kw)
        self._rampup_begin_step = rampup_begin_step
        self._sparsity = sparsity
        self._local_grad_clip_norm = local_grad_clip_norm

    def _append_optimize_op(self, block, param_and_grad):
        super()._append_optimize_op(block, param_and_grad)
        if not hasattr(block, "ops"):
            return  # dygraph EagerBlock: eager DGC degrades to momentum
        op = block.ops[-1]
        op.attrs["dgc"] = True
        op.attrs["rampup_begin_step"] = self._rampup_begin_step
        op.attrs["sparsity"] = float(self._sparsity[-1]) \
            if isinstance(self._sparsity, (list, tuple)) else \
            float(self._sparsity)
        if self._local_grad_clip_norm is not None:
            op.attrs["local_grad_clip_norm"] = \
                float(self._local_grad_clip_norm)


class ExponentialMovingAverage:
    """ref optimizer.py:2457 — EMA shadow params + apply/restore guards."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or ""
        self._shadows: Dict[str, Variable] = {}
        self._backups: Dict[str, Variable] = {}

    def update(self):
        block = default_main_program().global_block()
        sb = default_startup_program().global_block()
        for p in block.all_parameters():
            if not p.trainable:
                continue
            sname = f"{self._name}{p.name}.ema"
            shadow = block.create_var(name=sname, shape=p.shape,
                                      dtype=p.dtype, persistable=True,
                                      stop_gradient=True)
            sb.create_var(name=sname, shape=list(p.shape), dtype=p.dtype,
                          persistable=True)
            sb.append_op("fill_constant", outputs={"Out": [sname]},
                         attrs={"shape": list(p.shape), "dtype": p.dtype,
                                "value": 0.0})
            self._shadows[p.name] = shadow
            # shadow = decay*shadow + (1-decay)*param
            tmp = block.create_var(
                name=unique_name.generate(sname + ".tmp"), shape=p.shape,
                dtype=p.dtype, stop_gradient=True)
            block.append_op("scale", inputs={"X": [shadow]},
                            outputs={"Out": [tmp]},
                            attrs={"scale": self._decay})
            tmp2 = block.create_var(
                name=unique_name.generate(sname + ".tmp2"), shape=p.shape,
                dtype=p.dtype, stop_gradient=True)
            block.append_op("scale", inputs={"X": [p]},
                            outputs={"Out": [tmp2]},
                            attrs={"scale": 1.0 - self._decay})
            block.append_op("elementwise_add",
                            inputs={"X": [tmp], "Y": [tmp2]},
                            outputs={"Out": [shadow]})

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            from .framework.scope import global_scope
            scope = global_scope()
            backups = {}
            for pname, shadow in self._shadows.items():
                backups[pname] = scope.find_var(pname)
                sval = scope.find_var(shadow.name)
                if sval is not None:
                    scope.set_var(pname, sval)
            try:
                yield
            finally:
                if need_restore:
                    for pname, v in backups.items():
                        scope.set_var(pname, v)
        return guard()

    def restore(self, executor=None):
        pass


class ModelAverage(Optimizer):
    """ref optimizer.py:2267 — running average of params over a window."""

    def __init__(self, average_window_rate, min_average_window=10000,
                 max_average_window=10000, **kw):
        super().__init__(0.0, **kw)
        self.average_window = average_window_rate
        self.min_average_window = min_average_window
        self.max_average_window = max_average_window
        self.params_grads: List[Tuple[Variable, Variable]] = []
        block = default_main_program().global_block()
        for p in block.all_parameters():
            if p.trainable:
                self._append_average_accumulate_op(p)

    def _append_average_accumulate_op(self, param):
        block = default_main_program().global_block()
        sum1 = self._add_accumulator("sum_1", param)
        sum2 = self._add_accumulator("sum_2", param)
        sum3 = self._add_accumulator("sum_3", param)
        num_acc = self._add_accumulator("num_accumulates", param,
                                        dtype="int64", shape=[1])
        old_num = self._add_accumulator("old_num_accumulates", param,
                                        dtype="int64", shape=[1])
        num_upd = self._add_accumulator("num_updates", param,
                                        dtype="int64", shape=[1])
        block.append_op(
            "average_accumulates",
            inputs={"param": [param], "in_sum_1": [sum1], "in_sum_2": [sum2],
                    "in_sum_3": [sum3], "in_num_accumulates": [num_acc],
                    "in_old_num_accumulates": [old_num],
                    "in_num_updates": [num_upd]},
            outputs={"out_sum_1": [sum1], "out_sum_2": [sum2],
                     "out_sum_3": [sum3], "out_num_accumulates": [num_acc],
                     "out_old_num_accumulates": [old_num],
                     "out_num_updates": [num_upd]},
            attrs={"average_window": self.average_window,
                   "min_average_window": self.min_average_window,
                   "max_average_window": self.max_average_window})

    def apply(self, executor=None, need_restore=True):
        import contextlib

        @contextlib.contextmanager
        def guard():
            from .framework.scope import global_scope
            scope = global_scope()
            backups = {}
            for pname in list(self._accumulators.get("sum_1", {})):
                s1 = scope.find_var(self._accumulators["sum_1"][pname].name)
                s2 = scope.find_var(self._accumulators["sum_2"][pname].name)
                s3 = scope.find_var(self._accumulators["sum_3"][pname].name)
                na = scope.find_var(self._accumulators["num_accumulates"][pname].name)
                on = scope.find_var(self._accumulators["old_num_accumulates"][pname].name)
                if s1 is None:
                    continue
                total = (np.asarray(s1) + np.asarray(s2) + np.asarray(s3))
                cnt = float(np.asarray(na).item() + np.asarray(on).item())
                backups[pname] = scope.find_var(pname)
                if cnt > 0:
                    scope.set_var(pname, total / cnt)
            try:
                yield
            finally:
                if need_restore:
                    for pname, v in backups.items():
                        scope.set_var(pname, v)
        return guard()

    def restore(self, executor=None):
        pass


# fluid-style aliases
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
LarsMomentum = LarsMomentumOptimizer
DGCMomentum = DGCMomentumOptimizer


def __getattr__(name):
    # PipelineOptimizer lives in parallel.pipeline (lazy: avoids a circular
    # import, since pipeline pulls in the executor machinery)
    if name == "PipelineOptimizer":
        from .parallel.pipeline import PipelineOptimizer
        return PipelineOptimizer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


class RecomputeOptimizer:
    """Activation-rematerialization wrapper (TPU-native; the 2019 reference
    has no recompute — see framework/recompute.py).  Usage mirrors the
    modern fluid API:

        opt = optimizer.RecomputeOptimizer(Adam(1e-4))
        opt._set_checkpoints([layer_out_1, layer_out_2, ...])
        opt.minimize(loss)
    """

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._checkpoints = []

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = [
            c.name if hasattr(c, "name") else c for c in checkpoints]

    def __getattr__(self, name):
        return getattr(self._optimizer, name)

    def _apply(self, program):
        # idempotent: minimize() delegates to the inner optimizer whose
        # backward() may already have routed through this wrapper
        if self._checkpoints and not program._attrs.get("__recompute__"):
            from .framework.recompute import apply_recompute
            apply_recompute(program, self._checkpoints)
            program._attrs["__recompute__"] = True

    def backward(self, loss, **kw):
        """fluid's documented recompute entry point: backward() builds the
        grad ops, then the program is rewritten for rematerialization."""
        result = self._optimizer.backward(loss, **kw)
        self._apply(loss.block.program)
        return result

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        result = self._optimizer.minimize(loss, startup_program,
                                          parameter_list, no_grad_set,
                                          grad_clip=grad_clip)
        self._apply(loss.block.program)
        return result


class LookaheadOptimizer:
    """Lookahead (arXiv:1907.08610; ref ``optimizer.py:2980``): the inner
    optimizer moves the fast weights every step; every k-th step the slow
    weights move toward the fast ones by ``alpha`` and the fast weights
    reset to them.

    TPU-native shape: the reference wraps the sync in a Switch over
    ``step % k`` (dynamic control flow); here the blend runs every step
    under a 0/1 mask — a handful of fused elementwise ops per parameter,
    branch-free under XLA, identical math.
    """

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert inner_optimizer is not None, "inner optimizer can not be None"
        assert 0.0 <= alpha <= 1.0, "alpha must be in [0, 1]"
        assert isinstance(k, int) and k > 0, "k must be a positive int"
        self.inner_optimizer = inner_optimizer
        self.alpha = alpha
        self.k = k
        self.type = "lookahead"

    def __getattr__(self, name):
        return getattr(self.inner_optimizer, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, grad_clip=None):
        from .framework import default_startup_program
        result = self.inner_optimizer.minimize(
            loss, startup_program=startup_program,
            parameter_list=parameter_list, no_grad_set=no_grad_set,
            grad_clip=grad_clip)

        main_block = loss.block
        startup = startup_program or default_startup_program()
        params = [p.name for p in main_block.program.all_parameters()]
        with main_block.program._op_role_guard("optimize"):
            self._append_lookahead_ops(main_block, startup, params)
        return result

    def _append_lookahead_ops(self, main_block, startup, params):
        from . import layers
        # slow copies live alongside the fast params (ref: <name>@SLOW),
        # initialized to the fast values by the startup program
        for name in params:
            fast = main_block.var(name)
            main_block.create_var(name=name + "@SLOW", shape=fast.shape,
                                  dtype=fast.dtype, persistable=True)
            sb = startup.global_block()
            sv = sb.create_var(name=name + "@SLOW", shape=fast.shape,
                               dtype=fast.dtype, persistable=True)
            if not sb.has_var(name):
                # params restored via load_persistables instead of init
                # ops: declare the var so the copy below is well-formed
                # (its value must be in the scope before startup runs)
                sb.create_var(name=name, shape=fast.shape,
                              dtype=fast.dtype, persistable=True)
            sb.append_op("assign", inputs={"X": [name]},
                         outputs={"Out": [sv.name]}, attrs={})

        # int32 counter: a float32 step would freeze at 2^24 and silently
        # stop (or jam on) the sync (ref uses an int32 lookahead_step too)
        step = layers.create_global_var(name="lookahead_step", shape=[1],
                                        value=0, dtype="int32",
                                        persistable=True)
        layers.increment(step, value=1, in_place=True)
        # mask = 1.0 every k-th step else 0.0
        mod = layers.elementwise_mod(step, layers.fill_constant(
            shape=[1], dtype="int32", value=self.k))
        mask = layers.cast(layers.equal(mod, layers.fill_constant(
            shape=[1], dtype="int32", value=0)), "float32")
        for name in params:
            fast = main_block.var(name)
            slow = main_block.var(name + "@SLOW")
            blend = slow + self.alpha * (fast - slow)
            new_slow = mask * blend + (1.0 - mask) * slow
            new_fast = mask * new_slow + (1.0 - mask) * fast
            layers.assign(new_slow, slow)
            layers.assign(new_fast, fast)
