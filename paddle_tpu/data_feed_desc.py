"""DataFeedDesc (ref ``python/paddle/fluid/data_feed_desc.py``): textual
descriptor of the MultiSlot input format, parsed from the reference's
protobuf-text files (``framework/data_feed.proto`` schema) without
requiring protobuf — the same ``name/batch_size/multi_slot_desc{slots{...}}``
grammar handled by a small recursive reader."""

from __future__ import annotations

import re
from types import SimpleNamespace

__all__ = ["DataFeedDesc"]


def _parse_prototxt(text: str):
    """Tiny text-format protobuf reader: k: v scalars and k { ... } blocks
    (repeated keys accumulate into lists)."""
    tokens = re.findall(r'[{}]|[\w.]+\s*:\s*(?:"[^"]*"|[^\s{}]+)|\w+(?=\s*{)',
                        text)
    pos = 0

    def parse_block():
        nonlocal pos
        out = {}
        while pos < len(tokens):
            tok = tokens[pos]
            if tok == "}":
                pos += 1
                return out
            if pos + 1 < len(tokens) and tokens[pos + 1] == "{":
                key = tok
                pos += 2
                val = parse_block()
            else:
                key, _, raw = tok.partition(":")
                raw = raw.strip()
                if raw.startswith('"'):
                    val = raw.strip('"')
                elif raw in ("true", "false"):
                    val = raw == "true"
                else:
                    try:
                        val = int(raw)
                    except ValueError:
                        try:
                            val = float(raw)
                        except ValueError:
                            val = raw
                pos += 1
            if key in out:
                if not isinstance(out[key], list):
                    out[key] = [out[key]]
                out[key].append(val)
            else:
                out[key] = val
        return out

    return parse_block()


def _emit(d, indent=0):
    pad = "  " * indent
    lines = []
    for key, val in d.items():
        vals = val if isinstance(val, list) else [val]
        for v in vals:
            if isinstance(v, dict):
                lines.append(f"{pad}{key} {{")
                lines.append(_emit(v, indent + 1))
                lines.append(pad + "}")
            elif isinstance(v, bool):
                lines.append(f"{pad}{key}: {str(v).lower()}")
            elif isinstance(v, str):
                lines.append(f'{pad}{key}: "{v}"')
            else:
                lines.append(f"{pad}{key}: {v}")
    return "\n".join(lines)


class DataFeedDesc:
    """ref data_feed_desc.py:21."""

    def __init__(self, proto_file: str):
        with open(proto_file) as f:
            self._d = _parse_prototxt(f.read())
        self._d.setdefault("pipe_command", "cat")
        self.__name_to_index = {}
        slots = self._slots()
        self.__name_to_index = {s["name"]: i for i, s in enumerate(slots)}
        self.proto_desc = SimpleNamespace(
            name=self._d.get("name", ""),
            batch_size=self._d.get("batch_size", 1))

    def _slots(self):
        msd = self._d.get("multi_slot_desc") or {}
        slots = msd.get("slots", [])
        return slots if isinstance(slots, list) else [slots]

    def set_batch_size(self, batch_size: int):
        """ref data_feed_desc.py:93."""
        self._d["batch_size"] = int(batch_size)
        self.proto_desc.batch_size = int(batch_size)

    def set_dense_slots(self, dense_slots_name):
        """ref data_feed_desc.py:128 — named slots become dense."""
        slots = self._slots()
        for name in dense_slots_name:
            if name not in self.__name_to_index:
                raise ValueError(f"slot {name!r} not in the descriptor")
            slots[self.__name_to_index[name]]["is_dense"] = True

    def set_use_slots(self, use_slots_name):
        """ref data_feed_desc.py:173 — only named slots are used."""
        slots = self._slots()
        for s in slots:
            s["is_used"] = False
        for name in use_slots_name:
            if name not in self.__name_to_index:
                raise ValueError(f"slot {name!r} not in the descriptor")
            slots[self.__name_to_index[name]]["is_used"] = True

    def desc(self) -> str:
        """Text-format descriptor (ref data_feed_desc.py:218)."""
        return _emit(self._d) + "\n"
