"""WeightedAverage (ref ``python/paddle/fluid/average.py:40``): host-side
streaming weighted mean over fetched metric values."""

from __future__ import annotations

import numpy as np

__all__ = ["WeightedAverage"]


def _is_number_or_matrix(value):
    return isinstance(value, (int, float, complex, np.ndarray)) and \
        not isinstance(value, bool)


class WeightedAverage:
    """accumulate sum(value*weight)/sum(weight) (ref average.py add/eval)."""

    def __init__(self):
        self.reset()

    def reset(self):
        self.numerator = 0.0
        self.denominator = 0.0

    def add(self, value, weight):
        if not _is_number_or_matrix(value):
            raise ValueError(
                "The 'value' must be a number(int, float) or a numpy ndarray")
        if not isinstance(weight, (int, float)) or isinstance(weight, bool):
            raise ValueError("The 'weight' must be a number(int, float)")
        self.numerator += float(np.asarray(value).mean()) * weight
        self.denominator += weight

    def eval(self):
        if self.denominator == 0.0:
            raise ValueError(
                "There is no data to be averaged in WeightedAverage")
        return self.numerator / self.denominator
