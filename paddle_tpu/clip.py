"""Gradient clipping (ref ``python/paddle/fluid/clip.py``):
GradientClipByValue / ByNorm / ByGlobalNorm append clip ops onto grads
before the optimizer ops."""

from __future__ import annotations

from .framework import unique_name


class BaseGradientClipAttr:
    def _append_clip_op(self, block, param, grad):
        raise NotImplementedError


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def _append_clip_op(self, block, param, grad):
        out = block.create_var(
            name=unique_name.generate(grad.name + ".clip"),
            shape=grad.shape, dtype=grad.dtype, stop_gradient=True)
        block.append_op("clip", inputs={"X": [grad]}, outputs={"Out": [out]},
                        attrs={"min": self.min, "max": self.max})
        return out


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _append_clip_op(self, block, param, grad):
        out = block.create_var(
            name=unique_name.generate(grad.name + ".clip"),
            shape=grad.shape, dtype=grad.dtype, stop_gradient=True)
        block.append_op("clip_by_norm", inputs={"X": [grad]},
                        outputs={"Out": [out]},
                        attrs={"max_norm": self.clip_norm})
        return out


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    """ref clip.py GradientClipByGlobalNorm — scale all grads by
    clip_norm / max(global_norm, clip_norm)."""

    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip_all(self, params_grads):
        if not params_grads:
            return params_grads
        block = params_grads[0][1].block
        sq_norms = []
        for p, g in params_grads:
            if g is None or not p.need_clip:
                continue
            sq = block.create_var(
                name=unique_name.generate(g.name + ".sq"),
                shape=(1,), dtype="float32", stop_gradient=True)
            block.append_op("squared_l2_norm", inputs={"X": [g]},
                            outputs={"Out": [sq]})
            sq_norms.append(sq)
        if not sq_norms:
            return params_grads
        total = block.create_var(name=unique_name.generate("gnorm_sq"),
                                 shape=(1,), dtype="float32",
                                 stop_gradient=True)
        block.append_op("sum", inputs={"X": sq_norms},
                        outputs={"Out": [total]})
        gnorm = block.create_var(name=unique_name.generate("gnorm"),
                                 shape=(1,), dtype="float32",
                                 stop_gradient=True)
        block.append_op("sqrt", inputs={"X": [total]},
                        outputs={"Out": [gnorm]})
        # scale = clip / max(gnorm, clip)
        maxed = block.create_var(name=unique_name.generate("gnorm_max"),
                                 shape=(1,), dtype="float32",
                                 stop_gradient=True)
        clipv = block.create_var(name=unique_name.generate("clipnorm"),
                                 shape=(1,), dtype="float32",
                                 stop_gradient=True)
        block.append_op("fill_constant", outputs={"Out": [clipv]},
                        attrs={"shape": [1], "dtype": "float32",
                               "value": self.clip_norm})
        block.append_op("elementwise_max",
                        inputs={"X": [gnorm], "Y": [clipv]},
                        outputs={"Out": [maxed]})
        scale = block.create_var(name=unique_name.generate("clip_scale"),
                                 shape=(1,), dtype="float32",
                                 stop_gradient=True)
        block.append_op("elementwise_div",
                        inputs={"X": [clipv], "Y": [maxed]},
                        outputs={"Out": [scale]})
        out = []
        for p, g in params_grads:
            if g is None or not p.need_clip:
                out.append((p, g))
                continue
            ng = block.create_var(
                name=unique_name.generate(g.name + ".clip"),
                shape=g.shape, dtype=g.dtype, stop_gradient=True)
            block.append_op("elementwise_mul",
                            inputs={"X": [g], "Y": [scale]},
                            outputs={"Out": [ng]})
            out.append((p, ng))
        return out


def append_gradient_clip_ops(params_grads, clip_attr=None):
    if clip_attr is None:
        return params_grads
    if isinstance(clip_attr, GradientClipByGlobalNorm):
        return clip_attr._clip_all(params_grads)
    out = []
    for p, g in params_grads:
        if g is None or not p.need_clip:
            out.append((p, g))
            continue
        out.append((p, clip_attr._append_clip_op(g.block, p, g)))
    return out


def set_gradient_clip(clip, param_list=None, program=None):
    """ref clip.py set_gradient_clip — stores clip on params."""
    from .framework.core import default_main_program
    program = program or default_main_program()
    if param_list is None:
        param_list = program.all_parameters()
    for p in param_list:
        p.gradient_clip_attr = clip


def error_clip_callback(block, context):
    pass


ErrorClipByValue = GradientClipByValue
