"""High-level Inferencer API (ref ``python/paddle/fluid/contrib/
inferencer.py``: Inferencer(infer_func, param_path).infer(inputs))."""

from __future__ import annotations

from typing import Callable, Optional

from .. import io as pio
from ..framework import unique_name
from ..framework.core import Program, Variable, program_guard
from ..framework.executor import Executor
from ..framework.scope import Scope

__all__ = ["Inferencer"]


class Inferencer:
    """Builds the inference program from ``infer_func`` and loads trained
    params from ``param_path`` (ref inferencer.py:27)."""

    def __init__(self, infer_func: Callable, param_path: str, place=None,
                 parallel: bool = False):
        self.place = place
        self.scope = Scope()
        self.inference_program = Program()
        startup = Program()
        with program_guard(self.inference_program, startup), \
                unique_name.guard():
            out = infer_func()
            self.predict_var = out if isinstance(out, Variable) else out[0]
        self.inference_program = \
            self.inference_program.clone(for_test=True)
        self.exe = Executor(place)
        pio.load_params(self.exe, dirname=param_path,
                        main_program=self.inference_program,
                        scope=self.scope)

    def infer(self, inputs: dict, return_numpy: bool = True):
        """inputs: feed-var name → numpy array (ref inferencer.py:85)."""
        if not isinstance(inputs, dict):
            raise ValueError(
                "inputs should be a map of {'input_name': input_var}")
        return self.exe.run(self.inference_program, feed=inputs,
                            fetch_list=[self.predict_var.name],
                            scope=self.scope, return_numpy=return_numpy)
