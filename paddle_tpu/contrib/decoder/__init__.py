"""Seq2seq decoder toolkit (ref ``python/paddle/fluid/contrib/decoder/``)."""

from .beam_search_decoder import (BeamSearchDecoder, InitState,  # noqa
                                  StateCell, TrainingDecoder)
