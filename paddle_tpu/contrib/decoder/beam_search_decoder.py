"""Declarative seq2seq decoding: InitState / StateCell / TrainingDecoder /
BeamSearchDecoder (ref ``python/paddle/fluid/contrib/decoder/
beam_search_decoder.py:43,159,384,523``).

TPU-native shape: the reference threads variable-width beams through LoD
(``sequence_expand`` to replicate states, ``lod_reset`` on scores).  Here
every batch keeps exactly ``beam_size`` dense hypothesis slots
([batch*beam, ...] activations), the ``beam_search`` op returns explicit
``parent_idx`` pointers, and states are re-ordered with one ``gather`` —
the layout XLA can tile, with no ragged metadata.  Training decode wraps
DynamicRNN (one ``lax.scan``); beam decode is a ``While`` whose body is one
jitted step."""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

from ... import layers
from ...framework import unique_name
from ...framework.core import Variable

__all__ = ["InitState", "StateCell", "TrainingDecoder", "BeamSearchDecoder"]


class _DecoderType:
    TRAINING = 1
    BEAM_SEARCH = 2


class InitState:
    """Initial decoder state (ref beam_search_decoder.py:43): either an
    existing Variable (e.g. encoder final state) or a zero-filled shape."""

    def __init__(self, init=None, shape=None, value=0.0,
                 init_boot=None, need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "init_boot must be provided to infer the init batch size")
        else:
            self._init = layers.fill_constant_batch_size_like(
                input=init_boot, value=value, shape=shape, dtype=dtype)
        self._shape = shape
        self._value = value
        self._need_reorder = need_reorder
        self._dtype = dtype

    @property
    def value(self):
        return self._init

    @property
    def need_reorder(self):
        return self._need_reorder


class _MemoryState:
    """State held as a DynamicRNN memory (training mode; ref :100)."""

    def __init__(self, state_name, rnn_obj, init_state):
        self._state_name = state_name
        self._rnn_obj = rnn_obj
        self._state_mem = self._rnn_obj.memory(init=init_state.value)

    def get_state(self):
        return self._state_mem

    def update_state(self, state):
        self._rnn_obj.update_memory(self._state_mem, state)


class _ArrayState:
    """State held in a tensor array (beam-search mode; ref :114): read at
    the loop counter, written at counter+1 by the decoder's end-of-step
    hook."""

    def __init__(self, state_name, program, init_state, buffer_len=128):
        self._state_name = state_name
        self._init = init_state.value
        self._need_reorder = init_state.need_reorder
        # the array + its seed write live in the PARENT block (ref :115
        # parent_block.append_op write_to_array) — inside the while body
        # they would never run before the first iteration
        from ...layers.control_flow import _parent_block
        ctx = (_parent_block(program)
               if program.current_block().parent_idx >= 0
               else contextlib.nullcontext())
        with ctx:
            self._state_array = layers.create_array(self._init.dtype,
                                                    max_len=buffer_len)
            zero = layers.fill_constant([1], "int64", 0)
            layers.array_write(self._init, zero, self._state_array)
        self._counter = None          # bound by the decoder
        self._pending = None

    def get_state(self):
        return layers.array_read(self._state_array, self._counter)

    def update_state(self, state):
        self._pending = state


class StateCell:
    """Named decoder states + inputs with a user ``state_updater``
    (ref beam_search_decoder.py:159)."""

    def __init__(self, inputs: Dict[str, Optional[Variable]],
                 states: Dict[str, InitState], out_state: str, name=None):
        self._inputs = dict(inputs)
        self._cur_states: Dict[str, object] = {}
        self._state_names = list(states)
        self._states_holder = states
        self._out_state = out_state
        self._pending_values: Dict[str, Variable] = {}
        self._updater = None
        self._decoder_obj = None
        self._in_decoder = False
        self._switched_decoder = False

    # -- decoder binding (ref _enter/_leave/_switch_decoder) -----------------
    def _enter_decoder(self, decoder_obj):
        if self._in_decoder:
            raise ValueError("StateCell has already entered a decoder.")
        self._in_decoder = True
        self._decoder_obj = decoder_obj
        self._switched_decoder = False

    def _leave_decoder(self, decoder_obj):
        if not self._in_decoder or self._decoder_obj is not decoder_obj:
            raise ValueError(
                "StateCell not in this decoder object.")
        self._in_decoder = False
        self._decoder_obj = None
        self._switched_decoder = False

    def _switch_decoder(self):
        if not self._in_decoder:
            raise ValueError("StateCell must be enrolled in a decoder.")
        if self._switched_decoder:
            raise ValueError("StateCell already done switching.")
        for state_name, init in self._states_holder.items():
            if self._decoder_obj.type == _DecoderType.TRAINING:
                self._cur_states[state_name] = _MemoryState(
                    state_name, self._decoder_obj.dynamic_rnn, init)
            else:
                st = _ArrayState(
                    state_name, self._decoder_obj._program, init,
                    buffer_len=self._decoder_obj._buffer_len)
                st._counter = self._decoder_obj._counter
                self._cur_states[state_name] = st
                self._decoder_obj._register_state(st)
        self._switched_decoder = True

    # -- state access --------------------------------------------------------
    def get_state(self, state_name):
        if not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError(f"unknown state {state_name!r}")
        st = self._cur_states[state_name]
        return st.get_state() if not isinstance(st, Variable) else st

    def get_input(self, input_name):
        if input_name not in self._inputs or \
                self._inputs[input_name] is None:
            raise ValueError(f"input variable {input_name!r} not found")
        return self._inputs[input_name]

    def set_state(self, state_name, state_value):
        """Stage a new value; committed by update_states (ref :303)."""
        if not self._switched_decoder:
            self._switch_decoder()
        if state_name not in self._cur_states:
            raise ValueError(f"unknown state {state_name!r}")
        self._pending_values[state_name] = state_value

    def state_updater(self, updater):
        """Decorator registering the per-step state computation
        (ref :314)."""
        self._updater = updater

        def _decorator(state_cell):
            if state_cell is not self:
                raise TypeError("updater must take this StateCell")
            updater(state_cell)
        return _decorator

    def compute_state(self, inputs: Dict[str, Variable]):
        """Bind this step's inputs, then run the updater (ref :335)."""
        if not self._switched_decoder:
            self._switch_decoder()
        for name, value in inputs.items():
            if name not in self._inputs:
                raise ValueError(f"unknown input {name!r}")
            self._inputs[name] = value
        self._updater(self)

    def update_states(self):
        """Commit staged values back to memories/arrays (ref :360)."""
        for name, value in self._pending_values.items():
            self._cur_states[name].update_state(value)
        self._pending_values = {}

    def out_state(self):
        """This step's output state: the staged value if present, else the
        holder's current value (ref :374)."""
        pending = self._pending_values.get(self._out_state)
        if pending is not None:
            return pending
        return self._cur_states[self._out_state].get_state()


class TrainingDecoder:
    """Teacher-forced decoding over DynamicRNN (ref :384)."""

    def __init__(self, state_cell: StateCell, name=None):
        self._rnn = layers.DynamicRNN(name=name)
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._in_block = False
        self._outputs: List[Variable] = []

    @property
    def dynamic_rnn(self):
        return self._rnn

    @property
    def type(self):
        return _DecoderType.TRAINING

    @property
    def state_cell(self):
        return self._state_cell

    @contextlib.contextmanager
    def block(self):
        self._in_block = True
        with self._rnn.block():
            yield
        self._in_block = False
        self._state_cell._leave_decoder(self)

    def step_input(self, x, seq_len=None):
        return self._rnn.step_input(x, seq_len=seq_len)

    def static_input(self, x):
        # parent-scope vars are captured by the scan lowering automatically;
        # the reference needed an explicit reorder-by-rank-table copy
        return x

    def output(self, *outputs):
        for out in outputs:
            self._rnn.step_output(out)

    def __call__(self, *args, **kwargs):
        return self._rnn(*args, **kwargs)


class BeamSearchDecoder:
    """Inference-time beam search (ref :523).

    Usage (auto mode)::

        decoder = BeamSearchDecoder(state_cell, init_ids, init_scores,
                                    target_dict_dim, word_dim,
                                    beam_size=4, end_id=1, max_len=20)
        decoder.decode()
        translation_ids, translation_scores = decoder()
    """

    BEFORE, IN, AFTER = 0, 1, 2

    def __init__(self, state_cell: StateCell, init_ids, init_scores,
                 target_dict_dim, word_dim, input_var_dict=None,
                 topk_size=50, sparse_emb=True, max_len=100, beam_size=1,
                 end_id=1, name=None):
        self._counter = layers.zeros(shape=[1], dtype="int64")
        self._counter.stop_gradient = True
        self._buffer_len = max_len + 1      # exact dense array size
        self._max_len = layers.fill_constant([1], "int64", max_len)
        self._cond = layers.less_than(self._counter, self._max_len)
        self._while_op = layers.While(self._cond)
        self._state_cell = state_cell
        self._state_cell._enter_decoder(self)
        self._status = self.BEFORE
        self._zero_idx = layers.fill_constant([1], "int64", 0)
        self._array_dict = {}
        self._array_link = []
        self._array_states: List[_ArrayState] = []
        self._ids_array = None
        self._scores_array = None
        self._parents_array = None     # created+seeded at first decode step
        self._beam_size = beam_size
        self._end_id = end_id
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = target_dict_dim
        self._topk_size = topk_size
        self._sparse_emb = sparse_emb
        self._word_dim = word_dim
        self._input_var_dict = dict(input_var_dict or {})
        self._parent_idx = None
        from ...framework.core import default_main_program
        self._program = default_main_program()

    @property
    def type(self):
        return _DecoderType.BEAM_SEARCH

    def _parent_block(self):
        return self._program.global_block()

    def _register_state(self, array_state: _ArrayState):
        self._array_states.append(array_state)

    @contextlib.contextmanager
    def block(self):
        """Per-step body; on exit the step-end bookkeeping runs under
        'still alive' (ref :620-643)."""
        if self._status != self.BEFORE:
            raise ValueError("block() can only be invoked once.")
        self._status = self.IN
        with self._while_op.block():
            yield
            sw = layers.Switch()
            with sw.case(self._cond):
                layers.increment(self._counter, value=1.0, in_place=True)
                for value, array in self._array_link:
                    layers.array_write(value, self._counter, array)
                if self._parent_idx is not None:
                    layers.array_write(self._parent_idx, self._counter,
                                       self._parents_array)
                # re-ordered states stored for the next step
                for st in self._array_states:
                    if st._pending is not None:
                        layers.array_write(st._pending, self._counter,
                                           st._state_array)
                        st._pending = None
                layers.less_than(self._counter, self._max_len,
                                 cond=self._cond)
        self._status = self.AFTER
        self._state_cell._leave_decoder(self)

    def early_stop(self):
        """break: force the while condition false (ref :649)."""
        false = layers.fill_constant([1], "bool", 0)
        layers.assign(false, self._cond)

    def read_array(self, init, is_ids=False, is_scores=False):
        """Array-backed loop variable seeded with ``init`` (ref :731)."""
        if self._status != self.IN:
            raise ValueError("read_array must be called inside block()")
        if is_ids and is_scores:
            raise ValueError("an array cannot be ids and scores at once")
        if not isinstance(init, Variable):
            raise TypeError("`init` must be a Variable")
        from ...layers.control_flow import _parent_block
        with _parent_block(self._program):
            array = layers.create_array(init.dtype,
                                        max_len=self._buffer_len)
            layers.array_write(init, self._zero_idx, array)
        if is_ids:
            self._ids_array = array
        elif is_scores:
            self._scores_array = array
        read_value = layers.array_read(array, self._counter)
        self._array_dict[read_value.name] = array
        return read_value

    def update_array(self, array_var, value):
        """Queue ``value`` for the end-of-step write (ref :780)."""
        if self._status != self.IN:
            raise ValueError("update_array must be called inside block()")
        array = self._array_dict.get(array_var.name)
        if array is None:
            raise ValueError("invoke read_array before update_array")
        self._array_link.append((value, array))

    @property
    def state_cell(self):
        return self._state_cell

    # -- auto decode (ref :653) ----------------------------------------------
    def decode(self):
        with self.block():
            prev_ids = self.read_array(self._init_ids, is_ids=True)
            prev_scores = self.read_array(self._init_scores,
                                          is_scores=True)
            # parents array seeded with identity pointers for step 0
            from ...layers.control_flow import _parent_block
            with _parent_block(self._program):
                self._parents_array = layers.create_array(
                    "int64", max_len=self._buffer_len)
                seed_parents = layers.fill_constant_batch_size_like(
                    self._init_ids, shape=[-1], dtype="int64", value=0)
                layers.array_write(seed_parents, self._zero_idx,
                                   self._parents_array)
            prev_emb = layers.embedding(
                prev_ids, size=[self._target_dict_dim, self._word_dim],
                dtype="float32", is_sparse=self._sparse_emb,
                param_attr=None)
            prev_emb = layers.reshape(prev_emb, [-1, self._word_dim])

            feed_dict, update_dict = {}, {}
            for name, init_var in self._input_var_dict.items():
                if name not in self._state_cell._inputs:
                    raise ValueError(
                        f"Variable {name} not found in StateCell")
                read_var = self.read_array(init=init_var)
                update_dict[name] = read_var
                feed_dict[name] = read_var
            for input_name in self._state_cell._inputs:
                if input_name not in feed_dict:
                    feed_dict[input_name] = prev_emb

            self._state_cell.compute_state(inputs=feed_dict)
            current_state = self._state_cell.out_state()
            scores = layers.fc(current_state,
                               size=self._target_dict_dim, act="softmax")
            topk_scores, topk_indices = layers.topk(scores,
                                                    k=self._topk_size)
            # dense: prev_scores [bb,1] broadcasts over the topk axis
            accu_scores = layers.elementwise_add(
                layers.log(topk_scores), prev_scores)
            selected_ids, selected_scores, parent_idx = layers.beam_search(
                prev_ids, prev_scores, topk_indices, accu_scores,
                self._beam_size, end_id=self._end_id, level=0)
            self._parent_idx = parent_idx

            # NOTE: no early exit here (vs the reference's
            # is_empty(selected_ids) check) — finished beams re-emit end_id
            # with frozen scores under the dense beam_search op, so running
            # the fixed trip count is semantically identical while keeping
            # every array slot written (an early stop would leave zero-
            # filled steps that corrupt the backtrack) and the loop shape
            # static for XLA.

            # re-order THIS STEP's computed states by the beam parents,
            # then commit (gathering st.get_state() would reorder the
            # stale previous-step value and drop the update entirely)
            for name in self._state_cell._state_names:
                staged = self._state_cell._pending_values.get(name)
                if staged is None:
                    staged = self._state_cell._cur_states[name].get_state()
                self._state_cell.set_state(
                    name, layers.gather(staged, parent_idx))
            self._state_cell.update_states()
            self.update_array(prev_ids, selected_ids)
            self.update_array(prev_scores, selected_scores)
            for name, var_to_update in update_dict.items():
                self.update_array(var_to_update, feed_dict[name])

    def __call__(self):
        """Backtrack arrays into sentences (ref :802)."""
        if self._status != self.AFTER:
            raise ValueError(
                "output may only be read after the decode block")
        ids, _ = layers.tensor_array_to_tensor(self._ids_array, axis=0,
                                               use_stack=True)
        scores, _ = layers.tensor_array_to_tensor(self._scores_array,
                                                  axis=0, use_stack=True)
        parents, _ = layers.tensor_array_to_tensor(self._parents_array,
                                                   axis=0, use_stack=True)
        return layers.beam_search_decode(ids, scores, parents,
                                         beam_size=self._beam_size,
                                         end_id=self._end_id)
