"""Basic GRU/LSTM built from primitive ops (ref ``python/paddle/fluid/
contrib/layers/rnn_impl.py``: BasicGRUUnit/basic_gru/BasicLSTMUnit/
basic_lstm — multi-layer, bidirectional, length-masked recurrences over
StaticRNN).

TPU-native shape: each layer×direction is ONE ``lax.scan`` (our StaticRNN
lowering), so the whole stack compiles to a handful of scans whose per-step
matmuls XLA fuses — not a Python-unrolled loop.  Variable lengths use a
per-step 0/1 mask (new_h = mask·h' + (1-mask)·h) on dense padded batches:
the padded-region steps carry state through unchanged, which also makes
the naive time-reversal correct for the backward direction."""

from __future__ import annotations

from ... import layers
from ...framework import unique_name
from ...param_attr import ParamAttr

__all__ = ["BasicGRUUnit", "basic_gru", "BasicLSTMUnit", "basic_lstm"]


class BasicGRUUnit:
    """One GRU step from concat/matmul/sigmoid/tanh ops (ref
    rnn_impl.py:22)."""

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype="float32"):
        self._name = unique_name.generate(name_scope)
        self._hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_activation = gate_activation or layers.sigmoid
        self._activation = activation or layers.tanh
        self._dtype = dtype
        self._built = False

    def build_once(self, input_size):
        if self._built:
            return
        h = self._hidden_size
        self._gate_weight = layers.create_parameter(
            [input_size + h, 2 * h], dtype=self._dtype,
            name=self._name + "_gate_w", attr=self._param_attr)
        self._gate_bias = layers.create_parameter(
            [2 * h], dtype=self._dtype, name=self._name + "_gate_b",
            attr=self._bias_attr, is_bias=True)
        self._candidate_weight = layers.create_parameter(
            [input_size + h, h], dtype=self._dtype,
            name=self._name + "_cand_w", attr=self._param_attr)
        self._candidate_bias = layers.create_parameter(
            [h], dtype=self._dtype, name=self._name + "_cand_b",
            attr=self._bias_attr, is_bias=True)
        self._built = True

    def __call__(self, input, pre_hidden):
        if not self._built:
            self.build_once(int(input.shape[-1]))
        concat = layers.concat([input, pre_hidden], axis=1)
        gate_input = layers.elementwise_add(
            layers.matmul(concat, self._gate_weight), self._gate_bias)
        gates = self._gate_activation(gate_input)
        r, u = layers.split(gates, num_or_sections=2, dim=1)
        r_hidden = layers.elementwise_mul(r, pre_hidden)
        candidate = layers.elementwise_add(
            layers.matmul(layers.concat([input, r_hidden], axis=1),
                          self._candidate_weight), self._candidate_bias)
        c = self._activation(candidate)
        # h' = u·h + (1-u)·c
        return layers.elementwise_add(
            layers.elementwise_mul(u, pre_hidden),
            layers.elementwise_mul(1.0 - u, c))


class BasicLSTMUnit:
    """One LSTM step (ref rnn_impl.py:622): i,j,f,o from a single fused
    matmul; forget_bias added pre-sigmoid."""

    def __init__(self, name_scope, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype="float32"):
        self._name = unique_name.generate(name_scope)
        self._hidden_size = hidden_size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._gate_activation = gate_activation or layers.sigmoid
        self._activation = activation or layers.tanh
        self._forget_bias = float(forget_bias)
        self._dtype = dtype
        self._built = False

    def build_once(self, input_size):
        if self._built:
            return
        h = self._hidden_size
        self._weight = layers.create_parameter(
            [input_size + h, 4 * h], dtype=self._dtype,
            name=self._name + "_w", attr=self._param_attr)
        self._bias = layers.create_parameter(
            [4 * h], dtype=self._dtype, name=self._name + "_b",
            attr=self._bias_attr, is_bias=True)
        self._built = True

    def __call__(self, input, pre_hidden, pre_cell):
        if not self._built:
            self.build_once(int(input.shape[-1]))
        concat = layers.concat([input, pre_hidden], axis=1)
        gate_input = layers.elementwise_add(
            layers.matmul(concat, self._weight), self._bias)
        i, j, f, o = layers.split(gate_input, num_or_sections=4, dim=1)
        new_cell = layers.elementwise_add(
            layers.elementwise_mul(
                pre_cell,
                self._gate_activation(f + self._forget_bias)),
            layers.elementwise_mul(self._gate_activation(i),
                                   self._activation(j)))
        new_hidden = layers.elementwise_mul(
            self._activation(new_cell), self._gate_activation(o))
        return new_hidden, new_cell


def _mask_per_step(sequence_length, seq_len, dtype):
    """[T, batch, 1] 0/1 mask, time-major."""
    mask = layers.sequence_mask(sequence_length, maxlen=seq_len,
                                dtype=dtype)                    # [B, T]
    return layers.unsqueeze(layers.transpose(mask, [1, 0]), [2])


def _run_direction(unit_fn, step_in, init_states, mask, seq_len):
    """One scan: unit_fn(x_t, *states) → new states tuple; masked carry."""
    rnn = layers.StaticRNN()
    with rnn.step():
        x_t = rnn.step_input(step_in)
        mems = [rnn.memory(init=s) for s in init_states]
        new_states = unit_fn(x_t, *mems)
        if not isinstance(new_states, tuple):
            new_states = (new_states,)
        if mask is not None:
            m_t = rnn.step_input(mask)
            new_states = tuple(
                layers.elementwise_add(
                    layers.elementwise_mul(ns, m_t),
                    layers.elementwise_mul(pm, 1.0 - m_t))
                for ns, pm in zip(new_states, mems))
        for pm, ns in zip(mems, new_states):
            rnn.update_memory(pm, ns)
        rnn.step_output(new_states[0])
        for ns in new_states:
            rnn.step_output(ns)
    outs = rnn()
    outs = outs if isinstance(outs, (list, tuple)) else [outs]
    seq_out = outs[0]                               # [T, B, H]
    finals = [layers.slice(o, axes=[0], starts=[seq_len - 1],
                           ends=[seq_len])
              for o in outs[1:]]
    finals = [layers.squeeze(f, axes=[0]) for f in finals]
    return seq_out, finals


def _stack_rnn(make_unit, n_states, input, init_states, hidden_size,
               num_layers, sequence_length, dropout_prob, bidirectional,
               batch_first, dtype):
    """Shared driver for basic_gru/basic_lstm."""
    if batch_first:
        input = layers.transpose(input, [1, 0, 2])       # → [T, B, in]
    seq_len = int(input.shape[0])
    mask = None
    if sequence_length is not None:
        mask = _mask_per_step(sequence_length, seq_len, dtype)
    directions = 2 if bidirectional else 1

    # init_states[k]: [num_layers*dirs, batch, hidden] or None
    def init_of(k, layer, direction):
        if init_states[k] is None:
            shape = [1, int(input.shape[1]), hidden_size]
            z = layers.fill_constant_batch_size_like(
                input, shape=[-1, hidden_size], dtype=dtype, value=0.0,
                input_dim_idx=1, output_dim_idx=0)
            return z
        idx = layer * directions + direction
        s = layers.slice(init_states[k], axes=[0], starts=[idx],
                         ends=[idx + 1])
        return layers.squeeze(s, axes=[0])

    layer_in = input
    in_size = int(input.shape[-1])
    last_states = [[] for _ in range(n_states)]
    for layer in range(num_layers):
        dir_outs = []
        for direction in range(directions):
            unit = make_unit(layer, direction)
            # params built OUTSIDE the scan body, with a static input size
            # (step vars lose shape inference inside the sub-block)
            unit.build_once(in_size)
            x = layer_in if direction == 0 else \
                layers.reverse(layer_in, axis=[0])
            m = mask if direction == 0 else (
                layers.reverse(mask, axis=[0]) if mask is not None else None)
            seq_out, finals = _run_direction(
                unit, x, [init_of(k, layer, direction)
                          for k in range(n_states)], m, seq_len)
            if direction == 1:
                seq_out = layers.reverse(seq_out, axis=[0])
            dir_outs.append(seq_out)
            for k in range(n_states):
                last_states[k].append(finals[k])
        layer_in = dir_outs[0] if directions == 1 else \
            layers.concat(dir_outs, axis=2)
        in_size = hidden_size * directions
        if dropout_prob > 0.0 and layer != num_layers - 1:
            layer_in = layers.dropout(layer_in, dropout_prob)

    rnn_out = layer_in                                   # [T, B, H*dirs]
    if batch_first:
        rnn_out = layers.transpose(rnn_out, [1, 0, 2])
    finals = [layers.stack(st, axis=0) for st in last_states]
    return rnn_out, finals


def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0, bidirectional=False,
              batch_first=True, param_attr=None, bias_attr=None,
              gate_activation=None, activation=None, dtype="float32",
              name="basic_gru"):
    """ref rnn_impl.py:139 — returns (rnn_out, last_hidden)."""
    def make_unit(layer, direction):
        return BasicGRUUnit(
            f"{name}_l{layer}_d{direction}", hidden_size,
            _sub_attr(param_attr, layer, direction),
            _sub_attr(bias_attr, layer, direction),
            gate_activation, activation, dtype)
    rnn_out, (last_hidden,) = _stack_rnn(
        make_unit, 1, input, [init_hidden], hidden_size, num_layers,
        sequence_length, dropout_prob, bidirectional, batch_first, dtype)
    return rnn_out, last_hidden


def basic_lstm(input, init_hidden, init_cell, hidden_size, num_layers=1,
               sequence_length=None, dropout_prob=0.0, bidirectional=False,
               batch_first=True, param_attr=None, bias_attr=None,
               gate_activation=None, activation=None, forget_bias=1.0,
               dtype="float32", name="basic_lstm"):
    """ref rnn_impl.py:353 — returns (rnn_out, last_hidden, last_cell)."""
    def make_unit(layer, direction):
        return BasicLSTMUnit(
            f"{name}_l{layer}_d{direction}", hidden_size,
            _sub_attr(param_attr, layer, direction),
            _sub_attr(bias_attr, layer, direction),
            gate_activation, activation, forget_bias, dtype)
    rnn_out, (last_hidden, last_cell) = _stack_rnn(
        make_unit, 2, input, [init_hidden, init_cell], hidden_size,
        num_layers, sequence_length, dropout_prob, bidirectional,
        batch_first, dtype)
    return rnn_out, last_hidden, last_cell


def _sub_attr(attr, layer, direction):
    """Per-layer param attr names (ref rnn_impl.py name mangling)."""
    if attr is None or not isinstance(attr, ParamAttr) or attr.name is None:
        return attr
    return ParamAttr(name=f"{attr.name}_l{layer}_d{direction}",
                     initializer=attr.initializer)
