"""Contrib metric layers (ref ``python/paddle/fluid/contrib/layers/
metric_op.py``)."""

from __future__ import annotations

from ... import layers

__all__ = ["ctr_metric_bundle"]


def ctr_metric_bundle(input, label):
    """CTR metric bundle (ref metric_op.py:30): returns
    (local_sqrerr, local_abserr, local_prob, local_q) accumulator-style
    sums a CTR trainer aggregates across batches/workers."""
    sub = layers.elementwise_sub(input, label)
    sqrerr = layers.reduce_sum(layers.square(sub))
    abserr = layers.reduce_sum(layers.abs(sub))
    prob = layers.reduce_sum(input)
    q = layers.reduce_sum(layers.elementwise_mul(input, label))
    return sqrerr, abserr, prob, q
