"""Contrib layer collection (ref ``python/paddle/fluid/contrib/layers/``)."""

from .metric_op import ctr_metric_bundle  # noqa
from .nn import fused_elemwise_activation  # noqa
from .rnn_impl import (BasicGRUUnit, BasicLSTMUnit, basic_gru,  # noqa
                       basic_lstm)
