"""Contrib NN layers (ref ``python/paddle/fluid/contrib/layers/nn.py``)."""

from __future__ import annotations

from ... import layers
from ...layer_helper import LayerHelper

__all__ = ["fused_elemwise_activation"]


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """ref contrib/layers/nn.py fused_elemwise_activation → the
    fused_elemwise_activation op (XLA fuses the chain anyway; the op keeps
    the exact fluid semantics incl. the intermediate output)."""
    if isinstance(functor_list, str):
        functor_list = [functor_list]
    helper = LayerHelper("fused_elemwise_activation")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    intermediate = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="fused_elemwise_activation",
        inputs={"X": x, "Y": y},
        outputs={"Out": out, "IntermediateOut": intermediate},
        attrs={"axis": axis, "scale": scale,
               "functor_list": list(functor_list),
               "save_intermediate_out": bool(save_intermediate_out)})
    return out
