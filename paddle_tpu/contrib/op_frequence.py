"""Op-frequency statistics over programs (ref
``python/paddle/fluid/contrib/op_frequence.py`` op_freq_statistic)."""

from __future__ import annotations

from collections import Counter

from ..framework import core

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program: core.Program):
    """Returns (uni_op_freq, adj_op_freq): single-op counts and adjacent
    op-pair counts over the whole program (the reference uses these to
    prioritize fusion-pass work)."""
    uni = Counter()
    adj = Counter()
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] += 1
            if prev is not None:
                adj[f"{prev}->{op.type}"] += 1
            prev = op.type
    return uni, adj
