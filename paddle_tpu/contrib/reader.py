"""Distributed reader decorator (ref ``python/paddle/fluid/contrib/reader/
distributed_reader.py``): shard a batch reader across trainers by stride so
each process sees a disjoint slice of the stream."""

from __future__ import annotations

import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    """Each trainer keeps every num_trainers-th batch, offset by its id
    (env contract PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM, same as the
    launcher's)."""
    trainer_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    trainers_num = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    def decorated():
        for idx, batch in enumerate(batch_reader()):
            if idx % trainers_num == trainer_id:
                yield batch

    return decorated
