"""Model summary: per-layer params + FLOPs table (ref
``python/paddle/fluid/contrib/model_stat.py`` summary())."""

from __future__ import annotations

import numpy as np

from ..framework import core

__all__ = ["summary"]

def _numel(shape):
    return int(np.prod([d for d in (shape or []) if d and d > 0])) if shape \
        else 0


def _op_stats(op, block, batch_size):
    """(params, flops) for one op; conv/fc/matmul carry the MXU work.
    A dynamic (-1) batch dim counts as ``batch_size`` samples."""
    def shape(name):
        return block.var(name).shape if block.has_var(name) else None

    def batched_numel(s):
        if not s:
            return 0
        n = _numel(s)
        return n * batch_size if s[0] in (-1, None) else n

    if op.type in ("conv2d", "depthwise_conv2d"):
        w = shape(op.input("Filter")[0])
        out = shape(op.output("Output")[0])
        if w and out:
            params = _numel(w)
            flops = 2 * params // max(w[0], 1) * batched_numel(out)
            return params, flops
    elif op.type == "mul":
        w = shape(op.input("Y")[0])
        x = shape(op.input("X")[0])
        if w and x:
            params = _numel(w)
            batch = batch_size if x[0] in (-1, None) else abs(x[0])
            return params, 2 * params * batch
    elif op.type == "matmul":
        x, y = shape(op.input("X")[0]), shape(op.input("Y")[0])
        if x and y:
            m = batched_numel(x[:-1])
            k = abs(x[-1])
            return 0, 2 * m * k * abs(y[-1])
    elif op.type in ("elementwise_add", "relu", "batch_norm", "softmax"):
        outs = op.output_arg_names()
        if outs:
            o = shape(outs[0])
            return (0, batched_numel(o)) if o else (0, 0)
    return 0, 0


def summary(program: core.Program, batch_size: int = 1) -> str:
    """Printable table + returns the text; also usable as
    ``summary(main_program)`` right after building (ref model_stat usage).
    ``batch_size`` scales FLOPs of dynamic (-1) batch dims."""
    block = program.global_block()
    rows = []
    total_p = total_f = 0
    for op in block.ops:
        if op.type.endswith("_grad"):
            continue
        p, f = _op_stats(op, block, batch_size)
        total_p += p
        total_f += f
        if p or f:
            rows.append((op.type, p, f))
    width = max([len(r[0]) for r in rows], default=8) + 2
    lines = [f"{'op':<{width}}{'params':>14}{'FLOPs':>16}", "-" * (width + 30)]
    for t, p, f in rows:
        lines.append(f"{t:<{width}}{p:>14,}{f:>16,}")
    lines.append("-" * (width + 30))
    lines.append(f"{'total':<{width}}{total_p:>14,}{total_f:>16,}")
    text = "\n".join(lines)
    return text
