"""Half-precision inference transpiler (ref ``paddle/contrib/float16/
float16_transpiler.py`` Float16Transpiler: rewrite a saved *inference*
program to fp16 — params converted in place, cast ops inserted at the
boundaries, feed/fetch kept fp32).

TPU-native notes: bfloat16 is the hardware-native half type (MXU ingests
bf16 at full rate), so ``target_dtype`` defaults to bf16 while fp16 is
kept for reference parity.  Casts are only emitted at precision
boundaries; XLA fuses them into the adjacent kernels, so the transpiled
program's memory traffic — the usual inference bottleneck — halves."""

from __future__ import annotations

import numpy as np

from ..framework.core import Operator, Program

__all__ = ["Float16Transpiler"]

#: ops executed in half precision (ref float16_transpiler.py
#: fp16-capable set; bn stats stay fp32 like the cudnn path)
HALF_OPS = ("mul", "matmul", "conv2d", "depthwise_conv2d", "fc",
            "elementwise_add", "elementwise_mul", "relu", "relu6",
            "leaky_relu", "pool2d", "softmax", "concat", "transpose2",
            "reshape2", "scale")


class Float16Transpiler:
    def transpile(self, program: Program, place=None, scope=None,
                  target_dtype: str = "bfloat16"):
        """Rewrite ``program`` IN PLACE for half-precision inference.

        scope: holds the fp32 params to convert (default global scope).
        target_dtype: 'bfloat16' (TPU-native) or 'float16'."""
        from ..framework.scope import global_scope
        if target_dtype not in ("float16", "bfloat16"):
            raise ValueError(f"bad target_dtype {target_dtype!r}")
        scope = scope or global_scope()
        block = program.global_block()

        # 1. convert params consumed only by half-capable, non-affine slots
        consumers = {}
        for op in block.ops:
            for name in op.input_arg_names():
                consumers.setdefault(name, []).append(op)
        converted = set()
        for var in list(block.vars.values()):
            if not var.persistable or var.dtype != "float32":
                continue
            ops = consumers.get(var.name, [])
            if ops and all(o.type in HALF_OPS and
                           not self._is_affine_param(o, var.name)
                           for o in ops):
                value = scope.find_var(var.name)
                if value is None:
                    continue
                arr = np.asarray(value)
                if target_dtype == "float16":
                    scope.set_var(var.name, arr.astype(np.float16))
                else:
                    import jax.numpy as jnp
                    scope.set_var(var.name, jnp.asarray(arr, jnp.bfloat16))
                var.dtype = target_dtype
                converted.add(var.name)

        # 2. insert casts at precision boundaries
        half_out = set(converted)
        new_ops = []
        cast_cache = {}

        def cast_to(name, dtype):
            """Var holding ``name`` cast to ``dtype``; emits the cast op
            (into new_ops, i.e. right before the first use) once."""
            key = (name, dtype)
            if key in cast_cache:
                return cast_cache[key]
            src = block.var(name)
            out = block.create_var(
                name=f"{name}.cast_{dtype[:4]}",
                shape=src.shape, dtype=dtype)
            op = Operator(block, "cast", {"X": [name]}, {"Out": [out.name]},
                          {"in_dtype": src.dtype, "out_dtype": dtype})
            new_ops.append(op)
            cast_cache[key] = out.name
            return out.name

        for op in block.ops:
            if op.type in HALF_OPS:
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [
                        cast_to(n, target_dtype)
                        if (n and block.has_var(n)
                            and block.var(n).dtype == "float32"
                            and not self._is_affine_param(op, n))
                        else n
                        for n in names]
                for names in op.outputs.values():
                    for n in names:
                        if n and block.has_var(n) and \
                                not block.var(n).persistable:
                            block.var(n).dtype = target_dtype
                            half_out.add(n)
            else:
                # full-precision op: cast any half inputs back to fp32
                for slot, names in op.inputs.items():
                    op.inputs[slot] = [
                        cast_to(n, "float32")
                        if (n in half_out and block.has_var(n)
                            and block.var(n).dtype == target_dtype)
                        else n
                        for n in names]
            new_ops.append(op)
        block.ops = new_ops

        # fetch contract: graph sinks go back to fp32 under their ORIGINAL
        # names (ref _modify_feed_fetch keeps feed/fetch fp32) — the
        # producer is renamed to <n>.half and a final cast restores <n>
        consumed = set()
        for op in block.ops:
            consumed.update(op.input_arg_names())
        for n in sorted(half_out):
            if n in consumed or not block.has_var(n) or \
                    block.var(n).dtype != target_dtype:
                continue
            v = block.var(n)
            half_name = n + ".half"
            block.create_var(name=half_name, shape=v.shape,
                             dtype=target_dtype)
            for op in block.ops:
                for slot, names in op.outputs.items():
                    op.outputs[slot] = [half_name if m == n else m
                                        for m in names]
            v.dtype = "float32"
            block.ops.append(Operator(
                block, "cast", {"X": [half_name]}, {"Out": [n]},
                {"in_dtype": target_dtype, "out_dtype": "float32"}))
        program._bump_version()
        return program

    @staticmethod
    def _is_affine_param(op, name):
        """bn-style affine/stats stay fp32 (ref: cudnn bn takes fp32
        scale/bias even in fp16 mode)."""
        for slot in ("Scale", "Bias", "Mean", "Variance"):
            if name in (op.inputs.get(slot) or []):
                return True
        return False
