"""High-level Trainer API (ref ``python/paddle/fluid/contrib/trainer.py``:
Trainer(train_func, optimizer_func) with epoch/step events, checkpointing,
test(), save_params/save_inference_model; the book-chapter fluent API).

The train loop compiles to the same single jitted block as the raw
Executor path — the event callbacks run host-side between steps and only
the metrics the handler asked for are fetched (BeginStepEvent.fetch_metrics
gates the device→host transfer, same as the reference)."""

from __future__ import annotations

import os
from typing import Callable, List, Optional, Sequence

from .. import io as pio
from ..data.feeder import DataFeeder
from ..framework import core, unique_name
from ..framework.core import Program, Variable, program_guard
from ..framework.executor import Executor
from ..framework.scope import Scope, scope_guard

__all__ = ["BeginEpochEvent", "EndEpochEvent", "BeginStepEvent",
           "EndStepEvent", "CheckpointConfig", "Trainer"]


class BeginEpochEvent:
    """ref trainer.py:40."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class EndEpochEvent:
    """ref trainer.py:52."""

    def __init__(self, epoch_id):
        self.epoch = epoch_id


class BeginStepEvent:
    """ref trainer.py:64; set ``fetch_metrics=False`` to skip the
    device→host metric transfer for this step."""

    def __init__(self, epoch_id, step_id):
        self.epoch = epoch_id
        self.step = step_id
        self.fetch_metrics = True


class EndStepEvent:
    """ref trainer.py:83."""

    def __init__(self, epoch_id, step_id, metrics):
        self.epoch = epoch_id
        self.step = step_id
        self.metrics = metrics


class CheckpointConfig:
    """ref trainer.py:100."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 max_num_checkpoints: int = 3, epoch_interval: int = 1,
                 step_interval: int = 10):
        self.checkpoint_dir = checkpoint_dir or \
            os.path.join(os.getcwd(), "checkpoints")
        self.max_num_checkpoints = max_num_checkpoints
        self.epoch_interval = max(1, epoch_interval)
        self.step_interval = max(1, step_interval)
        self.epoch_id = 0
        self.step_id = 0


class Trainer:
    """ref trainer.py:169.

    train_func: () → loss Variable or [loss, *metrics]
    optimizer_func: () → Optimizer
    """

    def __init__(self, train_func: Callable, optimizer_func: Callable,
                 param_path: Optional[str] = None, place=None,
                 parallel: bool = False,
                 checkpoint_config: Optional[CheckpointConfig] = None):
        self.place = place
        self.parallel = parallel
        self.checkpoint_cfg = checkpoint_config
        self.scope = Scope()
        self.startup_program = Program()
        self.train_program = Program()
        self.__stop = False

        with program_guard(self.train_program, self.startup_program), \
                unique_name.guard():
            outs = train_func()
            if isinstance(outs, Variable):
                outs = [outs]
            self.train_func_outputs: List[Variable] = list(outs)
            loss = outs[0]
            optimizer = optimizer_func()
            optimizer.minimize(loss, startup_program=self.startup_program)
        self.test_program = self.train_program.clone(for_test=True)

        self.exe = Executor(place)
        with self._prog_and_scope_guard():
            self.exe.run(self.startup_program, scope=self.scope,
                         fetch_list=[])
        if param_path and os.path.isdir(param_path):
            pio.load_persistables(self.exe, dirname=param_path,
                                  main_program=self.startup_program,
                                  scope=self.scope)
        if self.checkpoint_cfg:
            self._load_checkpoint()

    def _prog_and_scope_guard(self):
        import contextlib

        @contextlib.contextmanager
        def guard():
            with program_guard(self.train_program, self.startup_program), \
                    scope_guard(self.scope):
                yield
        return guard()

    def stop(self):
        """ref trainer.py:373 — stop training at the next step."""
        self.__stop = True

    # -- train/test ----------------------------------------------------------
    def train(self, num_epochs: int, event_handler: Callable,
              reader=None, feed_order: Optional[Sequence[str]] = None):
        """ref trainer.py:379."""
        feed_vars = _feed_var_list(self.train_program, feed_order)
        feeder = DataFeeder(feed_vars, self.place)
        fetch = [v.name for v in self.train_func_outputs]
        start_epoch = self.checkpoint_cfg.epoch_id if self.checkpoint_cfg \
            else 0
        for epoch_id in range(start_epoch, num_epochs):
            event_handler(BeginEpochEvent(epoch_id))
            for step_id, data in enumerate(reader()):
                if self.__stop:
                    if self.checkpoint_cfg:
                        self._save_checkpoint(epoch_id, step_id)
                    return
                begin = BeginStepEvent(epoch_id, step_id)
                event_handler(begin)
                metrics = self.exe.run(
                    self.train_program, feed=feeder.feed(data),
                    fetch_list=fetch if begin.fetch_metrics else [],
                    scope=self.scope)
                event_handler(EndStepEvent(epoch_id, step_id, metrics))
                if self.checkpoint_cfg and \
                        step_id % self.checkpoint_cfg.step_interval == 0:
                    self._save_checkpoint(epoch_id, step_id)
            event_handler(EndEpochEvent(epoch_id))
            if self.checkpoint_cfg and \
                    epoch_id % self.checkpoint_cfg.epoch_interval == 0:
                self._save_checkpoint(epoch_id, 0)

    def test(self, reader, feed_order: Optional[Sequence[str]] = None):
        """Mean of the train_func metrics over the reader (ref
        trainer.py:407)."""
        import numpy as np
        feed_vars = _feed_var_list(self.test_program, feed_order)
        feeder = DataFeeder(feed_vars, self.place)
        fetch = [v.name for v in self.train_func_outputs]
        totals = np.zeros(len(fetch), np.float64)
        count = 0
        for data in reader():
            outs = self.exe.run(self.test_program, feed=feeder.feed(data),
                                fetch_list=fetch, scope=self.scope)
            totals += [float(np.asarray(o).mean()) for o in outs]
            count += 1
        return (totals / max(count, 1)).tolist()

    # -- persistence ---------------------------------------------------------
    def save_params(self, param_path: str):
        """ref trainer.py:420."""
        with self._prog_and_scope_guard():
            pio.save_persistables(self.exe, dirname=param_path,
                                  scope=self.scope)

    def save_inference_model(self, param_path: str,
                             feeded_var_names: Sequence[str],
                             target_var_indexes: Sequence[int]):
        """ref trainer.py:434 — targets picked from train_func outputs by
        index."""
        with self._prog_and_scope_guard():
            pio.save_inference_model(
                param_path, feeded_var_names,
                [self.train_func_outputs[i] for i in target_var_indexes],
                self.exe, main_program=self.train_program,
                scope=self.scope)

    # -- checkpoints ---------------------------------------------------------
    def _ckpt_dir(self, serial):
        return os.path.join(self.checkpoint_cfg.checkpoint_dir, str(serial))

    def _save_checkpoint(self, epoch_id, step_id):
        cfg = self.checkpoint_cfg
        path = self._ckpt_dir(epoch_id)
        pio.save_persistables(self.exe, dirname=path,
                              main_program=self.train_program,
                              scope=self.scope)
        with open(os.path.join(path, "__meta__"), "w") as f:
            f.write(f"{epoch_id} {step_id}")
        serials = sorted(int(d) for d in os.listdir(cfg.checkpoint_dir)
                         if d.isdigit())
        for old in serials[:-cfg.max_num_checkpoints]:
            import shutil
            shutil.rmtree(self._ckpt_dir(old), ignore_errors=True)

    def _load_checkpoint(self):
        cfg = self.checkpoint_cfg
        if not os.path.isdir(cfg.checkpoint_dir):
            return
        serials = sorted(int(d) for d in os.listdir(cfg.checkpoint_dir)
                         if d.isdigit())
        if not serials:
            return
        path = self._ckpt_dir(serials[-1])
        pio.load_persistables(self.exe, dirname=path,
                              main_program=self.train_program,
                              scope=self.scope)
        with open(os.path.join(path, "__meta__")) as f:
            epoch_id, step_id = map(int, f.read().split())
        cfg.epoch_id = epoch_id
        cfg.step_id = step_id


def _feed_var_list(program: Program, feed_order) -> List[Variable]:
    """ref trainer.py:630 build_feed_var_list."""
    block = program.global_block()
    if feed_order is None:
        feed_order = [v.name for v in block.vars.values()
                      if getattr(v, "is_data", False)]
        if not feed_order:
            raise ValueError("pass feed_order: the program declares no "
                             "data vars to infer it from")
    if isinstance(feed_order, dict):
        feed_order = [n for n, _ in
                      sorted(feed_order.items(), key=lambda kv: kv[1])]
    return [block.var(n) for n in feed_order]
