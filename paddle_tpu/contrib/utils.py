"""Contrib utilities (ref ``python/paddle/fluid/contrib/utils/``:
hdfs_utils.py HDFSClient + multi_download/multi_upload shell wrappers,
lookup_table_utils.py PS lookup-table checkpoint surgery).

HDFSClient drives the ``hadoop fs`` CLI exactly as the reference does (the
native runtime's fs layer shells out the same way, ref framework/io/
shell.h); without a hadoop binary every call raises a clear error, so the
API is importable/configurable on any box and functional where hadoop
exists."""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import List, Optional

__all__ = ["HDFSClient", "multi_download", "multi_upload",
           "convert_dist_to_sparse_program"]


class HDFSClient:
    """ref hdfs_utils.py HDFSClient — thin ``hadoop fs`` process wrapper."""

    def __init__(self, hadoop_home: str, configs: Optional[dict] = None):
        self.pre_commands: List[str] = []
        hadoop_bin = os.path.join(hadoop_home, "bin", "hadoop")
        self.pre_commands.append(hadoop_bin)
        self.pre_commands.append("fs")
        for k, v in (configs or {}).items():
            self.pre_commands += ["-D", f"{k}={v}"]
        self._available = os.path.exists(hadoop_bin) or \
            shutil.which(hadoop_bin) is not None

    def _run(self, commands: List[str], retry_times: int = 5):
        if not self._available:
            raise RuntimeError(
                f"hadoop binary {self.pre_commands[0]!r} not found; "
                "HDFSClient needs a hadoop installation")
        whole = self.pre_commands + commands
        last = None
        for _ in range(max(1, retry_times)):
            proc = subprocess.run(whole, capture_output=True, text=True)
            if proc.returncode == 0:
                return True, proc.stdout
            last = proc.stderr
        return False, last

    def is_exist(self, hdfs_path) -> bool:
        ok, _ = self._run(["-test", "-e", hdfs_path], retry_times=1)
        return ok

    def is_dir(self, hdfs_path) -> bool:
        ok, _ = self._run(["-test", "-d", hdfs_path], retry_times=1)
        return ok

    def delete(self, hdfs_path) -> bool:
        ok, _ = self._run(["-rm", "-r", "-skipTrash", hdfs_path])
        return ok

    def rename(self, hdfs_src_path, hdfs_dst_path, overwrite=False) -> bool:
        if overwrite and self.is_exist(hdfs_dst_path):
            self.delete(hdfs_dst_path)
        ok, _ = self._run(["-mv", hdfs_src_path, hdfs_dst_path])
        return ok

    def makedirs(self, hdfs_path) -> bool:
        ok, _ = self._run(["-mkdir", "-p", hdfs_path])
        return ok

    def ls(self, hdfs_path) -> List[str]:
        ok, out = self._run(["-ls", hdfs_path])
        if not ok:
            return []
        return [line.split()[-1] for line in out.splitlines()
                if line and not line.startswith("Found")]

    def lsr(self, hdfs_path) -> List[str]:
        ok, out = self._run(["-ls", "-R", hdfs_path])
        if not ok:
            return []
        return [line.split()[-1] for line in out.splitlines()
                if line and not line.startswith("Found")]

    def upload(self, hdfs_path, local_path, overwrite=False,
               retry_times=5) -> bool:
        if overwrite and self.is_exist(hdfs_path):
            self.delete(hdfs_path)
        ok, _ = self._run(["-put", local_path, hdfs_path], retry_times)
        return ok

    def download(self, hdfs_path, local_path, overwrite=False,
                 unzip=False) -> bool:
        if overwrite and os.path.exists(local_path):
            shutil.rmtree(local_path, ignore_errors=True)
        ok, _ = self._run(["-get", hdfs_path, local_path])
        return ok


def multi_download(client: HDFSClient, hdfs_path: str, local_path: str,
                   trainer_id: int, trainers: int, multi_processes: int = 5):
    """Shard-aware download: trainer i pulls every trainers-th file (ref
    hdfs_utils.py multi_download)."""
    files = sorted(client.lsr(hdfs_path))
    mine = files[trainer_id::max(trainers, 1)]
    out = []
    os.makedirs(local_path, exist_ok=True)
    for f in mine:
        dst = os.path.join(local_path, os.path.basename(f))
        if client.download(f, dst):
            out.append(dst)
    return out


def multi_upload(client: HDFSClient, hdfs_path: str, local_path: str,
                 multi_processes: int = 5, overwrite: bool = False):
    """Upload every file under local_path (ref hdfs_utils.py
    multi_upload)."""
    uploaded = []
    for root, _, names in os.walk(local_path):
        for name in names:
            src = os.path.join(root, name)
            rel = os.path.relpath(src, local_path)
            dst = os.path.join(hdfs_path, rel)
            client.makedirs(os.path.dirname(dst))
            if client.upload(dst, src, overwrite=overwrite):
                uploaded.append(dst)
    return uploaded


def convert_dist_to_sparse_program(program):
    """ref lookup_table_utils.py convert_dist_to_sparse_program: turn the
    PS-transpiled trainer program's distributed_lookup_table pulls back
    into local sparse lookup_table ops (for single-box inference over a
    model trained on a PS cluster)."""
    block = program.global_block()
    for op in block.ops:
        if op.type == "distributed_lookup_table":
            op.type = "lookup_table"
            op.attrs.pop("table_names", None)
            op.attrs.pop("endpoints", None)
            op.attrs["is_distributed"] = False
            op.attrs["is_sparse"] = True
    program._bump_version()
    return program
