"""Int8 quantization transpiler (ref ``python/paddle/fluid/contrib/
quantize/quantize_transpiler.py`` QuantizeTranspiler: training_transpile
inserts fake quant/dequant before minimize, freeze_program bakes trained
scales for int8 inference).

This is the pre-slim program-level API; the heavy lifting is shared with
``contrib.slim.quantization`` — the same QDQ op rewrite and freeze pass,
exposed under the transpiler names the reference ships."""

from __future__ import annotations

from typing import Optional

from ..framework import core
from ..framework.core import Program
from .slim.quantization import (QuantizationFreezePass,
                                QuantizationTransformPass)

__all__ = ["QuantizeTranspiler"]


class QuantizeTranspiler:
    """ref quantize_transpiler.py:80."""

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "abs_max",
                 weight_quantize_type: str = "abs_max",
                 window_size: int = 10000, moving_rate: float = 0.9):
        if activation_quantize_type == "range_abs_max":
            # the windowed tracker trains the same EMA-style scale; map to
            # the moving-average QDQ op family
            activation_quantize_type = "moving_average_abs_max"
        self._transform = QuantizationTransformPass(
            weight_bits, activation_bits, activation_quantize_type,
            weight_quantize_type, moving_rate)
        self._wbits = weight_bits
        self._w_type = weight_quantize_type

    def training_transpile(self, program: Optional[Program] = None,
                           startup_program: Optional[Program] = None):
        """Insert QDQ training ops; call BEFORE optimizer.minimize (ref
        quantize_transpiler.py:146)."""
        self._transform.apply(program, startup_program)

    def freeze_program(self, program: Program, place=None, scope=None):
        """Bake trained scales for inference (ref
        quantize_transpiler.py:223)."""
        from ..framework.scope import global_scope
        return QuantizationFreezePass(
            scope or global_scope(), self._wbits, self._w_type).apply(program)
