"""Estimate training memory (ref ``python/paddle/fluid/contrib/
memory_usage_calc.py`` memory_usage): sums var sizes in a program for a
given batch size.  Under the block compiler, actual peak memory is XLA's
buffer assignment; this is the same build-time estimate the reference
gives."""

from __future__ import annotations


__all__ = ["memory_usage"]

DTYPE_SIZES = {"float64": 8, "int64": 8, "float32": 4, "int32": 4,
               "float16": 2, "bfloat16": 2, "int16": 2, "uint8": 1,
               "int8": 1, "bool": 1}


def memory_usage(program, batch_size=1, unit="MB"):
    """Returns (lower_bound, upper_bound, unit_str) like the reference
    (upper adds a 1.5x slack for temporaries)."""
    total = 0.0
    for var in program.list_vars():
        if var.shape is None:
            continue
        numel = 1
        for d in var.shape:
            numel *= batch_size if d in (-1, None) else d
        total += numel * DTYPE_SIZES.get(var.dtype, 4)
    units = {"B": 1, "KB": 2 ** 10, "MB": 2 ** 20, "GB": 2 ** 30}
    key = str(unit).upper()
    if key not in units:
        raise ValueError(f"unit must be one of {sorted(units)}, got "
                         f"{unit!r}")
    div = units[key]
    low = total / div
    return low, low * 1.5, unit
