"""Contrib subpackages (ref ``python/paddle/fluid/contrib/``)."""

from . import memory_usage_calc, model_stat, op_frequence, slim  # noqa
