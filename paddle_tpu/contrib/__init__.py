"""Contrib subpackages (ref ``python/paddle/fluid/contrib/``)."""

from . import (decoder, extend_optimizer, layers,  # noqa
               memory_usage_calc, model_stat, op_frequence, quantize,
               reader, slim, utils)
from .extend_optimizer import extend_with_decoupled_weight_decay  # noqa
from .float16_transpiler import Float16Transpiler  # noqa
from .inferencer import Inferencer  # noqa
from .quantize import QuantizeTranspiler  # noqa
from .reader import distributed_batch_reader  # noqa
from .trainer import (BeginEpochEvent, BeginStepEvent,  # noqa
                      CheckpointConfig, EndEpochEvent, EndStepEvent,
                      Trainer)
