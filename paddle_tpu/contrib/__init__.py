"""Contrib subpackages (ref ``python/paddle/fluid/contrib/``)."""

from . import slim  # noqa
