"""Contrib subpackages (ref ``python/paddle/fluid/contrib/``)."""

from . import model_stat, op_frequence, slim  # noqa
