"""Decoupled weight decay optimizer extension (ref ``python/paddle/fluid/
contrib/extend_optimizer/extend_optimizer_with_weight_decay.py``):
``extend_with_decoupled_weight_decay(Adam)`` returns an AdamW-style class
whose minimize subtracts ``coeff * param`` from each parameter *outside*
the gradient-based update (Loshchilov & Hutter decoupling)."""

from __future__ import annotations

from .. import layers
from ..framework.core import Variable
from ..optimizer import Optimizer

__all__ = ["extend_with_decoupled_weight_decay"]


class DecoupledWeightDecay:
    """Mixin applied in front of a concrete Optimizer class
    (ref extend_optimizer_with_weight_decay.py:20)."""

    def __init__(self, coeff=0.0, apply_decay_param_fun=None, **kwargs):
        if not isinstance(coeff, (float, Variable)):
            raise TypeError("coeff should be float or Variable.")
        self._params_name = set()
        self._apply_decay_param_fun = apply_decay_param_fun
        self._coeff = coeff
        super().__init__(**kwargs)

    def _scale_parameters(self, params_and_grads):
        """(param, grad, param*coeff) triples for params that decay."""
        if isinstance(self._coeff, float) and self._coeff == 0.0:
            return []
        scaled = []
        for param, grad in params_and_grads:
            if grad is None:
                continue
            if self._apply_decay_param_fun is not None and \
                    not self._apply_decay_param_fun(param.name):
                continue
            scaled.append((param, grad, param * self._coeff))
            self._params_name.add(param.name)
        return scaled

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(loss, startup_program, parameter_list,
                                     no_grad_set)
        # decay BEFORE the update, decoupled from the gradient path; tagged
        # optimize so clone(for_test=True) prunes it with the rest
        with loss.block.program._op_role_guard("optimize"):
            for param, grad, scaled in self._scale_parameters(params_grads):
                updated = layers.elementwise_sub(x=param, y=scaled)
                layers.assign(input=updated, output=param)
        optimize_ops = self.apply_optimize(loss, startup_program,
                                           params_grads)
        return optimize_ops, params_grads

    def __str__(self):
        return " ".join(["Weight Decay, params:",
                         ",".join(self._params_name)])


def extend_with_decoupled_weight_decay(base_optimizer):
    """Build an AdamW-style class from any Optimizer subclass (ref
    extend_optimizer_with_weight_decay.py:102).

    >>> AdamW = extend_with_decoupled_weight_decay(fluid.optimizer.Adam)
    >>> optimizer = AdamW(learning_rate=1e-3, coeff=0.01)
    """
    if not issubclass(base_optimizer, Optimizer):
        raise TypeError(
            "The input(base_optimizer) should be a derived class of "
            "Optimizer.")

    class OptimizerWithDecoupledWeightDecay(DecoupledWeightDecay,
                                            base_optimizer):
        def __init__(self, weight_decay=None, coeff=None, **kwargs):
            if coeff is None:
                coeff = 0.0 if weight_decay is None else weight_decay
            super().__init__(coeff=coeff, **kwargs)

    return OptimizerWithDecoupledWeightDecay
