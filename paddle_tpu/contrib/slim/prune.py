"""Structured filter pruning (ref ``python/paddle/fluid/contrib/slim/prune/``:
pruner.py StructurePruner, prune_strategy.py PruneStrategy/
UniformPruneStrategy/SensitivePruneStrategy, auto_prune_strategy.py
AutoPruneStrategy).

TPU-native shape — the reference physically shrinks parameter tensors and
walks the graph rewriting every dependent shape (prune_strategy.py
_prune_parameters/_forward_search_related_op).  Dynamic shapes are hostile
to XLA's compilation cache, so here pruning is realized in two phases:

1. **Training: channel masks.**  Each pruned parameter P gets a persistable
   0/1 mask ``P.prune_mask``; consumers are rewritten to read
   ``P.pruned = elementwise_mul(P, mask)``.  Shapes stay static (one
   recompile per prune event, not per step), autodiff routes gradients
   through the mask so pruned channels receive zero gradient and stay dead,
   and XLA folds the multiply into the adjacent conv/matmul.  Batch-norm
   scale/bias of the pruned conv output are masked with the same indices so
   the channel's activation is exactly zero (the physical-removal
   equivalent).
2. **Export: materialization.**  ``materialize_pruned_program`` rewrites a
   forward program once, slicing masked channels out of conv→(bn)→conv
   chains — the one-time shape change the reference does continuously.
"""

from __future__ import annotations

import os
import pickle
import re
from typing import Dict, List, Optional, Sequence

import numpy as np

from .core import Strategy
from .graph import GraphWrapper
from .searcher import SAController

__all__ = ["Pruner", "StructurePruner", "PruneStrategy",
           "UniformPruneStrategy", "SensitivePruneStrategy",
           "AutoPruneStrategy", "materialize_pruned_program"]


class Pruner:
    """Base pruner (ref pruner.py:22)."""

    def prune(self, param):
        raise NotImplementedError


class StructurePruner(Pruner):
    """Whole-channel pruner; per-param axis and ranking criterion
    (ref pruner.py:34).  criterions/pruning_axis map param-name patterns
    ('*' = default) to values."""

    def __init__(self, pruning_axis: Optional[Dict[str, int]] = None,
                 criterions: Optional[Dict[str, str]] = None):
        self.pruning_axis = pruning_axis or {"*": 0}
        self.criterions = criterions or {"*": "l1_norm"}

    def _lookup(self, table: Dict, name: str):
        for pattern, value in table.items():
            if pattern != "*" and re.match(pattern, name):
                return value
        return table.get("*")

    def axis_of(self, name: str) -> int:
        return int(self._lookup(self.pruning_axis, name))

    def cal_pruned_idx(self, name: str, param: np.ndarray, ratio: float,
                       axis: Optional[int] = None) -> np.ndarray:
        """Indices of the lowest-importance channels (ref
        pruner.py cal_pruned_idx)."""
        axis = self.axis_of(name) if axis is None else axis
        criterion = self._lookup(self.criterions, name)
        moved = np.moveaxis(np.asarray(param, np.float64), axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        if criterion == "l1_norm":
            score = np.abs(flat).sum(axis=1)
        elif criterion == "l2_norm":
            score = np.square(flat).sum(axis=1)
        elif criterion == "abs_max":
            score = np.abs(flat).max(axis=1)
        else:
            raise ValueError(f"unknown criterion {criterion!r}")
        n_prune = int(round(ratio * len(score)))
        return np.argsort(score)[:n_prune]


def _mask_from_idx(shape, axis, idx) -> np.ndarray:
    mask = np.ones(shape, np.float32)
    if len(idx):
        sl = [slice(None)] * len(shape)
        sl[axis] = np.asarray(idx, np.int64)
        mask[tuple(sl)] = 0.0
    return mask


class PruneStrategy(Strategy):
    """Mask-pruning machinery shared by the concrete strategies
    (ref prune_strategy.py:36)."""

    MASK_SUFFIX = ".prune_mask"
    PRUNED_SUFFIX = ".pruned"

    def __init__(self, pruner: Optional[StructurePruner] = None,
                 start_epoch=0, end_epoch=0, target_ratio: float = 0.5,
                 metric_name: Optional[str] = None,
                 pruned_params: str = r".*conv.*weights.*"):
        super().__init__(start_epoch, end_epoch)
        self.pruner = pruner or StructurePruner()
        self.target_ratio = target_ratio
        self.metric_name = metric_name
        self.pruned_params = pruned_params

    # -- selection -----------------------------------------------------------
    def _candidate_params(self, graph: GraphWrapper) -> List[str]:
        return [p.name for p in graph.all_parameters()
                if re.match(self.pruned_params, p.name)]

    # -- graph surgery -------------------------------------------------------
    def _ensure_mask_op(self, graph: GraphWrapper, name: str):
        """Idempotently rewire consumers of param ``name`` through a
        mask multiply."""
        block = graph.program.global_block()
        masked = name + self.PRUNED_SUFFIX
        if block.has_var(masked):
            return False
        v = block.var(name)
        block.create_var(name=name + self.MASK_SUFFIX, shape=v.shape,
                         dtype="float32", persistable=True)
        block.create_var(name=masked, shape=v.shape, dtype=v.dtype)
        first = min((i for i, op in enumerate(block.ops)
                     if name in op.input_arg_names()),
                    default=len(block.ops))
        for op in block.ops:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [masked if n == name else n for n in names]
        block.insert_op(first, "elementwise_mul",
                        inputs={"X": [name], "Y": [name + self.MASK_SUFFIX]},
                        outputs={"Out": [masked]}, attrs={"axis": -1})
        graph.program._bump_version()
        return True

    def _related_bn_params(self, graph: GraphWrapper, param: str) -> List[str]:
        """Scale/Bias of a batch_norm fed by the conv that consumes
        ``param`` — masked with the conv's output-channel indices so the
        pruned channel's activation is exactly zero (the reference's
        _forward_pruning_ralated_params equivalent for the mask design)."""
        out = []
        for op in graph.ops_by_input(param + self.PRUNED_SUFFIX) + \
                graph.ops_by_input(param):
            if op.type not in ("conv2d", "depthwise_conv2d"):
                continue
            for nxt in graph.next_ops(op):
                if nxt.type == "batch_norm":
                    out += [nxt.input("Scale")[0], nxt.input("Bias")[0]]
        # consumers may already read the rewired ``.pruned`` names
        return [n[:-len(self.PRUNED_SUFFIX)]
                if n.endswith(self.PRUNED_SUFFIX) else n for n in out]

    def _apply_masks(self, context, ratios: Dict[str, float],
                     rebuild: bool = True):
        """Set masks (and zero weights) for each param → ratio; mutates the
        forward train/eval graphs once, then rebuilds the optimize graph."""
        graphs = [g for g in (context.train_graph, context.eval_graph)
                  if g is not None]
        mutated = False
        for name, ratio in ratios.items():
            value = np.array(context.scope.find_var(name), copy=True)
            axis = self.pruner.axis_of(name)
            idx = self.pruner.cal_pruned_idx(name, value, ratio, axis)
            mask = _mask_from_idx(value.shape, axis, idx)
            for g in graphs:
                mutated |= self._ensure_mask_op(g, name)
            context.scope.set_var(name + self.MASK_SUFFIX, mask)
            context.scope.set_var(name, (value * mask).astype(value.dtype))
            # zero the downstream BN affine channels too
            for bn_param in self._related_bn_params(graphs[0], name):
                bnv = np.array(context.scope.find_var(bn_param), copy=True)
                bn_mask = _mask_from_idx(bnv.shape, 0, idx)
                for g in graphs:
                    mutated |= self._ensure_mask_op(g, bn_param)
                context.scope.set_var(bn_param + self.MASK_SUFFIX, bn_mask)
                context.scope.set_var(bn_param,
                                      (bnv * bn_mask).astype(bnv.dtype))
        if rebuild and (mutated or ratios):
            context.rebuild_optimize_graph()

    def _clear_masks(self, context, names: Sequence[str]):
        for name in names:
            mv = context.scope.find_var(name + self.MASK_SUFFIX)
            if mv is not None:
                context.scope.set_var(name + self.MASK_SUFFIX,
                                      np.ones(np.shape(mv), np.float32))

    def restore_from_checkpoint(self, context):
        """Re-create the mask graph surgery before the Compressor loads
        persistables, so the saved .prune_mask vars have declarations to
        load into (mask/weight VALUES then come from the checkpoint)."""
        self.on_compression_begin(context)
        ratios = context.get("prune_ratios")
        if ratios and context.epoch_id > self.start_epoch:
            self._apply_masks(context, ratios)

    # -- accounting ----------------------------------------------------------
    def _pruned_fraction(self, context, names: Sequence[str],
                         ratios: Dict[str, float]) -> float:
        """Fraction of candidate-param numel removed at these ratios."""
        total = pruned = 0
        for name in names:
            n = int(np.prod(np.shape(context.scope.find_var(name))))
            total += n
            pruned += int(n * ratios.get(name, 0.0))
        return pruned / max(total, 1)


class UniformPruneStrategy(PruneStrategy):
    """Same ratio for every candidate param, chosen (binary search) so the
    overall pruned fraction hits target_ratio (ref prune_strategy.py:563)."""

    def _get_best_ratios(self, context):
        names = self._candidate_params(context.train_graph)
        # uniform ratio prunes numel proportionally, so ratio==target;
        # binary search kept for parity with non-uniform channel rounding
        lo, hi = 0.0, 1.0
        for _ in range(20):
            mid = (lo + hi) / 2
            frac = self._pruned_fraction(context, names,
                                         {n: mid for n in names})
            if frac < self.target_ratio:
                lo = mid
            else:
                hi = mid
        return names, hi

    def on_epoch_begin(self, context):
        if context.epoch_id != self.start_epoch:
            return
        names, ratio = self._get_best_ratios(context)
        self._apply_masks(context, {n: ratio for n in names})
        context.put("prune_ratios", {n: ratio for n in names})


class SensitivePruneStrategy(PruneStrategy):
    """Per-param ratios from sensitivity analysis (ref
    prune_strategy.py:668): sweep each param's prune ratio on the eval
    metric, then pick the largest per-param ratios whose predicted metric
    loss stays under a common budget that just reaches target_ratio."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=0,
                 target_ratio=0.5, metric_name=None,
                 pruned_params=r".*conv.*weights.*", delta_rate: float = 0.2,
                 sensitivities_file: Optional[str] = None,
                 num_steps: int = 1):
        super().__init__(pruner, start_epoch, end_epoch, target_ratio,
                         metric_name, pruned_params)
        self.delta_rate = delta_rate
        self.sensitivities_file = sensitivities_file
        self.num_steps = max(1, num_steps)
        self._step = 0

    # -- sensitivity sweep (ref _compute_sensitivities) ----------------------
    def _compute_sensitivities(self, context) -> Dict[str, Dict[float, float]]:
        if self.sensitivities_file and os.path.exists(self.sensitivities_file):
            with open(self.sensitivities_file, "rb") as f:
                return pickle.load(f)
        baseline, _ = context.run_eval_graph(record=False)
        sens: Dict[str, Dict[float, float]] = {}
        for name in self._candidate_params(context.train_graph):
            backup = np.array(context.scope.find_var(name), copy=True)
            sens[name] = {0.0: 0.0}
            ratio = self.delta_rate
            while ratio < 1.0 - 1e-9:
                idx = self.pruner.cal_pruned_idx(name, backup, ratio)
                mask = _mask_from_idx(backup.shape,
                                      self.pruner.axis_of(name), idx)
                context.scope.set_var(name,
                                      (backup * mask).astype(backup.dtype))
                metric, _ = context.run_eval_graph(record=False)
                sens[name][round(ratio, 4)] = \
                    (baseline - metric) / (abs(baseline) + 1e-12)
                ratio += self.delta_rate
            context.scope.set_var(name, backup)
        if self.sensitivities_file:
            with open(self.sensitivities_file, "wb") as f:
                pickle.dump(sens, f)
        return sens

    @staticmethod
    def _max_ratio_under(sens_curve: Dict[float, float], budget: float):
        """Largest ratio whose (linearly interpolated) sensitivity ≤
        budget."""
        pts = sorted(sens_curve.items())
        best = 0.0
        for (r0, s0), (r1, s1) in zip(pts, pts[1:]):
            if s1 <= budget:
                best = r1
            elif s0 <= budget and s1 > s0:
                best = r0 + (r1 - r0) * (budget - s0) / (s1 - s0)
                break
        return min(best, 0.95)

    def _get_best_ratios(self, context, sens, target) -> Dict[str, float]:
        names = list(sens)
        lo, hi = 0.0, max(max(c.values()) for c in sens.values()) + 1e-6
        ratios = {n: 0.0 for n in names}
        for _ in range(30):
            budget = (lo + hi) / 2
            cand = {n: self._max_ratio_under(sens[n], budget) for n in names}
            if self._pruned_fraction(context, names, cand) < target:
                lo = budget
            else:
                hi = budget
                ratios = cand
        return ratios

    def restore_from_checkpoint(self, context):
        super().restore_from_checkpoint(context)
        self._step = min(self.num_steps,
                         max(0, context.epoch_id - self.start_epoch))

    def on_epoch_begin(self, context):
        if not (self.start_epoch <= context.epoch_id < self.end_epoch):
            return
        if self._step >= self.num_steps:
            return
        self._step += 1
        sens = self._compute_sensitivities(context)
        target = self.target_ratio * self._step / self.num_steps
        ratios = self._get_best_ratios(context, sens, target)
        self._apply_masks(context, ratios)
        context.put("prune_ratios", ratios)


class AutoPruneStrategy(PruneStrategy):
    """SA-search over per-param ratios (ref auto_prune_strategy.py:28):
    each epoch in [start,end) tries controller-proposed ratios, trains one
    epoch, rewards with the eval metric, restores; the best tokens are
    applied for good at end_epoch."""

    def __init__(self, pruner=None, start_epoch=0, end_epoch=10,
                 target_ratio=0.5, metric_name=None,
                 pruned_params=r".*conv.*weights.*",
                 controller: Optional[SAController] = None,
                 ratio_steps: int = 9):
        super().__init__(pruner, start_epoch, end_epoch, target_ratio,
                         metric_name, pruned_params)
        self._controller = controller or SAController()
        self._ratio_steps = ratio_steps       # token t → ratio t/steps*0.9
        self._names: List[str] = []
        self._tokens: Optional[List[int]] = None
        self._snapshot = None

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_snapshot"] = None      # param arrays don't belong in the meta
        return d

    def _tokens_to_ratios(self, tokens) -> Dict[str, float]:
        return {n: 0.9 * t / self._ratio_steps
                for n, t in zip(self._names, tokens)}

    def _make_constrain(self, context):
        def constrain(tokens):
            frac = self._pruned_fraction(context, self._names,
                                         self._tokens_to_ratios(tokens))
            return frac >= self.target_ratio
        return constrain

    def on_compression_begin(self, context):
        self._names = self._candidate_params(context.train_graph)
        if getattr(self._controller, "_range_table", None):
            # resumed controller: keep its annealing chain/best tokens,
            # just re-attach the (unpicklable) constraint closure
            self._controller._constrain_func = self._make_constrain(context)
            return
        init = [int(round(self.target_ratio / 0.9 * self._ratio_steps))] * \
            len(self._names)
        self._controller.reset([self._ratio_steps + 1] * len(self._names),
                               init_tokens=init,
                               constrain_func=self._make_constrain(context))

    def on_epoch_begin(self, context):
        if not (self.start_epoch <= context.epoch_id < self.end_epoch):
            return
        self._tokens = self._controller.next_tokens()
        self._snapshot = context.train_graph.backup_params()
        self._apply_masks(context, self._tokens_to_ratios(self._tokens))

    def on_epoch_end(self, context):
        if self._tokens is not None and \
                self.start_epoch <= context.epoch_id < self.end_epoch:
            reward, _ = context.run_eval_graph()
            self._controller.update(self._tokens, reward)
            context.train_graph.restore_params(self._snapshot)
            self._clear_masks(context, list(self._snapshot))
            self._tokens = None
        if context.epoch_id == self.end_epoch - 1:
            best = self._controller.best_tokens or \
                [int(round(self.target_ratio / 0.9 * self._ratio_steps))] * \
                len(self._names)
            ratios = self._tokens_to_ratios(best)
            self._apply_masks(context, ratios)
            context.put("prune_ratios", ratios)


def materialize_pruned_program(program, scope):
    """One-time physical channel removal for export (phase 2 of the module
    docstring): for each masked conv filter, slice the kept output channels
    out of the filter / bn affine params and out of the *input* axis of a
    directly-following conv.  Chains it can't prove safe keep their masks
    (XLA constant-folds those).  Returns the rewritten program."""
    prog = program.clone()
    graph = GraphWrapper(prog, scope)
    block = prog.global_block()

    def _strip(name):
        return name[:-len(PruneStrategy.PRUNED_SUFFIX)] \
            if name.endswith(PruneStrategy.PRUNED_SUFFIX) else name

    for op in list(graph.ops()):
        if op.type not in ("conv2d", "depthwise_conv2d"):
            continue
        pname = _strip(op.input("Filter")[0])
        mask_var = scope.find_var(pname + PruneStrategy.MASK_SUFFIX)
        if mask_var is None:
            continue
        mask = np.asarray(mask_var)
        keep = np.where(mask.reshape(mask.shape[0], -1).any(axis=1))[0]
        if len(keep) == mask.shape[0]:
            continue
        # the conv's consumers must be bn/activation then exactly convs,
        # else leave the mask in place
        nexts = graph.next_ops(op)
        frontier, ok = [], True
        while nexts:
            n = nexts.pop()
            if n.type == "batch_norm" or n.type in (
                    "relu", "relu6", "leaky_relu", "sigmoid", "tanh"):
                nexts += graph.next_ops(n)
            elif n.type == "conv2d":
                frontier.append(n)
            else:
                ok = False
                break
        if not ok:
            continue
        # slice producer output channels
        w = np.asarray(scope.find_var(pname))
        scope.set_var(pname, np.ascontiguousarray(w[keep]))
        block.var(pname).shape = tuple(np.shape(scope.find_var(pname)))
        _drop_mask(block, graph, pname)
        for bn_op in [n for n in graph.next_ops(op)
                      if n.type == "batch_norm"]:
            for slot in ("Scale", "Bias", "Mean", "Variance"):
                bname = _strip(bn_op.input(slot)[0])
                bv = np.asarray(scope.find_var(bname))
                scope.set_var(bname, np.ascontiguousarray(bv[keep]))
                block.var(bname).shape = (len(keep),)
                _drop_mask(block, graph, bname)
        # slice consumer input channels (incl. any still-attached mask of
        # the consumer's own pruning, which must track the new shape)
        for nxt in frontier:
            fname = _strip(nxt.input("Filter")[0])
            fv = np.asarray(scope.find_var(fname))
            scope.set_var(fname, np.ascontiguousarray(fv[:, keep]))
            new_shape = tuple(np.shape(scope.find_var(fname)))
            block.var(fname).shape = new_shape
            fmask = scope.find_var(fname + PruneStrategy.MASK_SUFFIX)
            if fmask is not None:
                scope.set_var(fname + PruneStrategy.MASK_SUFFIX,
                              np.ascontiguousarray(
                                  np.asarray(fmask)[:, keep]))
                for aux in (fname + PruneStrategy.MASK_SUFFIX,
                            fname + PruneStrategy.PRUNED_SUFFIX):
                    if block.has_var(aux):
                        block.var(aux).shape = new_shape
        # conv output var channel dim
        for out_name in op.output("Output"):
            v = block.var(out_name)
            if v.shape is not None and len(v.shape) == 4:
                v.shape = (v.shape[0], len(keep)) + tuple(v.shape[2:])
    prog._bump_version()
    return prog


def _drop_mask(block, graph: GraphWrapper, pname: str):
    """Remove the elementwise_mul mask op for ``pname``; consumers read the
    (now physically pruned) parameter directly."""
    masked = pname + PruneStrategy.PRUNED_SUFFIX
    if not block.has_var(masked):
        return
    for i, op in enumerate(list(block.ops)):
        if op.type == "elementwise_mul" and op.output("Out") == [masked]:
            block.remove_op(i)
            break
    for op in block.ops:
        for slot, names in op.inputs.items():
            op.inputs[slot] = [pname if n == masked else n for n in names]
    block.vars.pop(masked, None)
    block.vars.pop(pname + PruneStrategy.MASK_SUFFIX, None)
