"""Light-NAS: architecture search driven by simulated annealing (ref
``python/paddle/fluid/contrib/slim/nas/``: search_space.py SearchSpace,
controller_server.py socket server, search_agent.py client,
light_nas_strategy.py strategy).

The controller lives behind a tiny line-JSON TCP server so a multi-host
search (many trainers evaluating candidate nets in parallel, e.g. one per
TPU slice) shares one annealing chain — the reference's
controller_server/search_agent topology.  Single-host search just talks to
the same server on localhost."""

from __future__ import annotations

import json
import socket
import threading
from typing import Optional

from ...framework.executor import Executor
from .core import Strategy
from .graph import GraphWrapper
from .searcher import SAController

__all__ = ["SearchSpace", "ControllerServer", "SearchAgent",
           "LightNASStrategy"]


class SearchSpace:
    """User-subclassed search space (ref search_space.py:19)."""

    def init_tokens(self):
        """Initial token vector."""
        raise NotImplementedError

    def range_table(self):
        """Per-position exclusive upper bounds."""
        raise NotImplementedError

    def create_net(self, tokens):
        """tokens → (startup_program, train_program, eval_program,
        train_fetch_list, eval_fetch_list, train_reader, eval_reader)."""
        raise NotImplementedError

    def get_model_latency(self, program) -> float:
        """Optional measured/predicted latency for the candidate."""
        raise NotImplementedError


class ControllerServer:
    """Serve an SAController over TCP line-JSON (ref
    controller_server.py).  Protocol:
        {"cmd": "next_tokens"}                     → {"tokens": [...]}
        {"cmd": "update", "tokens": T, "reward": r} → {"tokens": next}
    """

    def __init__(self, controller: SAController, address=("127.0.0.1", 0),
                 max_client_num: int = 10):
        self._controller = controller
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(address)
        self._sock.listen(max_client_num)
        self._closed = False
        self._thread = threading.Thread(target=self._serve, daemon=True)

    @property
    def address(self):
        return self._sock.getsockname()

    def start(self):
        self._thread.start()
        return self

    def close(self):
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def _serve(self):
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # one thread per client so a hung trainer can't starve the
            # accept loop; the idle timeout reaps dead connections
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        conn.settimeout(60)
        try:
            with conn, conn.makefile("rw") as f:
                for line in f:
                    try:
                        req = json.loads(line)
                    except ValueError:
                        break
                    with self._lock:
                        if req.get("cmd") == "update":
                            self._controller.update(req["tokens"],
                                                    float(req["reward"]))
                        resp = {"tokens": self._controller.next_tokens()}
                    f.write(json.dumps(resp) + "\n")
                    f.flush()
        except OSError:
            pass


class SearchAgent:
    """Client side of the controller protocol (ref search_agent.py)."""

    def __init__(self, server_ip: str, server_port: int):
        self.server_ip = server_ip
        self.server_port = server_port

    def _request(self, payload: dict) -> list:
        with socket.create_connection((self.server_ip, self.server_port),
                                      timeout=30) as s, \
                s.makefile("rw") as f:
            f.write(json.dumps(payload) + "\n")
            f.flush()
            return json.loads(f.readline())["tokens"]

    def next_tokens(self) -> list:
        return self._request({"cmd": "next_tokens"})

    def update(self, tokens, reward) -> list:
        """Report a reward; returns the next tokens to try."""
        return self._request({"cmd": "update", "tokens": list(tokens),
                              "reward": float(reward)})


class LightNASStrategy(Strategy):
    """Each epoch in the window: build the candidate net from the current
    tokens, train it, reward the controller with the eval metric (ref
    light_nas_strategy.py:34).  Candidates over the FLOPs/latency budget
    are rejected before any training."""

    def __init__(self, controller: Optional[SAController] = None,
                 start_epoch=0, end_epoch=10, target_flops: float = 0,
                 target_latency: float = 0, metric_name: str = "acc_top1",
                 server_ip: str = "127.0.0.1", server_port: int = 0,
                 is_server: bool = True, retrain_epoch: int = 1,
                 max_try_times: int = 101):
        super().__init__(start_epoch, end_epoch)
        self._controller = controller or SAController()
        self._max_flops = target_flops
        self._max_latency = target_latency
        self.metric_name = metric_name
        self._server_ip = server_ip
        self._server_port = server_port
        self._is_server = is_server
        self._retrain_epoch = max(1, retrain_epoch)
        self._max_try_times = max_try_times
        self._server: Optional[ControllerServer] = None
        self._agent: Optional[SearchAgent] = None
        self._current_tokens = None
        self.best_tokens = None
        self.best_reward = float("-inf")

    def __getstate__(self):
        d = dict(self.__dict__)
        d["_server"] = None        # socket/thread state is rebuilt on resume
        d["_agent"] = None
        return d

    def on_compression_begin(self, context):
        space = context.search_space
        assert space is not None, "Compressor needs search_space for NAS"
        if self._is_server:
            if not getattr(self._controller, "_range_table", None):
                self._controller.reset(space.range_table(),
                                       space.init_tokens())
            # (a resumed controller keeps its annealing chain)
            self._server = ControllerServer(
                self._controller,
                (self._server_ip, self._server_port)).start()
            self._server_port = self._server.address[1]
        self._agent = SearchAgent(self._server_ip, self._server_port)
        if self._current_tokens is None:
            self._current_tokens = space.init_tokens()

    def on_compression_end(self, context):
        if self._server is not None:
            self._server.close()

    def _within_budget(self, eval_program, space) -> bool:
        if self._max_flops > 0:
            if GraphWrapper(eval_program).flops() > self._max_flops:
                return False
        if self._max_latency > 0:
            if space.get_model_latency(eval_program) > self._max_latency:
                return False
        return True

    def on_epoch_begin(self, context):
        if not (self.start_epoch <= context.epoch_id < self.end_epoch) or \
                (context.epoch_id - self.start_epoch) % self._retrain_epoch:
            return
        space = context.search_space
        net = None
        for attempt in range(self._max_try_times):
            net = space.create_net(self._current_tokens)
            if self._within_budget(net[2], space) or \
                    attempt == self._max_try_times - 1:
                # keep net/_current_tokens consistent even when the budget
                # was never met (the reward is zeroed at epoch end)
                break
            self._current_tokens = self._agent.next_tokens()
        (startup, train_p, eval_p, train_fetch, eval_fetch,
         train_reader, eval_reader) = net
        Executor(context.place).run(startup, scope=context.scope,
                                    fetch_list=[])
        context.train_graph = GraphWrapper(train_p, context.scope)
        context.eval_graph = GraphWrapper(eval_p, context.scope)
        context.train_fetch_list = list(train_fetch)
        context.eval_fetch_list = list(eval_fetch)
        context.train_reader = train_reader
        context.eval_reader = eval_reader
        context.rebuild_optimize_graph()

    def on_epoch_end(self, context):
        if not (self.start_epoch <= context.epoch_id < self.end_epoch) or \
                (context.epoch_id - self.start_epoch + 1) \
                % self._retrain_epoch:
            return
        reward, _ = context.run_eval_graph()
        if not self._within_budget(context.eval_graph.program,
                                   context.search_space):
            reward = 0.0
        if reward > self.best_reward:
            self.best_reward = reward
            self.best_tokens = list(self._current_tokens)
        self._current_tokens = self._agent.update(self._current_tokens,
                                                  reward)
        context.put("nas_best_tokens", self.best_tokens)
        context.put("nas_best_reward", self.best_reward)
