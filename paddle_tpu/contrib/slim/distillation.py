"""Knowledge distillation (ref ``python/paddle/fluid/contrib/slim/
distillation/``: distiller.py L2/FSP/SoftLabel distillers building loss ops
on the merged graph; distillation_strategy.py swapping the optimize graph
for the distillation window).

The teacher program is merged op-for-op into a clone of the student's
forward program (shared data-input vars unify the two nets, teacher vars
are stop_gradient so autodiff never differentiates the teacher), distiller
losses are appended with the ordinary layer DSL, and the whole merged net —
student + frozen teacher + losses — compiles to ONE XLA computation: the
teacher forward fuses into the same step, no separate teacher session as a
naive port would run."""

from __future__ import annotations

from typing import List, Optional, Sequence

from ... import layers
from ...framework import core
from ...framework.core import program_guard
from .core import Strategy
from .graph import GraphWrapper

__all__ = ["L2Distiller", "FSPDistiller", "SoftLabelDistiller",
           "DistillationStrategy", "merge_programs"]


def merge_programs(student: core.Program, teacher: core.Program,
                   prefix: str = "", data_name_map=None) -> core.Program:
    """Clone ``student`` and append every var/op of ``teacher`` (ref
    graph_wrapper.py GraphWrapper.merge).  Vars already present in the
    student (the shared feed vars) are reused, which is how the two nets
    see the same minibatch.  ``prefix`` optionally renames teacher vars to
    avoid collisions when both nets share layer names; ``data_name_map``
    (teacher var → student var) pins the shared inputs when a prefix is
    used."""
    merged = student.clone()
    dst = merged.global_block()
    src = teacher.global_block()
    data_name_map = dict(data_name_map or {})

    def _name(n):
        if not n:
            return n
        if n in data_name_map:
            return data_name_map[n]
        if prefix and src.has_var(n):
            return prefix + n
        return n

    for name, var in src.vars.items():
        new = _name(name)
        if var.persistable and not prefix and name not in data_name_map \
                and dst.has_var(new):
            raise ValueError(
                f"teacher parameter {name!r} collides with a student var; "
                "pass teacher_prefix= (and data_name_map= for the shared "
                "inputs) so the teacher keeps its own weights")
        if not dst.has_var(new):
            v = dst.create_var(name=new, shape=var.shape, dtype=var.dtype,
                               persistable=var.persistable)
            v.is_parameter = getattr(var, "is_parameter", False)
            v.stop_gradient = True        # teacher side is frozen
    for op in src.ops:
        dst.append_op(
            op.type,
            inputs={s: [_name(n) for n in ns] for s, ns in op.inputs.items()},
            outputs={s: [_name(n) for n in ns]
                     for s, ns in op.outputs.items()},
            attrs=dict(op.attrs))
    merged._bump_version()
    return merged


class L2Distiller:
    """L2 loss between a student and a teacher feature map
    (ref distiller.py:25)."""

    def __init__(self, student_feature_map: str, teacher_feature_map: str,
                 distillation_loss_weight: float = 1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph: GraphWrapper):
        block = graph.program.global_block()
        s = block.var(self.student_feature_map)
        t = block.var(self.teacher_feature_map)
        diff = layers.elementwise_sub(s, t)
        loss = layers.reduce_mean(layers.square(diff)) * self.weight
        return loss


class FSPDistiller:
    """Flow-of-solution-procedure distillation: match the student's and
    teacher's FSP (gram) matrices between layer pairs (ref
    distiller.py:103; fsp op ref operators/fsp_op.cc)."""

    def __init__(self, student_pairs: Sequence[Sequence[str]],
                 teacher_pairs: Sequence[Sequence[str]],
                 distillation_loss_weight: float = 1.0):
        self.student_pairs = student_pairs
        self.teacher_pairs = teacher_pairs
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph: GraphWrapper):
        block = graph.program.global_block()
        losses = []
        for (s0, s1), (t0, t1) in zip(self.student_pairs,
                                      self.teacher_pairs):
            fs = layers.fsp_matrix(block.var(s0), block.var(s1))
            ft = layers.fsp_matrix(block.var(t0), block.var(t1))
            losses.append(layers.reduce_mean(
                layers.square(layers.elementwise_sub(fs, ft))))
        total = losses[0]
        for l in losses[1:]:
            total = total + l
        return total * self.weight


class SoftLabelDistiller:
    """Soft-label cross entropy between temperature-softened student and
    teacher logits (ref distiller.py:195)."""

    def __init__(self, student_feature_map: str, teacher_feature_map: str,
                 student_temperature: float = 1.0,
                 teacher_temperature: float = 1.0,
                 distillation_loss_weight: float = 1.0):
        self.student_feature_map = student_feature_map
        self.teacher_feature_map = teacher_feature_map
        self.student_temperature = student_temperature
        self.teacher_temperature = teacher_temperature
        self.weight = distillation_loss_weight

    def distiller_loss(self, graph: GraphWrapper):
        block = graph.program.global_block()
        s = layers.softmax(
            block.var(self.student_feature_map) / self.student_temperature)
        t = layers.softmax(
            block.var(self.teacher_feature_map) / self.teacher_temperature)
        t.stop_gradient = True
        ce = layers.cross_entropy(s, t, soft_label=True)
        return layers.reduce_mean(ce) * self.weight


class DistillationStrategy(Strategy):
    """Swap the train graph for student+teacher+distill-loss during
    [start_epoch, end_epoch) (ref distillation_strategy.py:27)."""

    def __init__(self, distillers: Optional[List] = None, start_epoch=0,
                 end_epoch=0, teacher_prefix: str = "",
                 data_name_map=None):
        super().__init__(start_epoch, end_epoch)
        self.distillers = distillers or []
        self.teacher_prefix = teacher_prefix
        self.data_name_map = data_name_map

    def on_epoch_begin(self, context):
        if context.epoch_id != self.start_epoch:
            return
        student_fwd = context.train_graph.program
        merged = student_fwd
        for tg in context.teacher_graphs:
            merged = merge_programs(merged, tg.program, self.teacher_prefix,
                                    self.data_name_map)
            if self.teacher_prefix:
                # renamed teacher vars need their scope values under the
                # prefixed names the merged program reads
                import numpy as np
                for v in tg.program.list_vars():
                    if v.persistable and \
                            context.scope.find_var(v.name) is not None:
                        # real copy: aliasing the student's buffer would
                        # collide with the executor's donation of trained
                        # params
                        context.scope.set_var(
                            self.teacher_prefix + v.name,
                            np.array(context.scope.find_var(v.name),
                                     copy=True))
        graph = GraphWrapper(merged, context.scope)
        student_loss = context._fetch_name(context.train_fetch_list[0])
        with program_guard(merged):
            total = merged.global_block().var(student_loss)
            for d in self.distillers:
                total = total + d.distiller_loss(graph)
        # stash originals for restore (ref distillation_backup_optimize_graph)
        context.put("distillation_backup",
                    (context.train_graph, list(context.train_fetch_list),
                     context.optimizer))
        distiller_opt = context.get("distiller_optimizer")
        if distiller_opt is not None:
            context.optimizer = distiller_opt
        context.train_graph = graph
        context.train_fetch_list = [total.name] + \
            list(context.train_fetch_list[1:])
        context.rebuild_optimize_graph()

    def on_epoch_end(self, context):
        if context.epoch_id != self.end_epoch - 1:
            return
        backup = context.get("distillation_backup")
        if backup:
            (context.train_graph, context.train_fetch_list,
             context.optimizer) = backup
            context.put("distillation_backup", None)
            context.rebuild_optimize_graph()

    def restore_from_checkpoint(self, context):
        # re-enter the distillation graph if resuming inside the window;
        # epoch_id == start_epoch means the checkpoint predates the merge
        # and the ordinary on_epoch_begin will apply it
        if self.start_epoch < context.epoch_id < self.end_epoch:
            saved = context.epoch_id
            context.epoch_id = self.start_epoch
            self.on_epoch_begin(context)
            context.epoch_id = saved
