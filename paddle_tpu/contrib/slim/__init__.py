"""Model-compression toolkit (ref ``python/paddle/fluid/contrib/slim/``)."""

from . import core, distillation, graph, nas, prune, quantization  # noqa
from .core import Compressor, ConfigFactory, Context, Strategy  # noqa
from .distillation import (DistillationStrategy, FSPDistiller,  # noqa
                           L2Distiller, SoftLabelDistiller)
from .graph import GraphWrapper  # noqa
from .nas import (ControllerServer, LightNASStrategy, SearchAgent,  # noqa
                  SearchSpace)
from .prune import (AutoPruneStrategy, PruneStrategy,  # noqa
                    SensitivePruneStrategy, StructurePruner,
                    UniformPruneStrategy, materialize_pruned_program)
from .quantization import (QuantizationFreezePass,  # noqa
                           QuantizationStrategy, QuantizationTransformPass)
from .searcher import EvolutionaryController, SAController  # noqa
