"""Model-compression toolkit (ref ``python/paddle/fluid/contrib/slim/``)."""

from . import quantization  # noqa
from .quantization import (QuantizationFreezePass,  # noqa
                           QuantizationTransformPass)
