"""Compression framework core (ref ``python/paddle/fluid/contrib/slim/core/``:
compressor.py Context/Compressor, strategy.py Strategy, config.py
ConfigFactory).

The Compressor drives an epoch loop over a *forward* train program (loss
built, optimizer NOT yet applied) and calls strategy hooks around it.
Strategies mutate the forward program (prune masks, distillation teacher
merge, quant ops); the Compressor then (re)builds the optimized train graph
by cloning the forward program and appending backward + optimizer ops — each
rebuild is one fresh XLA compilation, after which steps run at full speed
(static shapes throughout; no per-batch host-side graph work).
"""

from __future__ import annotations

import logging
import os
import pickle
from typing import Dict, List, Optional, Sequence

import numpy as np

_logger = logging.getLogger(__name__)

from ... import io as pio
from ...framework import core
from ...framework.core import Program, program_guard
from ...framework.executor import Executor
from ...framework.scope import global_scope
from .graph import GraphWrapper

__all__ = ["Context", "Strategy", "Compressor", "ConfigFactory"]


class Strategy:
    """Base strategy with epoch/batch callbacks (ref strategy.py:18)."""

    def __init__(self, start_epoch=0, end_epoch=0):
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch

    def on_compression_begin(self, context):  # noqa: D102
        pass

    def on_epoch_begin(self, context):
        pass

    def on_epoch_end(self, context):
        pass

    def on_batch_begin(self, context):
        pass

    def on_batch_end(self, context):
        pass

    def on_compression_end(self, context):
        pass

    def restore_from_checkpoint(self, context):
        self.on_compression_begin(context)


class Context:
    """Mutable state threaded through strategies (ref compressor.py:74)."""

    def __init__(self, place, scope, train_graph: Optional[GraphWrapper],
                 eval_graph: Optional[GraphWrapper], executor: Executor,
                 optimizer=None, train_reader=None, eval_reader=None,
                 teacher_graphs: Sequence[GraphWrapper] = (),
                 train_feed_list=None, train_fetch_list=None,
                 eval_feed_list=None, eval_fetch_list=None):
        self.place = place
        self.scope = scope
        self.executor = executor
        self.train_graph = train_graph          # forward program wrapper
        self.eval_graph = eval_graph
        self.optimizer = optimizer
        self.train_reader = train_reader
        self.eval_reader = eval_reader
        self.teacher_graphs = list(teacher_graphs)
        self.train_feed_list = list(train_feed_list or [])
        self.train_fetch_list = list(train_fetch_list or [])
        self.eval_feed_list = list(eval_feed_list or [])
        self.eval_fetch_list = list(eval_fetch_list or [])
        self.epoch_id = 0
        self.batch_id = 0
        self.search_space = None
        self.skip_training = False
        self.eval_results: Dict[str, List[float]] = {}
        self.k_v: Dict[str, object] = {}
        # compiled (backward+optimizer appended) program; rebuilt on demand
        self.optimize_graph: Optional[Program] = None
        self._optimize_fetches: List[str] = []

    # -- kv (ref Context.put/get) -------------------------------------------
    def put(self, key, value):
        self.k_v[key] = value

    def get(self, key):
        return self.k_v.get(key)

    # -- train-graph rebuild -------------------------------------------------
    def rebuild_optimize_graph(self):
        """Clone the forward train program, append backward + optimizer.

        Called at init and after every strategy that mutates the forward
        graph.  The clone keeps the forward program pristine so later
        strategies compose (prune → distill → quant)."""
        fwd = self.train_graph.program
        prog = fwd.clone()
        startup = core.Program()
        with program_guard(prog, startup):
            loss_name = self._fetch_name(self.train_fetch_list[0])
            loss = prog.global_block().var(loss_name)
            self.optimizer.minimize(loss, startup_program=startup)
        # the optimizer caches vars (LR, accumulators) created under an
        # earlier rebuild's program; declare them in this block so the
        # executor collects them from the scope
        block = prog.global_block()
        for op in block.ops:
            for name in op.input_arg_names() + op.output_arg_names():
                if name and not block.has_var(name) and \
                        self.scope.find_var(name) is not None:
                    val = np.asarray(self.scope.find_var(name))
                    block.create_var(name=name, shape=tuple(val.shape),
                                     dtype=str(val.dtype), persistable=True)
        # run only the *new* startup pieces (optimizer accumulators, LR var):
        # existing params already live in the scope
        new_vars = [op.output_arg_names()[0]
                    for op in startup.global_block().ops
                    if op.output_arg_names()
                    and self.scope.find_var(op.output_arg_names()[0]) is None]
        if new_vars:
            self.executor.run(startup, scope=self.scope, fetch_list=[])
        self.optimize_graph = prog
        self._optimize_fetches = [self._fetch_name(f)
                                  for f in self.train_fetch_list]

    @staticmethod
    def _fetch_name(f):
        return f.name if hasattr(f, "name") else f

    # -- eval loop (ref Context.run_eval_graph) ------------------------------
    def run_eval_graph(self, sampled_rate=None, cached_id=0, record=True):
        """``record=False`` keeps probe evals (e.g. sensitivity sweeps)
        out of the per-epoch metric history."""
        assert self.eval_graph is not None and self.eval_reader is not None
        fetches = [self._fetch_name(f) for f in self.eval_fetch_list]
        feed_names = [self._fetch_name(f) for f in self.eval_feed_list]
        totals = np.zeros(len(fetches), np.float64)
        count = 0
        for data in self.eval_reader():
            feed = _make_feed(self.eval_graph.program, feed_names, data)
            outs = self.executor.run(self.eval_graph.program, feed=feed,
                                     fetch_list=fetches, scope=self.scope)
            totals += [float(np.asarray(o).mean()) for o in outs]
            count += 1
        result = (totals / max(count, 1)).tolist()
        if record:
            for name, val in zip(fetches, result):
                self.eval_results.setdefault(name, []).append(val)
            self.k_v["_evaled_epoch"] = self.epoch_id
        return result[0], fetches[0]

    def eval_converged(self, metric_name, delta=0.001):
        hist = self.eval_results.get(metric_name, [])
        if len(hist) < 2:
            return False
        return abs(hist[-1] - hist[-2]) < delta


def _make_feed(program: Program, feed_names: Sequence[str], data):
    """One reader sample-batch (list of tuples) → feed dict, via the
    standard DataFeeder batching convention."""
    if isinstance(data, dict):
        return data
    from ...data.feeder import DataFeeder
    block = program.global_block()
    feed_list = [block.var(n) if block.has_var(n) else n
                 for n in feed_names]
    return DataFeeder(feed_list).feed(data)


class Compressor:
    """Epoch-driven compression driver (ref compressor.py:229).

    ``train_program``/``eval_program`` are *forward* programs whose first
    train fetch is the loss; the optimizer is applied by the Compressor so
    strategies may rewrite the forward graph at epoch boundaries."""

    def __init__(self, place, scope, train_program: Program,
                 train_reader=None, train_feed_list=None,
                 train_fetch_list=None, eval_program: Optional[Program] = None,
                 eval_reader=None, eval_feed_list=None, eval_fetch_list=None,
                 teacher_programs=(), checkpoint_path: Optional[str] = None,
                 train_optimizer=None, epoch: int = 1,
                 distiller_optimizer=None, search_space=None,
                 log_period: int = 20):
        self.place = place
        self.scope = scope or global_scope()
        self.epoch = epoch
        self.checkpoint_path = checkpoint_path
        self.log_period = log_period
        self.strategies: List[Strategy] = []
        self.executor = Executor(place)
        self.distiller_optimizer = distiller_optimizer
        self.context = Context(
            place, self.scope,
            GraphWrapper(train_program, self.scope),
            GraphWrapper(eval_program, self.scope) if eval_program else None,
            self.executor, optimizer=train_optimizer,
            train_reader=train_reader, eval_reader=eval_reader,
            teacher_graphs=[GraphWrapper(p, self.scope)
                            for p in teacher_programs],
            train_feed_list=train_feed_list, train_fetch_list=train_fetch_list,
            eval_feed_list=eval_feed_list, eval_fetch_list=eval_fetch_list)
        self.context.put("distiller_optimizer", distiller_optimizer)
        self.context.search_space = search_space

    def add_strategy(self, strategy: Strategy):
        self.strategies.append(strategy)
        self.epoch = max(self.epoch, strategy.end_epoch)
        return self

    def config(self, config_file: str):
        """Load strategies from a YAML config (ref config.py factory)."""
        factory = ConfigFactory(config_file)
        for s in factory.strategies:
            self.add_strategy(s)
        if factory.compressor.get("epoch"):
            self.epoch = int(factory.compressor["epoch"])
        if factory.compressor.get("checkpoint_path"):
            self.checkpoint_path = factory.compressor["checkpoint_path"]
        return self

    # -- checkpoint (ref _save/_load_checkpoint) -----------------------------
    def _save_checkpoint(self, context):
        if not self.checkpoint_path:
            return
        path = os.path.join(self.checkpoint_path, str(context.epoch_id))
        os.makedirs(path, exist_ok=True)
        pio.save_persistables(self.executor, dirname=path,
                              main_program=context.optimize_graph,
                              scope=context.scope)
        meta = {"epoch_id": context.epoch_id,
                "eval_results": context.eval_results,
                "prune_ratios": context.get("prune_ratios"),
                # strategies carry search state (SA chains, best tokens);
                # unpicklables (sockets, closures) are dropped by their
                # __getstate__ hooks
                "strategies": self.strategies}
        with open(os.path.join(path, "context.pkl"), "wb") as f:
            pickle.dump(meta, f)

    def _load_checkpoint(self, context) -> bool:
        """Returns True when a checkpoint was resumed (strategies were
        notified via restore_from_checkpoint)."""
        if not self.checkpoint_path or not os.path.isdir(self.checkpoint_path):
            return False
        epochs = sorted(int(d) for d in os.listdir(self.checkpoint_path)
                        if d.isdigit())
        if not epochs:
            return False
        path = os.path.join(self.checkpoint_path, str(epochs[-1]))
        with open(os.path.join(path, "context.pkl"), "rb") as f:
            meta = pickle.load(f)
        context.epoch_id = meta["epoch_id"] + 1
        context.eval_results = meta["eval_results"]
        if meta.get("prune_ratios"):
            context.put("prune_ratios", meta["prune_ratios"])
        for cur, saved in zip(self.strategies, meta.get("strategies", [])):
            if type(cur) is type(saved):
                cur.__dict__.update(saved.__dict__)
        for s in self.strategies:
            s.restore_from_checkpoint(context)
        pio.load_persistables(self.executor, dirname=path,
                              main_program=context.optimize_graph,
                              scope=context.scope)
        return True

    # -- train loop (ref _train_one_epoch) -----------------------------------
    def _train_one_epoch(self, context: Context):
        if context.train_reader is None:
            return
        feed_names = [Context._fetch_name(f)
                      for f in context.train_feed_list]
        for batch_id, data in enumerate(context.train_reader()):
            context.batch_id = batch_id
            for s in self.strategies:
                s.on_batch_begin(context)
            feed = _make_feed(context.optimize_graph, feed_names, data)
            # metrics leave the device only on log steps (ref compressor.py
            # log_period; saves the per-step D2H transfer otherwise)
            log_step = batch_id % self.log_period == 0
            outs = context.executor.run(
                context.optimize_graph, feed=feed,
                fetch_list=context._optimize_fetches if log_step else [],
                scope=context.scope)
            if log_step:
                vals = ", ".join(
                    f"{n}={float(np.asarray(v).mean()):.6g}"
                    for n, v in zip(context._optimize_fetches, outs))
                _logger.info("epoch %d batch %d: %s",
                             context.epoch_id, batch_id, vals)
            for s in self.strategies:
                s.on_batch_end(context)

    def run(self) -> Context:
        context = self.context
        context.rebuild_optimize_graph()
        # on resume, restore_from_checkpoint (default: on_compression_begin)
        # already notified each strategy exactly once
        if not self._load_checkpoint(context):
            for s in self.strategies:
                s.on_compression_begin(context)
        start = context.epoch_id
        for epoch in range(start, self.epoch):
            context.epoch_id = epoch
            for s in self.strategies:
                s.on_epoch_begin(context)
            if not context.skip_training:
                self._train_one_epoch(context)
            context.skip_training = False
            for s in self.strategies:
                s.on_epoch_end(context)
            if context.eval_graph is not None and context.eval_reader and \
                    context.k_v.get("_evaled_epoch") != epoch:
                # skip when a strategy already scored this epoch (AutoPrune/
                # LightNAS) — their eval reflects the candidate, ours would
                # measure the restored weights
                context.run_eval_graph()
            self._save_checkpoint(context)
        for s in self.strategies:
            s.on_compression_end(context)
        return context


class ConfigFactory:
    """YAML strategy config loader (ref slim/core/config.py).

    Schema::

        version: 1.0
        strategies:
            quant_strategy:
                class: QuantizationStrategy
                start_epoch: 0
                ...
        compressor:
            epoch: 10
            checkpoint_path: ./ckpt
            strategies: [quant_strategy]     # optional subset/order
    """

    def __init__(self, config_file: str):
        import yaml
        with open(config_file) as f:
            cfg = yaml.safe_load(f) or {}
        self.compressor = cfg.get("compressor", {}) or {}
        defs = cfg.get("strategies", {}) or {}
        order = self.compressor.get("strategies") or list(defs)
        self.strategies = [self._build(defs[name]) for name in order]

    @staticmethod
    def _build(spec: dict) -> Strategy:
        from . import distillation, nas, prune, quantization
        spec = dict(spec)
        cls_name = spec.pop("class")
        for mod in (prune, distillation, nas, quantization):
            cls = getattr(mod, cls_name, None)
            if cls is not None:
                return cls(**spec)
        raise ValueError(f"unknown strategy class {cls_name!r}")
