"""Evolutionary token searchers (ref ``python/paddle/fluid/contrib/slim/
searcher/controller.py``: EvolutionaryController base + SAController
simulated annealing)."""

from __future__ import annotations

import math
from typing import Callable, List, Optional, Sequence

import numpy as np

__all__ = ["EvolutionaryController", "SAController"]


class EvolutionaryController:
    """Searches a token vector under a per-position range table
    (ref controller.py:28)."""

    def reset(self, range_table: Sequence[int],
              init_tokens: Optional[Sequence[int]] = None,
              constrain_func: Optional[Callable] = None):
        raise NotImplementedError

    def update(self, tokens: Sequence[int], reward: float):
        raise NotImplementedError

    def next_tokens(self) -> List[int]:
        raise NotImplementedError


class SAController(EvolutionaryController):
    """Simulated annealing over token vectors (ref controller.py:59).

    Accepts a worse candidate with probability exp(delta/temperature), with
    the temperature decayed by ``reduce_rate`` each update — classic SA so
    the search escapes local optima early and converges late."""

    def __init__(self, range_table: Optional[Sequence[int]] = None,
                 reduce_rate: float = 0.85, init_temperature: float = 1024,
                 max_iter_number: int = 300, seed: int = 0):
        self._range_table = list(range_table or [])
        self._reduce_rate = reduce_rate
        self._init_temperature = init_temperature
        self._max_iter_number = max_iter_number
        self._rng = np.random.RandomState(seed)
        self._constrain_func = None
        self._tokens: List[int] = []
        self._reward = -math.inf
        self._best_tokens: List[int] = []
        self._max_reward = -math.inf
        self._iter = 0

    # pickling for checkpoint (ref SAController.__getstate__)
    def __getstate__(self):
        d = dict(self.__dict__)
        d.pop("_constrain_func", None)
        return d

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._constrain_func = None

    @property
    def best_tokens(self):
        return list(self._best_tokens)

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens=None, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens) if init_tokens is not None else \
            [self._rng.randint(r) for r in self._range_table]
        self._best_tokens = list(self._tokens)
        self._reward = -math.inf
        self._max_reward = -math.inf
        self._iter = 0

    def update(self, tokens, reward):
        """Accept/reject ``tokens`` given its measured ``reward``."""
        self._iter += 1
        temperature = self._init_temperature * \
            self._reduce_rate ** self._iter
        if reward > self._reward or self._rng.random_sample() < math.exp(
                min((reward - self._reward) / max(temperature, 1e-12), 0.0)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self):
        """Perturb one random position of the current tokens."""
        for _ in range(self._max_iter_number):
            tokens = list(self._tokens)
            index = self._rng.randint(len(tokens))
            tokens[index] = self._rng.randint(self._range_table[index])
            if self._constrain_func is None or self._constrain_func(tokens):
                return tokens
        return list(self._tokens)
