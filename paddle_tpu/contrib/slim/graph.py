"""Graph wrapper for the compression toolkit (ref ``python/paddle/fluid/
contrib/slim/graph/graph_wrapper.py``: GraphWrapper over an IrGraph with
op/var queries, FLOPs counting, param backup/restore).

TPU-native shape: the wrapper holds a *forward* Program (pre-minimize) plus
the Scope with parameter values.  Strategies mutate the forward program (one
XLA recompile per mutation — static shapes preserved) and the Compressor
re-appends backward+optimizer; there is no per-op IrGraph surgery of grad
ops as in the reference.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...framework import core
from ...framework.core import Operator, Program, Variable

__all__ = ["GraphWrapper"]


def _numel(shape):
    n = 1
    for d in shape or ():
        n *= abs(int(d)) if d else 1
    return n


class GraphWrapper:
    """Query/mutation facade over (program, scope) used by slim strategies."""

    def __init__(self, program: Program, scope=None,
                 in_nodes: Optional[Dict[str, str]] = None,
                 out_nodes: Optional[Dict[str, str]] = None):
        self.program = program
        self.scope = scope
        self.in_nodes = dict(in_nodes or {})
        self.out_nodes = dict(out_nodes or {})

    # -- queries (ref GraphWrapper.ops/vars/pre_ops/next_ops) ----------------
    def ops(self) -> List[Operator]:
        return list(self.program.global_block().ops)

    def vars(self) -> List[Variable]:
        return list(self.program.global_block().vars.values())

    def var(self, name: str) -> Variable:
        return self.program.global_block().var(name)

    def all_parameters(self) -> List[Variable]:
        return self.program.global_block().all_parameters()

    def pre_ops(self, op: Operator) -> List[Operator]:
        ins = set(op.input_arg_names())
        return [o for o in self.ops()
                if o is not op and ins & set(o.output_arg_names())]

    def next_ops(self, op: Operator) -> List[Operator]:
        outs = set(op.output_arg_names())
        return [o for o in self.ops()
                if o is not op and outs & set(o.input_arg_names())]

    def ops_by_input(self, var_name: str) -> List[Operator]:
        return [o for o in self.ops() if var_name in o.input_arg_names()]

    def ops_by_output(self, var_name: str) -> List[Operator]:
        return [o for o in self.ops() if var_name in o.output_arg_names()]

    # -- stats (ref GraphWrapper.flops/numel_params) -------------------------
    def numel_params(self) -> int:
        return sum(_numel(p.shape) for p in self.all_parameters())

    def flops(self, only_conv: bool = False) -> int:
        """Multiply-accumulate count ×2 of conv/fc ops (ref
        GraphWrapper.flops)."""
        block = self.program.global_block()
        total = 0
        for op in self.ops():
            if op.type in ("conv2d", "depthwise_conv2d"):
                fshape = block.var(op.input("Filter")[0]).shape
                oshape = block.var(op.output("Output")[0]).shape
                # [O,I,kh,kw] filter × spatial output positions
                total += 2 * _numel(fshape) * _numel(oshape[-2:])
            elif op.type in ("mul", "matmul"):
                xs = block.var(op.input("X")[0]).shape
                ys = block.var(op.input("Y")[0]).shape
                total += 2 * _numel(xs) * int(ys[-1])
            elif not only_conv and op.type.startswith("elementwise"):
                total += _numel(block.var(op.output("Out")[0]).shape)
        return total

    # -- param snapshot (ref GraphWrapper backup used by prune/NAS) ----------
    def backup_params(self) -> Dict[str, np.ndarray]:
        snap = {}
        for p in self.all_parameters():
            v = self.scope.find_var(p.name) if self.scope else None
            if v is not None:
                snap[p.name] = np.array(v, copy=True)
        return snap

    def restore_params(self, snapshot: Dict[str, np.ndarray]) -> None:
        for name, value in snapshot.items():
            self.scope.set_var(name, value)

    def clone(self) -> "GraphWrapper":
        return GraphWrapper(self.program.clone(), self.scope,
                            self.in_nodes, self.out_nodes)
