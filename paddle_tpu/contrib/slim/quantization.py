"""Quantization-aware training passes (ref ``python/paddle/fluid/contrib/
slim/quantization/quantization_pass.py``: QuantizationTransformPass rewrites
the IrGraph inserting fake_quant/dequant pairs; QuantizationFreezePass bakes
trained scales in for inference).

TPU-native shape: the transform operates on the Program *before*
``append_backward`` and inserts the fused ``fake_quantize_dequantize_*`` ops
(straight-through-estimator gradient built in), so autodiff simply flows
through — no separate grad-graph surgery as in the reference's IrGraph
rewrite.  XLA then folds the round/clip arithmetic into neighbouring
kernels; the simulated-int8 training cost is a few elementwise ops.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np

from ...framework import core
from ...framework.core import Program
from .core import Strategy

__all__ = ["QuantizationTransformPass", "QuantizationFreezePass",
           "QuantizationStrategy"]

#: ops whose inputs get quantized (ref quantization_pass.py
#: _quantizable_op_type)
QUANTIZABLE_OPS = ("conv2d", "depthwise_conv2d", "mul", "matmul")

_QDQ_OPS = ("fake_quantize_dequantize_abs_max",
            "fake_channel_wise_quantize_dequantize_abs_max",
            "fake_quantize_dequantize_moving_average_abs_max")


class QuantizationTransformPass:
    """Insert weight + activation fake-quant-dequant before quantizable ops
    (ref QuantizationTransformPass.apply).

    weight_quantize_type: 'abs_max' | 'channel_wise_abs_max'
    activation_quantize_type: 'moving_average_abs_max' | 'abs_max'
    """

    def __init__(self, weight_bits: int = 8, activation_bits: int = 8,
                 activation_quantize_type: str = "moving_average_abs_max",
                 weight_quantize_type: str = "abs_max",
                 moving_rate: float = 0.9,
                 skip_pattern: str = "skip_quant"):
        if weight_quantize_type not in ("abs_max", "channel_wise_abs_max"):
            raise ValueError(f"bad weight_quantize_type "
                             f"{weight_quantize_type!r}")
        if activation_quantize_type not in ("abs_max",
                                            "moving_average_abs_max"):
            raise ValueError(f"bad activation_quantize_type "
                             f"{activation_quantize_type!r}")
        self._wbits = weight_bits
        self._abits = activation_bits
        self._act_type = activation_quantize_type
        self._w_type = weight_quantize_type
        self._moving_rate = moving_rate
        self._skip_pattern = skip_pattern

    # -- helpers -------------------------------------------------------------
    def _make_state(self, block, sblock, name, value):
        block.create_var(name=name, shape=(1,), dtype="float32",
                         persistable=True)
        if sblock is not None and not sblock.has_var(name):
            sblock.create_var(name=name, shape=(1,), dtype="float32",
                              persistable=True)
            sblock.append_op("fill_constant", outputs={"Out": [name]},
                             attrs={"shape": [1], "dtype": "float32",
                                    "value": float(value)})

    def _insert_qdq(self, block, sblock, idx, var_name, is_weight,
                    quant_axis=0, is_test=False):
        """Insert one QDQ op before ops[idx]; returns (new_idx, out_name)."""
        v = block.var(var_name)
        out = block.create_var(name=var_name + ".quantized",
                               shape=v.shape, dtype=v.dtype)
        scale_name = var_name + ".quant_scale"
        if is_weight:
            if self._w_type == "channel_wise_abs_max":
                op_type = "fake_channel_wise_quantize_dequantize_abs_max"
                block.create_var(name=scale_name,
                                 shape=(v.shape[quant_axis],),
                                 dtype="float32")
            else:
                op_type = "fake_quantize_dequantize_abs_max"
                block.create_var(name=scale_name, shape=(1,),
                                 dtype="float32")
            block.insert_op(
                idx, op_type,
                inputs={"X": [var_name]},
                outputs={"Out": [out.name], "OutScale": [scale_name]},
                attrs={"bit_length": self._wbits,
                       "quant_axis": quant_axis})
            return idx + 1, out.name
        if self._act_type == "abs_max":
            block.create_var(name=scale_name, shape=(1,), dtype="float32")
            block.insert_op(
                idx, "fake_quantize_dequantize_abs_max",
                inputs={"X": [var_name]},
                outputs={"Out": [out.name], "OutScale": [scale_name]},
                attrs={"bit_length": self._abits})
            return idx + 1, out.name
        # moving-average: persistable scale/state/accum trackers
        self._make_state(block, sblock, scale_name, 0.001)
        self._make_state(block, sblock, var_name + ".quant_state", 0.0)
        self._make_state(block, sblock, var_name + ".quant_accum", 0.0)
        block.insert_op(
            idx, "fake_quantize_dequantize_moving_average_abs_max",
            inputs={"X": [var_name], "InScale": [scale_name],
                    "InState": [var_name + ".quant_state"],
                    "InAccum": [var_name + ".quant_accum"]},
            outputs={"Out": [out.name], "OutScale": [scale_name],
                     "OutState": [var_name + ".quant_state"],
                     "OutAccum": [var_name + ".quant_accum"]},
            attrs={"bit_length": self._abits, "is_test": bool(is_test),
                   "moving_rate": self._moving_rate})
        return idx + 1, out.name

    # -- entry ---------------------------------------------------------------
    def apply(self, program: Optional[Program] = None,
              startup_program=None, is_test: bool = False) -> Program:
        """Rewrite IN PLACE (the reference mutates the IrGraph likewise);
        returns the program for chaining.  Call BEFORE minimize().

        ``startup_program``: Program to receive quant-state init ops;
        None → the global default startup; False → emit no init ops (for
        test-mode clones whose state vars are shared with the train
        program).  ``is_test``: emit frozen-scale QDQ ops that read but
        never update the moving-average trackers (for eval programs — the
        reference applies a test-mode transform to the eval IrGraph)."""
        program = program or core.default_main_program()
        startup = core.default_startup_program() \
            if startup_program is None else startup_program
        block = program.global_block()
        sblock = startup.global_block() if startup else None
        quantized: Dict[str, str] = {}     # var -> quantized var (per program)
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in QUANTIZABLE_OPS or \
                    op.attrs.get(self._skip_pattern):
                i += 1
                continue
            for slot, names in list(op.inputs.items()):
                new_names = []
                for name in names:
                    if not name or not block.has_var(name):
                        new_names.append(name)
                        continue
                    v = block.var(name)
                    if name in quantized:
                        new_names.append(quantized[name])
                        continue
                    is_weight = v.persistable
                    if is_weight and op.type in ("conv2d",
                                                 "depthwise_conv2d") \
                            and slot != "Filter":
                        new_names.append(name)   # conv bias etc.
                        continue
                    # per-OUTPUT-channel scales: conv filters [O,I,H,W] →
                    # axis 0; mul/matmul weights [in,out] → axis 1 (ref
                    # quantization_pass.py quant_axis selection)
                    axis = 1 if op.type in ("mul", "matmul") else 0
                    i, qname = self._insert_qdq(block, sblock, i, name,
                                                is_weight, quant_axis=axis,
                                                is_test=is_test)
                    quantized[name] = qname
                    new_names.append(qname)
                op.inputs[slot] = new_names
            i += 1
        program._bump_version()
        return program


class QuantizationFreezePass:
    """Bake trained quantization in for inference (ref
    QuantizationFreezePass): weight QDQ ops are folded numerically into the
    weight values (needs the scope), then stripped; activation QDQ ops flip
    to ``is_test`` so they quantize with the frozen moving-average scale."""

    def __init__(self, scope, weight_bits: int = 8,
                 weight_quantize_type: str = "abs_max"):
        self._scope = scope
        self._wbits = weight_bits
        self._w_type = weight_quantize_type

    def apply(self, program: Program) -> Program:
        block = program.global_block()
        keep = []
        renames: Dict[str, str] = {}
        for op in block.ops:
            if op.type in _QDQ_OPS:
                src = op.input("X")[0]
                dst = op.output("Out")[0]
                v = block.var(src)
                if v.persistable:        # weight: bake and strip
                    # the op's own bit_length, not the ctor default — the
                    # bake must match what training simulated
                    bnt = float(
                        (1 << (int(op.attrs.get("bit_length", 8)) - 1)) - 1)
                    w = np.asarray(self._scope.find_var(src), np.float64)
                    if op.type.startswith("fake_channel"):
                        axis = int(op.attrs.get("quant_axis", 0))
                        red = tuple(i for i in range(w.ndim) if i != axis)
                        s = np.maximum(np.abs(w).max(axis=red), 1e-8)
                        bshape = [1] * w.ndim
                        bshape[axis] = -1
                        s = s.reshape(bshape)
                    else:
                        s = max(np.abs(w).max(), 1e-8)
                    qdq = np.round(np.clip(w / s, -1, 1) * bnt) * s / bnt
                    self._scope.set_var(src, qdq.astype(np.float32))
                    renames[dst] = src
                    continue
                op.attrs["is_test"] = True   # activation: frozen scale
            keep.append(op)
        for op in keep:
            for slot, names in op.inputs.items():
                op.inputs[slot] = [renames.get(n, n) for n in names]
        block.ops = keep
        program._bump_version()
        return program


class QuantizationStrategy(Strategy):
    """Compressor strategy wrapping the two passes (ref
    slim/quantization/quantization_strategy.py:34): insert QDQ training ops
    at start_epoch, freeze + optionally save the int8-ready model at the
    end of the window."""

    def __init__(self, start_epoch=0, end_epoch=0, weight_bits=8,
                 activation_bits=8,
                 activation_quantize_type="moving_average_abs_max",
                 weight_quantize_type="abs_max",
                 save_in_nodes=None, save_out_nodes=None,
                 float_model_save_path=None):
        super().__init__(start_epoch, end_epoch)
        self._transform = QuantizationTransformPass(
            weight_bits, activation_bits, activation_quantize_type,
            weight_quantize_type)
        self._wbits = weight_bits
        self._w_type = weight_quantize_type
        self.save_in_nodes = save_in_nodes
        self.save_out_nodes = save_out_nodes
        self.float_model_save_path = float_model_save_path

    def restore_from_checkpoint(self, context):
        # epoch_id == start_epoch means the checkpoint predates the
        # transform (saved at start_epoch-1): the ordinary on_epoch_begin
        # will apply it.  Only re-apply when resuming PAST start_epoch, so
        # the quant state vars exist for load_persistables.
        if context.epoch_id > self.start_epoch:
            saved = context.epoch_id
            context.epoch_id = self.start_epoch
            self.on_epoch_begin(context)
            context.epoch_id = saved

    def on_epoch_begin(self, context):
        if context.epoch_id != self.start_epoch:
            return
        startup = core.Program()
        self._transform.apply(context.train_graph.program, startup)
        if context.eval_graph is not None:
            # eval clone shares the state vars — no init ops, and frozen
            # scales so evaluation never perturbs the EMA trackers
            self._transform.apply(context.eval_graph.program, False,
                                  is_test=True)
        context.executor.run(startup, scope=context.scope, fetch_list=[])
        context.rebuild_optimize_graph()

    def on_epoch_end(self, context):
        if context.epoch_id != self.end_epoch - 1:
            return
        graph = context.eval_graph or context.train_graph
        # freeze against a scope COPY: FreezePass bakes QDQ rounding into
        # the weights it touches, which must not leak into the live
        # training scope if the compressor keeps running
        from ...framework.scope import Scope
        frozen_scope = Scope()
        for v in graph.program.list_vars():
            if v.persistable and context.scope.find_var(v.name) is not None:
                frozen_scope.set_var(
                    v.name, np.array(context.scope.find_var(v.name),
                                     copy=True))
        frozen = QuantizationFreezePass(
            frozen_scope, self._wbits, self._w_type).apply(
                graph.program.clone())
        context.put("quantized_eval_program", frozen)
        context.put("quantized_eval_scope", frozen_scope)
        if self.float_model_save_path:
            from ... import io as pio
            outs = self.save_out_nodes or [
                context._fetch_name(f) for f in context.eval_fetch_list]
            ins = self.save_in_nodes or [
                context._fetch_name(f) for f in context.eval_feed_list]
            pio.save_inference_model(
                self.float_model_save_path, ins,
                [frozen.global_block().var(n) for n in outs],
                context.executor, main_program=frozen,
                scope=frozen_scope)
