"""Numerics observability plane: in-graph tensor-health statistics,
anomaly detection with auto-capture, and checkpoint quarantine.

PRs 8 and 11 built the *time*-domain observability plane (device
attribution, MFU, request tracing); nothing in the runtime observed
*values* — a NaN'd loss, an exploding grad norm, or a bf16 overflow was
invisible until a user eyeballed printed losses.  This module is the
value-domain counterpart:

- **In-graph stats** (:func:`build_step_stats`): behind ``FLAGS_numerics``
  (``off`` | ``sentinel`` | ``full``) the lowered step computes per-step
  tensor-health statistics INSIDE the jitted program — NaN/Inf trips
  for gradients and weight state plus the global grad norm at one
  reduction per tensor (``sentinel``), adding per-variable grad L2
  norms and absmax, element-exact finite masks, weight-update ratios
  (‖Δw‖/‖w‖), activation coverage and log2 dynamic-range histograms
  (``full``) — folded into ONE small packed f32 vector output per step.  The stats ride the PR-1
  lazy-fetch path: the training thread never syncs on them.

- **Anomaly engine** (:class:`NumericsEngine`, the process ``ENGINE``):
  materializes stats frames only once their arrays are ready (or a
  bounded backlog forces it — counted, never silent), runs NaN/Inf
  sentinel trips and windowed-median grad-norm spike detection with
  hysteresis, fires ``numerics.anomaly`` trace instants, opens a PR-9
  style profiler window (``trigger: "anomaly"`` in the manifest), and
  QUARANTINES the checkpoint plane: once a step is poisoned, the
  :class:`~paddle_tpu.resilience.CheckpointDaemon` holds commit so the
  gang manifest never advances past the last healthy step.

- **Surfaces**: per-variable gauges
  ``paddle_tpu_numerics_{grad_norm,update_ratio,absmax}`` with a bounded
  top-K registry series set (churn folds out, PR-2 retirement
  semantics), ``paddle_tpu_numerics_nonfinite_total{var_class}``
  counters, and the ``gnorm``/``nanf`` heartbeat-digest keys the gang
  coordinator folds into per-rank gauges and ``tools/gangtop.py``
  columns — a single rank producing NaNs is identifiable fleet-wide in
  one screen.

The dynamic-range histograms are the enabling signal for the ROADMAP's
quantized-collectives arc (EQuARX-style blockwise int8 needs per-tensor
dynamic range to pick scales; ``bench.py``'s loss-trajectory sha1 line
is the matching loss-parity gate).
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import monitor as _monitor

__all__ = [
    "MODES", "mode", "configure", "build_step_stats", "StatsLayout",
    "NumericsFrame", "NumericsEngine", "ENGINE", "record_anomaly",
    "note_nonfinite", "poisoned_since", "is_poisoned", "clear_quarantine",
    "plan_numerics", "loss_fingerprint",
]

MODES = ("off", "sentinel", "full")

#: per-variable sections traced in full mode are bounded: the largest
#: tensors dominate both numerics risk and cost, the tail folds into one
#: aggregate "other" section
MAX_TRACED_VARS = 32

#: log2 dynamic-range histogram bins: floor(log2|x|) clipped to
#: [_HIST_LO, _HIST_HI] — bf16's normal range is ~[-126, 127] but the
#: actionable band for int8 scale picking is this window
_HIST_LO, _HIST_HI = -20, 11
HIST_BINS = _HIST_HI - _HIST_LO + 1

# ---------------------------------------------------------------------------
# metric families (declared at import so digest presence-gating works the
# moment the engine publishes its first frame)
# ---------------------------------------------------------------------------

NUM_GNORM_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_numerics_grad_norm",
    "per-variable gradient L2 norm of the most recent processed step "
    "(top-K by norm; churn folds out so the registry stays bounded)",
    ("var",))
NUM_UPDATE_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_numerics_update_ratio",
    "per-variable weight-update ratio ‖Δw‖/‖w‖ of the most recent "
    "processed step (top-K by ratio) — the classic LR-sanity signal "
    "(healthy training sits around 1e-3)", ("var",))
NUM_ABSMAX_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_numerics_absmax",
    "per-variable gradient absmax of the most recent processed step "
    "(top-K; the bf16/int8 overflow headroom signal)", ("var",))
NUM_GLOBAL_GNORM_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_numerics_global_grad_norm",
    "global gradient L2 norm (sqrt of the sum over EVERY grad var, "
    "traced or not) of the most recent processed step — the heartbeat "
    "digest's 'gnorm' key")
NUM_RANGE_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_numerics_dynamic_range_bits",
    "occupied log2 dynamic range (highest - lowest populated exponent "
    "bin) of the most recent step's histogram, by class — the signal a "
    "blockwise-int8 quantization policy reads for scale headroom",
    ("var_class",))
NONFINITE_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_numerics_nonfinite_total",
    "non-finite (NaN/Inf) observations by variable class (grad / act / "
    "weight / logits): ELEMENT counts in full mode and the serving "
    "logits sentinel, poisoned-TENSOR counts in sentinel mode — the "
    "heartbeat digest's 'nanf' key", ("var_class",))
ANOMALY_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_numerics_anomalies_total",
    "numerics anomaly records by kind (nonfinite / grad_spike / "
    "nonfinite_logits / loss_scale_* / step_skipped)", ("kind",))
FORCED_SYNC_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_numerics_forced_syncs_total",
    "stats frames materialized by the backlog bound instead of the "
    "ready-poll — nonzero means the lazy path fell behind and the "
    "training thread paid a host sync")
QUARANTINE_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_checkpoint_quarantine_holds_total",
    "checkpoint captures held back because the numerics engine has the "
    "step quarantined (poisoned state must not advance the manifest)")


# ---------------------------------------------------------------------------
# configuration (mirrors FLAGS_numerics*; set_flags side-effects call
# configure(), the executor reads the module-level mode per dispatch)
# ---------------------------------------------------------------------------

_CONFIG = {
    "mode": "off",
    "spike_factor": 10.0,
    "window": 16,
    "topk": 8,
    "quarantine": True,
}


def mode() -> str:
    """The active ``FLAGS_numerics`` mode (one attribute read — the
    executor's per-dispatch fast path keys its plans on this)."""
    return _CONFIG["mode"]


def configure(mode: str, spike_factor: Optional[float] = None,
              window: Optional[int] = None, topk: Optional[int] = None,
              quarantine: Optional[bool] = None) -> None:
    if mode not in MODES:
        raise ValueError(
            f"FLAGS_numerics must be one of {MODES}, got {mode!r}")
    _CONFIG["mode"] = mode
    if spike_factor is not None:
        _CONFIG["spike_factor"] = float(spike_factor)
    if window is not None:
        _CONFIG["window"] = max(int(window), 4)
    if topk is not None:
        _CONFIG["topk"] = max(int(topk), 1)
    if quarantine is not None:
        _CONFIG["quarantine"] = bool(quarantine)


# ---------------------------------------------------------------------------
# compiler stat-capture slot: the post-fusion variable census
# ---------------------------------------------------------------------------

_plan_cache: Dict[Any, Dict[str, Any]] = {}
_plan_lock = threading.Lock()


def plan_numerics(program, fetch_names=()) -> Dict[str, Any]:
    """Static numerics-capture plan over the (post-fusion) program: the
    float intermediate activations the in-graph stats builder may trace
    in ``full`` mode.  Runs in ``compiler.optimize``'s pass slot AFTER
    fusion so fused programs census the variables the rewritten program
    actually produces, and is stamped into
    ``program._attrs["numerics"]`` (clone carries it onto the optimized
    program).  Fingerprint-cached; advisory — the trace-time builder
    intersects it with the live value environment, and grads/weights
    always trace regardless."""
    key = (program.fingerprint(), tuple(fetch_names))
    with _plan_lock:
        plan = _plan_cache.get(key)
        if plan is not None:
            return plan
    block = program.global_block()
    acts = []
    written = set()
    for op in block.ops:
        if op.type in ("feed", "fetch"):
            continue
        for n in op.output_arg_names():
            if not n or n in written:
                continue
            written.add(n)
            if not block.has_var(n):
                continue
            v = block.var(n)
            dt = str(getattr(v, "dtype", "") or "")
            if "float" not in dt and "bf16" not in dt:
                continue
            if not n.endswith("@GRAD") and not v.persistable:
                acts.append(n)
    # activations only: grads and weight state always trace from the
    # live value environment (missing one is exactly the blind spot to
    # avoid), so a census of them would be dead data
    plan = {"acts": sorted(acts)}
    with _plan_lock:
        if len(_plan_cache) > 256:
            _plan_cache.clear()
        _plan_cache.setdefault(key, plan)
    return plan


# ---------------------------------------------------------------------------
# trace-time stats builder
# ---------------------------------------------------------------------------

class StatsLayout:
    """Host-side description of one packed stats vector.

    Header (both modes): ``[nonfinite_grad, nonfinite_act,
    nonfinite_weight, global_gnorm_sq, grad_absmax, act_absmax]``.
    The weight-state scan matters even with the grad scan present: a
    NaN'd weight can hide from the backward entirely (``relu_grad``
    masks on ``x > 0``, and ``NaN > 0`` is False — the gradient comes
    back a clean 0) while the persisted state is poisoned forever.
    ``full`` appends, in order: 3 floats per traced grad (``gnorm_sq,
    absmax, nonfinite``), 2 per traced weight (``wnorm_sq, dnorm_sq``),
    then the grad and act log2 dynamic-range histograms
    (:data:`HIST_BINS` bins each)."""

    HEADER = 6

    def __init__(self, mode: str, grads: Tuple[str, ...] = (),
                 weights: Tuple[str, ...] = ()):
        self.mode = mode
        self.grads = tuple(grads)
        self.weights = tuple(weights)

    @property
    def size(self) -> int:
        if self.mode != "full":
            return self.HEADER
        return (self.HEADER + 3 * len(self.grads)
                + 2 * len(self.weights) + 2 * HIST_BINS)


def _is_float(v) -> bool:
    import jax.numpy as jnp
    dt = getattr(v, "dtype", None)
    if dt is None:
        return False
    try:
        return bool(jnp.issubdtype(dt, jnp.floating))
    except TypeError:
        return False


def _static_size(v) -> int:
    shape = getattr(v, "shape", None) or ()
    n = 1
    for d in shape:
        n *= int(d) if d else 1
    return n


def _exp_hist(parts):
    """Aggregate log2 dynamic-range histogram over a list of arrays:
    bin = clip(floor(log2|x|), lo, hi) over the finite nonzero
    elements.  One scatter-add per tensor — full-mode cost, by design."""
    import jax.numpy as jnp
    hist = jnp.zeros((HIST_BINS,), jnp.float32)
    for x in parts:
        ax = jnp.abs(jnp.ravel(x).astype(jnp.float32))
        ok = jnp.isfinite(ax) & (ax > 0)
        e = jnp.clip(jnp.floor(jnp.log2(jnp.where(ok, ax, 1.0))),
                     _HIST_LO, _HIST_HI)
        idx = (e - _HIST_LO).astype(jnp.int32)
        hist = hist.at[idx].add(jnp.where(ok, 1.0, 0.0))
    return hist


def build_step_stats(values: Dict[str, Any], written,
                     feed_names, persist_rw, rw_in, rw_out,
                     mode: str, spec: Optional[Dict[str, Any]] = None,
                     force: bool = False):
    """Trace-time: fold the block's tensor-health statistics into one
    packed f32 vector (returns ``(layout, packed)``, or ``(None, None)``
    when the block has nothing to observe — e.g. a startup program —
    and ``force`` is off; forcing returns an all-zero header so callers
    that need a fixed output arity, like the executor, always get one).

    ``sentinel`` observes GRADIENTS only (NaN/Inf counts, global norm,
    absmax) — NaN'd forward math poisons the backward within the same
    step, so a grad sentinel catches it at a fraction of the cost of
    scanning every activation.  ``full`` adds per-variable sections,
    weight-update ratios and activation absmax/dynamic-range
    histograms.

    Called from inside the lowered ``step()`` while tracing, so every
    operation here becomes part of the jitted program; the packed vector
    is ONE small extra output that rides the async dispatch.  ``spec``
    is the compiler's post-fusion census (advisory: intersected with the
    live value environment so a partially-fed program never KeyErrors).
    """
    import jax.numpy as jnp
    f32 = jnp.float32
    feed_set = set(feed_names)

    def _live_float(n):
        v = values.get(n)
        return v if v is not None and _is_float(v) \
            and getattr(v, "ndim", None) is not None else None

    grad_names = sorted(n for n in written
                        if n.endswith("@GRAD")
                        and _live_float(n) is not None)
    act_names = []
    if mode == "full":
        act_names = sorted(
            n for n in written
            if not n.endswith("@GRAD") and n not in feed_set
            and n not in persist_rw and _live_float(n) is not None
            and getattr(values[n], "ndim", 0) >= 1)
        if spec:
            # the compiler's census restricts activations (a fused
            # program's internal temporaries the census dropped stay
            # untraced); grads and weight state always trace — missing
            # one is exactly the blind spot to avoid
            allowed = set(spec.get("acts", ()))
            if allowed:
                act_names = [n for n in act_names if n in allowed]
    # weight pairs: rw persistables whose incoming value has the same
    # shape as the outgoing one (write-only rw gets dummy scalar zeros)
    weight_pairs = []
    if mode == "full":
        for n, old, new in zip(persist_rw, rw_in, rw_out):
            if (_is_float(new) and hasattr(old, "shape")
                    and getattr(old, "shape", None)
                    == getattr(new, "shape", None)
                    and _is_float(old) and (n + "@GRAD") in values):
                weight_pairs.append((n, old, new))
    state_vals = [v for v in rw_out if _is_float(v)
                  and getattr(v, "ndim", None) is not None]
    if not grad_names and not act_names and not weight_pairs \
            and not state_vals and not force:
        return None, None

    grad_vals = [values[n] for n in grad_names]
    act_vals = [values[n] for n in act_names]

    def _nonfinite(parts):
        t = jnp.zeros((), f32)
        for x in parts:
            t = t + jnp.sum(
                (~jnp.isfinite(x.astype(f32))).astype(f32))
        return t

    def _absmax(parts):
        if not parts:
            return jnp.zeros((), f32)
        return jnp.max(jnp.stack(
            [jnp.max(jnp.abs(x.astype(f32))) if x.size else
             jnp.zeros((), f32) for x in parts]))

    gnorm_sqs = [jnp.sum(jnp.square(g.astype(f32))) for g in grad_vals]
    if mode != "full":
        # sentinel tier: ONE reduction per tensor, total.  Finiteness is
        # derived from the reduction scalars (NaN/Inf propagate through
        # a sum), so the nonfinite_* header slots count poisoned TENSORS
        # here, not elements — the engine only needs > 0 to trip, and
        # the elementwise scans + absmax passes are exactly what pushed
        # the overhead past the 5% budget on small steps.
        def _tensor_trips(scalars):
            t = jnp.zeros((), f32)
            for s in scalars:
                t = t + (~jnp.isfinite(s)).astype(f32)
            return t

        state_sums = [jnp.sum(v.astype(f32)) for v in state_vals]
        header = [
            _tensor_trips(gnorm_sqs),
            jnp.zeros((), f32),
            _tensor_trips(state_sums),
            (sum(gnorm_sqs[1:], gnorm_sqs[0]) if gnorm_sqs
             else jnp.zeros((), f32)),
            jnp.zeros((), f32),
            jnp.zeros((), f32),
        ]
        return StatsLayout("sentinel"), jnp.stack(header)
    header = [
        _nonfinite(grad_vals),
        _nonfinite(act_vals),
        _nonfinite(state_vals),
        (sum(gnorm_sqs[1:], gnorm_sqs[0]) if gnorm_sqs
         else jnp.zeros((), f32)),
        _absmax(grad_vals),
        _absmax(act_vals),
    ]

    # full: per-variable sections for the largest tensors (bounded),
    # deterministic order (size desc, name asc) so retraces agree
    order = sorted(range(len(grad_names)),
                   key=lambda i: (-_static_size(grad_vals[i]),
                                  grad_names[i]))[:MAX_TRACED_VARS]
    traced_g = [grad_names[i] for i in order]
    per_var = []
    for i in order:
        g = grad_vals[i].astype(f32)
        per_var += [gnorm_sqs[i], jnp.max(jnp.abs(g)) if g.size else
                    jnp.zeros((), f32),
                    jnp.sum((~jnp.isfinite(g)).astype(f32))]
    worder = sorted(range(len(weight_pairs)),
                    key=lambda i: (-_static_size(weight_pairs[i][2]),
                                   weight_pairs[i][0]))[:MAX_TRACED_VARS]
    traced_w = [weight_pairs[i][0] for i in worder]
    for i in worder:
        _, old, new = weight_pairs[i]
        nf = new.astype(f32)
        per_var += [jnp.sum(jnp.square(nf)),
                    jnp.sum(jnp.square(nf - old.astype(f32)))]
    layout = StatsLayout("full", tuple(traced_g), tuple(traced_w))
    packed = jnp.concatenate([
        jnp.stack(header + per_var) if per_var else jnp.stack(header),
        _exp_hist(grad_vals), _exp_hist(act_vals)])
    return layout, packed


# ---------------------------------------------------------------------------
# host-side frame
# ---------------------------------------------------------------------------

class NumericsFrame:
    """One step's unpacked tensor-health statistics."""

    __slots__ = ("step", "nonfinite_grad", "nonfinite_act",
                 "nonfinite_weight", "global_gnorm",
                 "grad_absmax", "act_absmax", "grads", "weights",
                 "grad_hist", "act_hist")

    def __init__(self, step: int, vec: np.ndarray, layout: StatsLayout):
        if vec.ndim == 2:
            # collective shard_map mode stacks per-rank stats: counts
            # and hists SUM, absmax MAXes, norms average (grads are
            # replicated post-allreduce, activations are per-shard)
            v = vec.astype(np.float64)
            vec = np.where(
                np.isfinite(v).all(0), v.mean(0), np.float64("nan"))
            h = StatsLayout.HEADER
            for i in (0, 1, 2):
                vec[i] = v[:, i].sum()
            vec[4] = v[:, 4].max()
            vec[5] = v[:, 5].max()
            if layout.mode == "full":
                vec[-2 * HIST_BINS:] = v[:, -2 * HIST_BINS:].sum(0)
                for i in range(len(layout.grads)):
                    vec[h + 3 * i + 1] = v[:, h + 3 * i + 1].max()
                    vec[h + 3 * i + 2] = v[:, h + 3 * i + 2].sum()
        vec = np.asarray(vec, np.float64)
        self.step = int(step)
        self.nonfinite_grad = float(np.nan_to_num(vec[0], nan=1.0))
        self.nonfinite_act = float(np.nan_to_num(vec[1], nan=1.0))
        self.nonfinite_weight = float(np.nan_to_num(vec[2], nan=1.0))
        gsq = float(vec[3])
        self.global_gnorm = (float(np.sqrt(gsq)) if np.isfinite(gsq)
                             and gsq >= 0 else float("nan"))
        self.grad_absmax = float(vec[4])
        self.act_absmax = float(vec[5])
        self.grads: Dict[str, Dict[str, float]] = {}
        self.weights: Dict[str, Dict[str, float]] = {}
        self.grad_hist = self.act_hist = None
        if layout.mode == "full":
            off = StatsLayout.HEADER
            for n in layout.grads:
                sq, amax, nf = vec[off:off + 3]
                off += 3
                self.grads[n] = {
                    "norm": (float(np.sqrt(sq)) if np.isfinite(sq)
                             and sq >= 0 else float("nan")),
                    "absmax": float(amax), "nonfinite": float(nf)}
            for n in layout.weights:
                wsq, dsq = vec[off:off + 2]
                off += 2
                ratio = (float(np.sqrt(dsq / wsq))
                         if wsq > 0 and np.isfinite(wsq)
                         and np.isfinite(dsq) else 0.0)
                self.weights[n] = {
                    "wnorm": float(np.sqrt(max(wsq, 0.0))),
                    "update_ratio": ratio}
            self.grad_hist = vec[off:off + HIST_BINS]
            self.act_hist = vec[off + HIST_BINS:off + 2 * HIST_BINS]

    @property
    def nonfinite(self) -> float:
        return (self.nonfinite_grad + self.nonfinite_act
                + self.nonfinite_weight)

    @staticmethod
    def range_bits(hist) -> int:
        """Occupied log2 dynamic range of a histogram (0 = empty)."""
        nz = np.nonzero(np.asarray(hist) > 0)[0]
        return int(nz[-1] - nz[0] + 1) if nz.size else 0


# ---------------------------------------------------------------------------
# anomaly records (shared format: the engine, amp loss-scale events and
# the serving logits sentinel all emit these)
# ---------------------------------------------------------------------------

def record_anomaly(kind: str, step: Optional[int] = None,
                   var: Optional[str] = None,
                   value: Optional[float] = None,
                   detail: Optional[Dict[str, Any]] = None,
                   instant: str = "numerics.anomaly",
                   capture: bool = False,
                   quarantine: bool = False) -> Dict[str, Any]:
    """Append one anomaly record (the ONE record format every numerics
    event uses — engine trips, amp loss-scale events, serving logits
    sentinels): bumps ``paddle_tpu_numerics_anomalies_total{kind}``,
    emits the trace instant, optionally opens a profiler capture window
    (``trigger: "anomaly"`` in its manifest) and/or quarantines the
    checkpoint plane.  Returns the record."""
    rec: Dict[str, Any] = {"kind": kind, "t": time.time()}
    if step is not None:
        rec["step"] = int(step)
    if var is not None:
        rec["var"] = str(var)
    if value is not None:
        try:
            rec["value"] = float(value)
        except (TypeError, ValueError):
            rec["value"] = repr(value)
    if detail:
        rec.update(detail)
    ANOMALY_CTR.inc(1, kind=kind)
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant(instant, "numerics", dict(rec))
    ENGINE._note_record(rec, capture=capture, quarantine=quarantine)
    return rec


def note_nonfinite(var_class: str, n: int, step: Optional[int] = None,
                   detail: Optional[Dict[str, Any]] = None) -> None:
    """Out-of-graph sentinel entry point (the serving decode loop counts
    non-finite logits here): bumps the class counter and emits one
    anomaly record per episode (latched until a clean ``n == 0``
    observation un-latches the class)."""
    NONFINITE_CTR.inc(int(n), var_class=var_class)
    if int(n) > 0:
        ENGINE._class_trip(var_class, int(n), step=step, detail=detail)
    else:
        with ENGINE._mu:
            ENGINE._class_tripped.discard(var_class)


# ---------------------------------------------------------------------------
# the engine
# ---------------------------------------------------------------------------

class NumericsEngine:
    """Consumes in-flight stats frames and turns them into anomaly
    records, gauges and quarantine state.  All entry points are cheap
    and lock-guarded; frame materialization happens only for arrays
    that report ready (``jax.Array.is_ready``) or once the bounded
    backlog forces it (counted in
    ``paddle_tpu_numerics_forced_syncs_total``)."""

    MAX_BACKLOG = 8
    MAX_RECORDS = 256

    def __init__(self):
        self._mu = threading.Lock()
        self._pending: collections.deque = collections.deque()  # guarded-by: _mu
        self._windows: Dict[str, collections.deque] = {}  # guarded-by: _mu
        self._armed: Dict[str, bool] = {}  # guarded-by: _mu
        self._published: set = set()       # guarded-by: _mu
        self._published_w: set = set()     # guarded-by: _mu
        self._class_tripped: set = set()   # guarded-by: _mu
        self._poisoned_since: Optional[int] = None  # guarded-by: _mu
        self._nf_cells = {
            c: NONFINITE_CTR.labels(var_class=c)
            for c in ("grad", "act", "weight")}
        self.anomalies: collections.deque = collections.deque(
            maxlen=self.MAX_RECORDS)
        self.frames_processed = 0
        self.last_frame: Optional[NumericsFrame] = None

    # -- executor side -------------------------------------------------------
    def note_step(self, step_id: int, stats, layout: StatsLayout) -> None:
        """Register one dispatched step's in-flight stats array (the
        training thread; no sync — the array is still computing)."""
        with self._mu:
            self._pending.append((int(step_id), stats, layout))
        self.poll()

    def poll(self, force: bool = False) -> int:
        """Process ready frames.  ``force=True`` materializes EVERYTHING
        pending (a host sync — the checkpoint-quarantine gate and tests
        use it; never the steady-state dispatch path).  Returns the
        number of frames processed."""
        done = 0
        while True:
            with self._mu:
                if not self._pending:
                    return done
                step_id, stats, layout = self._pending[0]
                overflow = len(self._pending) > self.MAX_BACKLOG
                if not force and not overflow:
                    ready = getattr(stats, "is_ready", None)
                    try:
                        if ready is not None and not ready():
                            return done
                    except Exception:
                        pass
                self._pending.popleft()
            if overflow and not force:
                FORCED_SYNC_CTR.inc()
            try:
                frame = NumericsFrame(step_id, np.asarray(stats), layout)
            except Exception:
                continue         # a deleted/poisoned buffer never wedges us
            self._process(frame)
            done += 1

    # -- frame processing ----------------------------------------------------
    def _process(self, frame: NumericsFrame) -> None:
        self.frames_processed += 1
        self.last_frame = frame
        if np.isfinite(frame.global_gnorm):
            NUM_GLOBAL_GNORM_GAUGE.set(round(frame.global_gnorm, 6))
        self._nf_cells["grad"].inc(int(frame.nonfinite_grad))
        if frame.nonfinite_act:
            self._nf_cells["act"].inc(int(frame.nonfinite_act))
        if frame.nonfinite_weight:
            self._nf_cells["weight"].inc(int(frame.nonfinite_weight))
        if frame.grad_hist is not None:
            NUM_RANGE_GAUGE.set(frame.range_bits(frame.grad_hist),
                                var_class="grad")
            NUM_RANGE_GAUGE.set(frame.range_bits(frame.act_hist),
                                var_class="act")
        # -- NaN/Inf sentinel (latched per episode) ----------------------
        bad = frame.nonfinite > 0 or not np.isfinite(frame.global_gnorm)
        if bad:
            cls = ("weight" if frame.nonfinite_weight
                   else "grad" if frame.nonfinite_grad
                   or not np.isfinite(frame.global_gnorm) else "act")
            self._class_trip(
                cls, int(frame.nonfinite), step=frame.step,
                # absmax only exists in full mode — a hardwired 0.0 on
                # a sentinel record would read as "values are tiny"
                detail=({"grad_absmax": frame.grad_absmax,
                         "act_absmax": frame.act_absmax}
                        if frame.grad_hist is not None else None),
                in_graph=True)
        else:
            with self._mu:
                self._class_tripped -= {"grad", "act", "weight"}
        # -- per-var gauges + spike detection (full mode) ----------------
        if frame.grads:
            self._publish_vars(frame)
            self._detect_spikes(frame)

    def _publish_vars(self, frame: NumericsFrame) -> None:
        k = _CONFIG["topk"]
        top = sorted(frame.grads,
                     key=lambda n: -np.nan_to_num(
                         frame.grads[n]["norm"], nan=np.inf))[:k]
        wtop = sorted(frame.weights,
                      key=lambda n: -frame.weights[n]["update_ratio"])[:k]
        with self._mu:
            stale = self._published - set(top)
            stale_w = self._published_w - set(wtop)
            self._published = set(top)
            self._published_w = set(wtop)
        # PR-2 retirement semantics for gauges: churned-out vars DROP
        # (a stale per-var norm would read as live signal)
        for n in stale:
            NUM_GNORM_GAUGE.fold({"var": n}, None)
            NUM_ABSMAX_GAUGE.fold({"var": n}, None)
        for n in stale_w:
            NUM_UPDATE_GAUGE.fold({"var": n}, None)
        for n in top:
            g = frame.grads[n]
            NUM_GNORM_GAUGE.set(round(np.nan_to_num(
                g["norm"], nan=-1.0), 6), var=n)
            NUM_ABSMAX_GAUGE.set(round(np.nan_to_num(
                g["absmax"], nan=-1.0), 6), var=n)
        for n in wtop:
            NUM_UPDATE_GAUGE.set(
                round(frame.weights[n]["update_ratio"], 8), var=n)

    def _detect_spikes(self, frame: NumericsFrame) -> None:
        factor = _CONFIG["spike_factor"]
        wlen = _CONFIG["window"]
        for n, g in frame.grads.items():
            v = g["norm"]
            if not np.isfinite(v):
                continue             # the sentinel already tripped
            with self._mu:
                win = self._windows.get(n)
                if win is None or win.maxlen != wlen:
                    win = self._windows[n] = collections.deque(
                        list(win or ()), maxlen=wlen)
                    if len(self._windows) > 4 * MAX_TRACED_VARS:
                        # var churn across programs must not grow the
                        # window table forever
                        for dead in list(self._windows)[
                                :len(self._windows) // 2]:
                            if dead not in frame.grads:
                                del self._windows[dead]
                                self._armed.pop(dead, None)
                med = (sorted(win)[len(win) // 2] if win else None)
                armed = self._armed.get(n, True)
                fire = recover = False
                if med is not None and med > 0 and len(win) >= 4:
                    if v > factor * med:
                        if armed:
                            fire = True
                            self._armed[n] = False
                        # a spiking norm must not drag the median up to
                        # its own level and self-legitimize — freeze the
                        # window while tripped
                    else:
                        win.append(v)
                        if not armed and v <= (factor / 2.0) * med:
                            recover = self._armed[n] = True
                else:
                    win.append(v)
                med_out = med
            if fire:
                record_anomaly(
                    "grad_spike", step=frame.step, var=n, value=v,
                    detail={"median": round(float(med_out), 6),
                            "factor": factor}, capture=True)
            elif recover and _monitor.TRACER.enabled:
                _monitor.TRACER.instant(
                    "numerics.recovered", "numerics",
                    {"var": n, "step": frame.step, "value": v})

    # -- anomaly plumbing ----------------------------------------------------
    def _class_trip(self, var_class: str, n: int,
                    step: Optional[int] = None,
                    detail: Optional[Dict[str, Any]] = None,
                    in_graph: bool = False) -> None:
        with self._mu:
            first = var_class not in self._class_tripped
            self._class_tripped.add(var_class)
        if first:
            record_anomaly(
                "nonfinite" if in_graph else f"nonfinite_{var_class}",
                step=step, var=var_class, value=n, detail=detail,
                capture=True,
                quarantine=in_graph
                and var_class in ("grad", "act", "weight"))

    def _note_record(self, rec: Dict[str, Any], capture: bool,
                     quarantine: bool) -> None:
        self.anomalies.append(rec)
        if quarantine and _CONFIG["quarantine"]:
            with self._mu:
                if self._poisoned_since is None:
                    self._poisoned_since = int(rec.get("step", 0) or 0)
                    poisoned = self._poisoned_since
                else:
                    poisoned = None
            if poisoned is not None and _monitor.TRACER.enabled:
                _monitor.TRACER.instant(
                    "numerics.quarantine", "numerics",
                    {"since_step": poisoned, "kind": rec.get("kind")})
        if capture:
            try:
                from ..profiler import SAMPLER
                SAMPLER.trigger_window(rec.get("step"), trigger="anomaly")
            except Exception:
                pass             # capture is best-effort, never the step

    # -- quarantine ----------------------------------------------------------
    def poisoned_since(self) -> Optional[int]:
        with self._mu:
            return self._poisoned_since

    def clear_quarantine(self) -> None:
        """Operator action: the poisoned state was rolled back (e.g.
        resume_or_init restored the last healthy manifest step) — the
        checkpoint plane may commit again."""
        with self._mu:
            self._poisoned_since = None
            self._class_tripped.clear()

    def reset(self) -> None:
        """Full state reset (tests / bench isolation)."""
        with self._mu:
            self._pending.clear()
            self._windows.clear()
            self._armed.clear()
            self._published.clear()
            self._published_w.clear()
            self._class_tripped.clear()
            self._poisoned_since = None
        self.anomalies.clear()
        self.frames_processed = 0
        self.last_frame = None


ENGINE = NumericsEngine()


def poisoned_since() -> Optional[int]:
    return ENGINE.poisoned_since()


def is_poisoned() -> bool:
    return ENGINE.poisoned_since() is not None


def clear_quarantine() -> None:
    ENGINE.clear_quarantine()


# ---------------------------------------------------------------------------
# loss-trajectory fingerprint (bench.py's loss-parity gate)
# ---------------------------------------------------------------------------

def loss_fingerprint(losses, decimals: int = 5) -> str:
    """sha1 over the rounded loss trajectory — the loss-parity gate the
    quantized-collectives arc compares across codec configurations (and
    bench.py compares across FLAGS_numerics modes: the stats outputs
    must never perturb the training math)."""
    a = np.round(np.asarray(list(losses), np.float64), decimals)
    return hashlib.sha1(a.tobytes()).hexdigest()
