"""Collective-communication observability: static comms plan + runtime
measurement — the network's counterpart of the ``analysis.cost`` compute
attribution plane.

The framework already attributes compute (PR 8: live MFU from the
analytic flop model), requests (PR 11: trace propagation) and tensor
values (PR 12: numerics), but the collective path has been a black box:
no per-collective bytes, no measured bandwidth, no way to tell "slow
wire" from "waiting on a straggler".  The GSPMD and quantized-collective
arcs (PAPERS.md: EQuARX, arXiv 2506.17615; ZeRO, arXiv 2004.13336) live
or die on allreduce bandwidth — this module makes every bandwidth claim
they will make measurable.

Three layers:

- **Static comms plan** (:func:`plan_comms`): walk the dependency-ordered
  ``framework.ir`` Graph (the verifier/cost discipline), price every
  ``c_*`` collective with its payload bytes and the standard algorithm-
  bandwidth model — a ring allreduce moves ``2(n-1)/n·bytes`` per rank,
  allgather/reduce-scatter/broadcast ``(n-1)/n·bytes`` — and divide by a
  per-device-kind link-bandwidth table (:func:`device_link_bandwidth`,
  mirroring ``cost.device_peak_flops``) for an analytic comm-time
  estimate.  Compared against the cost plan's compute estimate this
  yields a static comm-vs-compute bound verdict per program.  Cached on
  the program fingerprint; the verifier stamps it into
  ``program._attrs["verify"]["comms"]`` and folds the plan fingerprint
  into the cross-rank collective fingerprint, so a gang whose ranks hold
  DIFFERENT comms plans refuses at the step barrier
  (``GangFingerprintError``) instead of hanging inside a collective.

- **Runtime measurement** (:class:`CommsMonitor` + the executor's
  collective shard_map path): every collective step dispatch is a
  ``collective.launch`` — the executor bumps the per-collective byte
  counters synchronously, exchanges a pre-collective host timestamp
  through the gang coordinator's ``comm_gate`` (the socket-plane form of
  a timestamp allgather), and hands the step's probe array to this
  module's background monitor thread.  The monitor blocks OFF-THREAD
  until the step retires and decomposes the measured wall time into
  *straggler wait* (max peer arrival skew, measured by the gate) vs
  *wire time* (post-arrival execution, attributed to comm by the plan's
  analytic comm share — in-graph collectives are fused into the step, so
  the share is the honest apportionment until device traces refine it).
  Feeds ``paddle_tpu_collective_ms{op,signature}`` /
  ``paddle_tpu_collective_bytes_total`` / ``paddle_tpu_collective_wait_ms``
  and the live ``paddle_tpu_collective_bus_bw`` gauge (measured algorithm
  bandwidth over link peak — the network's MFU analogue), plus
  ``collective.launch`` tracer spans carrying ``{signature, bytes,
  wait_ms, step_id}`` so comm spans correlate with the PR-8 device
  traces.  The training thread never blocks on the device for any of it.

- **Fleet surfaces**: the heartbeat digest gains ``comm_ms`` /
  ``comm_wait`` / ``comm_bw`` keys (monitor.metrics_digest), the
  coordinator folds them into per-rank gauges and computes the straggler
  NET of comm wait (a rank stalled waiting on a peer must not read as
  the slow one), gangtop grows COMM/BW% columns with a
  straggler-consistent COMM-BOUND flag, and ``bench.py`` /
  ``tools/comms_smoke.py`` gate analytic-vs-measured bytes and the wait
  decomposition in CI.

Gating: ``FLAGS_comms_telemetry`` (default on — the per-step cost is a
few counter bumps and one queue append; the coordinator gate engages
only when a socket gang is attached).
"""

from __future__ import annotations

import collections
import hashlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import monitor as _monitor
from ..framework.core import Block, Program

__all__ = [
    "CollectiveCost", "CommsPlan", "plan_comms", "clear_cache",
    "device_link_bandwidth", "CommsMonitor", "MONITOR",
]

# ---------------------------------------------------------------------------
# metric families (written here and by the executor's launch path; read by
# monitor.metrics_digest for the gang heartbeat keys)
# ---------------------------------------------------------------------------

COLLECTIVE_MS_HIST = _monitor.REGISTRY.histogram(
    "paddle_tpu_collective_ms",
    "measured per-collective wire time (ms) per dispatched collective "
    "step, apportioned across the step's collectives by wire bytes "
    "(in-graph collectives are fused into the step; the step's comm "
    "share is the analytic apportionment)", ("op", "signature"),
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
             50.0, 100.0, 250.0, 500.0, 1000.0, 5000.0, 30000.0))
COLLECTIVE_BYTES_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_collective_bytes_total",
    "collective payload bytes launched (static-plan bytes accounted per "
    "dispatched collective step — tools/comms_smoke.py gates this "
    "against the plan exactly)", ("op", "signature"))
COLLECTIVE_WAIT_HIST = _monitor.REGISTRY.histogram(
    "paddle_tpu_collective_wait_ms",
    "straggler wait per collective step (ms): max peer arrival skew "
    "measured by the pre-collective coordinator timestamp exchange "
    "(0 with no gang attached — all local ranks arrive together)",
    buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0,
             50.0, 100.0, 250.0, 500.0, 1000.0, 5000.0, 30000.0))
COMM_BW_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_collective_bus_bw",
    "measured algorithm bandwidth over the device link peak, in [0,1] "
    "— the network's MFU analogue (windowed median; digest key "
    "'comm_bw')")
COMM_STEP_MS_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_comm_step_ms",
    "measured comm time per collective step (ms), wait + wire "
    "(windowed median; digest key 'comm_ms')")
COMM_WAIT_MS_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_comm_wait_ms",
    "straggler-wait part of paddle_tpu_comm_step_ms (ms; windowed "
    "median; digest key 'comm_wait') — the coordinator subtracts it "
    "from step_ms when picking the straggler, so a rank stalled "
    "WAITING on a slow peer is never itself flagged slow")
COMMS_DROPPED_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_comms_records_dropped_total",
    "collective launch records dropped because the comms monitor's "
    "bounded queue was full (byte counters are bumped synchronously "
    "and stay exact; only the timing sample is lost)")
COMMS_GATE_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_comms_gate_total",
    "pre-collective coordinator timestamp exchanges by outcome "
    "('released' = every rank arrived, 'partial' = timeout or "
    "dead/departed peer, 'error' = transport failure, 'disabled' = "
    "gate latched off after repeated failures)", ("outcome",))

#: op type -> fraction of the payload each rank moves over the wire.
#: Ring algorithms: allreduce = reduce-scatter + allgather = 2(n-1)/n;
#: allgather / reduce-scatter / broadcast (ring pipeline) = (n-1)/n;
#: c_split is a local slice (no wire traffic).
_ALGO_FACTOR = {
    "c_allreduce_sum": lambda n: 2.0 * (n - 1) / n,
    "c_allreduce_max": lambda n: 2.0 * (n - 1) / n,
    "c_allreduce_min": lambda n: 2.0 * (n - 1) / n,
    # pprod lowers to allgather + local reduce (collective_ops._pprod)
    "c_allreduce_prod": lambda n: (n - 1) / n,
    "c_allgather": lambda n: (n - 1) / n,
    "c_reducescatter": lambda n: (n - 1) / n,
    "c_broadcast": lambda n: (n - 1) / n,
    "c_split": lambda n: 0.0,
}


def device_link_bandwidth(device=None) -> float:
    """Peak per-chip ICI link bandwidth in bytes/s — the bus-bandwidth
    denominator shared by the static plan's analytic comm-time estimate
    and the live ``paddle_tpu_collective_bus_bw`` gauge (the two
    accountings must divide by the SAME peak, exactly the
    ``cost.device_peak_flops`` discipline).  Values are the published
    per-chip interconnect bandwidths; CPU backends get a nominal 1e10
    smoke constant (the CPU "wire" is memcpy — the constant only keeps
    the estimate finite and the gauge in a plottable range)."""
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            return 1e10
    platform = getattr(device, "platform", "cpu")
    if platform not in ("tpu", "axon"):
        return 1e10
    # per-chip ICI: v4 2400 Gbps, v5e 1600 Gbps, v5p 4800 Gbps
    bw = {"v5e": 200e9, "v5lite": 200e9, "v5": 200e9,
          "v4": 300e9, "v5p": 600e9}
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    return next((bw[k] for k in sorted(bw, key=len, reverse=True)
                 if k in kind), 200e9)


_ITEMSIZE = {"bfloat16": 2, "float16": 2, "bool": 1}


def _itemsize(dtype) -> int:
    d = str(dtype or "float32")
    if d in _ITEMSIZE:
        return _ITEMSIZE[d]
    try:
        return int(np.dtype(d).itemsize)
    except TypeError:
        return 4


@dataclass(frozen=True)
class CollectiveCost:
    """One collective's static price at the resolved batch."""

    #: block path ("0" = top block; loop bodies e.g. "0/while@5/1")
    path: str
    #: dependency-order position within its block
    pos: int
    op: str
    ring_id: int
    dtype: str
    shape: Tuple[int, ...]
    #: logical payload bytes (numel x itemsize at the resolved batch)
    payload_bytes: int
    #: bytes each rank moves over the wire (payload x algorithm factor)
    wire_bytes: int
    #: analytic wire time at link peak (ms)
    est_ms: float

    @property
    def signature(self) -> str:
        """Compact label-safe signature (the {signature} metric label)."""
        dims = "x".join(str(d) for d in self.shape) or "scalar"
        return f"{self.op}:r{self.ring_id}:{self.dtype}:{dims}"


@dataclass
class CommsPlan:
    """Analytic per-step comms model of one program (see module doc)."""

    nranks: int = 1
    link_bw: float = 1e10
    batch_size: int = 1
    collectives: List[CollectiveCost] = field(default_factory=list)
    #: total logical payload bytes per step across collectives
    payload_bytes: int = 0
    #: total per-rank wire bytes per step (algorithm-model traffic)
    wire_bytes: int = 0
    #: analytic comm time per step at link peak (ms)
    est_ms: float = 0.0
    #: analytic compute time per step at chip peak (ms; from the cost
    #: plan — 0.0 when cost planning failed)
    compute_ms: float = 0.0
    #: sha1 over (nranks, ordered (path, op, ring, dtype, shape, bytes))
    #: — the cross-rank parity token folded into the collective
    #: fingerprint
    fingerprint: str = ""

    @property
    def comm_frac(self) -> float:
        """Analytic comm share of the step, in [0, 1]."""
        total = self.est_ms + self.compute_ms
        return self.est_ms / total if total > 0 else 0.0

    @property
    def bound(self) -> str:
        """Static verdict: what bounds the step if nothing overlaps."""
        if not self.collectives:
            return "compute"
        return "comm" if self.est_ms > self.compute_ms else "compute"

    def report(self) -> str:
        lines = [
            f"comms plan (nranks={self.nranks}, batch={self.batch_size}, "
            f"link {self.link_bw / 1e9:.0f} GB/s): "
            f"{len(self.collectives)} collective(s), "
            f"{self.payload_bytes / 1e6:.2f} MB payload, "
            f"{self.wire_bytes / 1e6:.2f} MB wire, "
            f"est {self.est_ms:.3f} ms comm vs {self.compute_ms:.3f} ms "
            f"compute -> {self.bound}-bound "
            f"(comm share {self.comm_frac:.1%})"]
        for c in self.collectives:
            lines.append(
                f"  {c.path}#{c.pos:<4} {c.signature:<48} "
                f"{c.payload_bytes / 1e6:8.3f} MB  "
                f"wire {c.wire_bytes / 1e6:8.3f} MB  {c.est_ms:7.4f} ms")
        return "\n".join(lines)


def _shape_of(block: Block, name, batch_size: int):
    if not name or not block.has_var(name):
        return None, "float32"
    v = block.var(name)
    if v.shape is None:
        return None, str(v.dtype or "float32")
    return tuple(batch_size if d in (-1, None) else int(d)
                 for d in v.shape), str(v.dtype or "float32")


# (program fingerprint, fetch tuple, batch, nranks) -> CommsPlan; bounded
# FIFO — the verifier/cost/memory cache discipline
_CACHE: Dict[tuple, CommsPlan] = {}  # guarded-by: _CACHE_LOCK
_CACHE_CAP = 128
_CACHE_LOCK = threading.Lock()


def clear_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()


def plan_comms(program: Program, fetch_names=(), batch_size: int = 1,
               nranks: Optional[int] = None) -> Optional[CommsPlan]:
    """Static comms plan for one program, or None when the program
    launches no collectives (and carries no ``collective`` attr).
    ``nranks`` defaults to the transpiler's ``_attrs["collective"]``
    stamp, falling back to the visible device count.  Cached on
    (program fingerprint, fetch tuple, batch, nranks)."""
    fetch_names = tuple(
        f.name if hasattr(f, "name") else f for f in (fetch_names or ()))
    if nranks is None:
        coll = program._attrs.get("collective") or {}
        nranks = int(coll.get("nranks", 0) or 0)
        if nranks <= 0:
            try:
                import jax
                nranks = len(jax.devices())
            except Exception:
                nranks = 1
    nranks = max(int(nranks), 1)
    key = (program.fingerprint(), fetch_names, int(batch_size), nranks)
    with _CACHE_LOCK:
        cached = _CACHE.get(key)
    if cached is not None:
        return cached if cached.collectives or cached.nranks else None
    with _monitor.TRACER.span("comms.plan", "compile",
                              fetches=len(fetch_names)):
        plan = _plan(program, fetch_names, int(batch_size), nranks)
    if plan is None:
        # negative result: cache an empty marker so steady-state
        # dispatch of collective-free programs stays a dict probe
        plan_obj = CommsPlan(nranks=0)
    else:
        plan_obj = plan
    with _CACHE_LOCK:
        if key not in _CACHE:
            if len(_CACHE) >= _CACHE_CAP:
                _CACHE.pop(next(iter(_CACHE)))
            _CACHE[key] = plan_obj
        plan_obj = _CACHE[key]
    return plan_obj if plan_obj.nranks else None


def _plan(program: Program, fetch_names, batch_size: int,
          nranks: int) -> Optional[CommsPlan]:
    from ..framework import ir
    from .verifier import _COLLECTIVE_OPS, sub_blocks_of

    link_bw = device_link_bandwidth()
    entries: List[CollectiveCost] = []

    def gather(block_graph, path: str):
        block = program.blocks[block_graph.block_idx]
        order = {n.id: i for i, n in enumerate(
            block_graph.topology_sort())}
        pos = {id(op): i for i, op in enumerate(block.ops)}
        for n in sorted(block_graph.op_nodes,
                        key=lambda n: (order.get(n.id, 0), n.id)):
            if n.name in _COLLECTIVE_OPS:
                op = n.op
                x = op.input("X")
                shape, dtype = _shape_of(block, x[0] if x else None,
                                         batch_size)
                numel = 1
                for d in (shape or ()):
                    numel *= max(int(d), 1)
                payload = (numel if shape is not None else 1) \
                    * _itemsize(dtype)
                factor = _ALGO_FACTOR.get(n.name, lambda n_: 1.0)(nranks) \
                    if nranks > 1 else 0.0
                wire = int(payload * factor)
                entries.append(CollectiveCost(
                    path=path,
                    pos=order.get(n.id, 0),
                    op=n.name,
                    ring_id=int(op.attrs.get("ring_id", 0) or 0),
                    dtype=dtype,
                    shape=tuple(shape or ()),
                    payload_bytes=int(payload),
                    wire_bytes=wire,
                    est_ms=wire / link_bw * 1e3))
            subs = sub_blocks_of(n.op)
            if subs:
                idx = pos.get(id(n.op), order.get(n.id, 0))
                for _, sub in subs:
                    gather(ir.Graph(program, sub.idx),
                           f"{path}/{n.name}@{idx}/{sub.idx}")

    gather(ir.Graph(program), "0")
    if not entries and not program._attrs.get("collective"):
        return None

    # compute-side estimate (analysis.cost; never blocks planning)
    compute_ms = 0.0
    try:
        from .cost import device_peak_flops, plan_cost
        cplan = plan_cost(program, fetch_names, batch_size=batch_size)
        compute_ms = cplan.flops / device_peak_flops() * 1e3
    except Exception:
        pass

    h = hashlib.sha1()
    h.update(repr(nranks).encode())
    for c in entries:
        h.update(repr((c.path, c.op, c.ring_id, c.dtype, c.shape,
                       c.payload_bytes)).encode())
    plan = CommsPlan(
        nranks=nranks, link_bw=link_bw, batch_size=batch_size,
        collectives=entries,
        payload_bytes=sum(c.payload_bytes for c in entries),
        wire_bytes=sum(c.wire_bytes for c in entries),
        est_ms=sum(c.est_ms for c in entries),
        compute_ms=compute_ms,
        fingerprint=h.hexdigest())
    return plan


def stamp_attrs(plan: Optional[CommsPlan]) -> Optional[dict]:
    """The machine-readable ``_attrs["verify"]["comms"]`` payload other
    layers (tools/analyze, bench, the quantized-collectives gate) read
    without re-planning."""
    if plan is None:
        return None
    return {
        "nranks": plan.nranks,
        "link_bw": plan.link_bw,
        "payload_bytes": plan.payload_bytes,
        "wire_bytes": plan.wire_bytes,
        "est_ms": round(plan.est_ms, 6),
        "compute_ms": round(plan.compute_ms, 6),
        "comm_frac": round(plan.comm_frac, 6),
        "bound": plan.bound,
        "fingerprint": plan.fingerprint,
        "collectives": [
            (c.path, c.pos, c.op, c.signature, c.payload_bytes,
             c.wire_bytes) for c in plan.collectives],
    }


# ---------------------------------------------------------------------------
# runtime measurement
# ---------------------------------------------------------------------------

class CommsMonitor:
    """Background decomposer of collective launch records.

    The executor's collective dispatch path hands every launch a record
    (step id, the step's never-donated probe array, the comms plan, the
    gate-cleared start time, the measured straggler wait).  A daemon
    worker blocks on the probe OFF the training thread, so the
    measurement costs the hot path one deque append — then publishes:

    - per-collective wire-time histograms and the bus-bandwidth gauge
      (wire time = post-arrival execution x the plan's analytic comm
      share, apportioned across collectives by wire bytes);
    - the straggler-wait histogram and the windowed-median
      ``comm_step_ms`` / ``comm_wait_ms`` / ``bus_bw`` gauges the gang
      digest carries;
    - a ``collective.launch`` tracer span per step with ``{signature,
      bytes, wait_ms, step_id}`` — stamped with the REAL launch/retire
      timestamps, so it overlays the PR-8 device traces.

    The queue is bounded: under backlog the oldest record's timing
    sample is dropped (counted) — byte counters are bumped synchronously
    at dispatch and stay exact regardless.
    """

    MAX_PENDING = 8
    _WINDOW = 9

    def __init__(self):
        self._cv = threading.Condition()
        self._pending: collections.deque = collections.deque()  # guarded-by: _cv
        self._inflight = 0                                      # guarded-by: _cv
        self._thread: Optional[threading.Thread] = None         # guarded-by: _cv
        self._ms_win: collections.deque = collections.deque(
            maxlen=self._WINDOW)                                # guarded-by: _cv
        self._wait_win: collections.deque = collections.deque(
            maxlen=self._WINDOW)                                # guarded-by: _cv
        self._bw_win: collections.deque = collections.deque(
            maxlen=self._WINDOW)                                # guarded-by: _cv
        #: wall-clock time of the last gauge publish — metrics_digest
        #: drops the comm_* digest keys once this goes stale, so a rank
        #: that STOPPED dispatching collectives doesn't haunt the
        #: straggler math with frozen medians (the same frozen-value
        #: discipline the coordinator's _fold_digest applies)
        self.last_publish_wall = 0.0

    def _ensure_thread_locked(self):  # guarded-by-caller: _cv
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="pt-comms-monitor")
            self._thread.start()

    def note_launch(self, step_id: int, probe, plan: CommsPlan,
                    t_start: float, t_dispatch: float,
                    wait_ms: Optional[float]) -> None:
        """Queue one collective launch for off-thread decomposition.
        ``t_start``/``t_dispatch`` are perf_counter seconds (gate-cleared
        launch entry / dispatch return); ``wait_ms`` is the gate-measured
        straggler wait (None = no gang attached)."""
        with self._cv:
            self._ensure_thread_locked()
            if len(self._pending) >= self.MAX_PENDING:
                self._pending.popleft()
                COMMS_DROPPED_CTR.inc()
            self._pending.append(
                (step_id, probe, plan, t_start, t_dispatch, wait_ms))
            self._cv.notify()

    def drain(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued record is decomposed (tests, bench,
        smoke teardown).  Returns False on timeout."""
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._pending or self._inflight:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(timeout=min(left, 0.1))
        return True

    def _loop(self):
        while True:
            with self._cv:
                while not self._pending:
                    self._cv.wait()
                rec = self._pending.popleft()
                self._inflight += 1
            try:
                self._decompose(*rec)
            except Exception:
                pass             # telemetry must never kill the worker
            finally:
                with self._cv:
                    self._inflight -= 1
                    self._cv.notify_all()

    def _decompose(self, step_id, probe, plan, t_start, t_dispatch,
                   wait_ms):
        if hasattr(probe, "block_until_ready"):
            probe.block_until_ready()
        t_ready = time.perf_counter()
        exec_ms = max((t_ready - t_start) * 1e3, 0.0)
        wire_ms = exec_ms * plan.comm_frac
        wait = float(wait_ms) if wait_ms is not None else 0.0
        comm_ms = wait + wire_ms
        total_wire = float(plan.wire_bytes) or 1.0
        for c in plan.collectives:
            COLLECTIVE_MS_HIST.observe(
                wire_ms * (c.wire_bytes / total_wire),
                op=c.op, signature=c.signature)
        COLLECTIVE_WAIT_HIST.observe(wait)
        # measured algorithm bandwidth over link peak — the network MFU
        bus_bw = 0.0
        if plan.wire_bytes and wire_ms > 0:
            bus_bw = (plan.wire_bytes / (wire_ms / 1e3)) / plan.link_bw
        with self._cv:
            self._ms_win.append(comm_ms)
            self._wait_win.append(wait)
            self._bw_win.append(bus_bw)
            med_ms = sorted(self._ms_win)[len(self._ms_win) // 2]
            med_wait = sorted(self._wait_win)[len(self._wait_win) // 2]
            med_bw = sorted(self._bw_win)[len(self._bw_win) // 2]
        COMM_STEP_MS_GAUGE.set(med_ms)
        COMM_WAIT_MS_GAUGE.set(med_wait)
        COMM_BW_GAUGE.set(med_bw)
        self.last_publish_wall = time.time()
        if _monitor.TRACER.enabled:
            _monitor.TRACER.add_complete(
                "collective.launch", "collective", t_start, t_ready,
                {"signature": plan.fingerprint[:12],
                 "bytes": plan.payload_bytes,
                 "wire_bytes": plan.wire_bytes,
                 "wait_ms": round(wait, 3),
                 "wire_ms": round(wire_ms, 3),
                 "nranks": plan.nranks,
                 "step_id": step_id,
                 "dispatch_ms": round((t_dispatch - t_start) * 1e3, 3)})


#: process-wide monitor — the executor's collective path feeds it
MONITOR = CommsMonitor()


def bound_byte_cells(plan: CommsPlan):
    """Resolve the (cell, payload) byte-counter pairs ONCE per compiled
    block, so the per-dispatch synchronous accounting is a lock+add per
    collective with no label resolution on the hot path."""
    return [(COLLECTIVE_BYTES_CTR.labels(op=c.op, signature=c.signature),
             c.payload_bytes) for c in plan.collectives]
