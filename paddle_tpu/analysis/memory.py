"""Static HBM peak-memory planner: interval liveness over the
dependency-ordered ``framework.ir`` Graph.

MLSys compilers derive memory plans from liveness over the dependency
graph (TVM's static memory planning pass, arxiv 1802.04799) and
per-primitive footprint contracts (TPP, arxiv 2104.05755); the reference
repo's ``contrib/memory_usage_calc.py`` only sums per-var bytes with a
batch multiplier.  This planner models what the executor's lowered step
actually keeps live:

- **persistables** (params, optimizer state, BN stats) are resident for
  the whole step; read-write persistables count ONCE — the executor
  donates their buffers, so the updated value aliases the input
  (``donate_argnums``), not a second allocation;
- **feeds** (data vars) are resident from step start to step end: the
  caller stages them on device and holds the reference across the
  dispatch;
- **fetches** pin their buffer from the producing op to end-of-step (a
  lazy ``FetchHandle`` holds it past the step); a fetched rw persistable
  additionally costs one defensive copy (the executor's
  donation-aliasing copy);
- **temporaries** live from their producing op to their last consumer in
  dependency order; inplace-pair outputs (``buffer_shared_inplace_pass``)
  alias their input's buffer and cost nothing while extending it;
- **sub-blocks** (while/cond bodies) add their own local-temporary peak
  while the enclosing op runs (carried vars live in the parent and are
  already counted there).

Symbolic (-1/None) dims resolve through ``batch_size`` (default 1 — the
verifier's conservative per-example estimate; ``bench.py`` passes the
real batch for its estimate-vs-measured lines).  Results are cached on
the program fingerprint, the same key as the verifier, so steady-state
dispatch never re-plans.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import monitor as _monitor
from ..framework.core import Block, Program

__all__ = ["MemoryPlan", "clear_cache", "plan_memory",
           "plan_sharded_memory"]

#: dtype -> bytes per element (numpy lacks bfloat16)
_ITEMSIZE = {"bfloat16": 2, "float16": 2, "bool": 1}

_PEAK_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_static_hbm_peak_bytes",
    "static memory planner: estimated peak HBM bytes of the most "
    "recently planned program")
_PLAN_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_memory_plans_total",
    "plan_memory calls by fingerprint-cache outcome", ("cache",))
_PLAN_HIT = _PLAN_CTR.labels(cache="hit")
_PLAN_MISS = _PLAN_CTR.labels(cache="miss")


def _itemsize(dtype) -> int:
    d = str(dtype or "float32")
    if d in _ITEMSIZE:
        return _ITEMSIZE[d]
    try:
        return int(np.dtype(d).itemsize)
    except TypeError:
        return 4


def _var_bytes(var, batch_size: int) -> int:
    """Static byte size of one var; symbolic dims (-1/None) resolve to
    ``batch_size``.  Shapeless vars count 0 (scalars count their dtype
    width via the empty product)."""
    if var is None or var.shape is None:
        return 0
    n = 1
    for d in var.shape:
        n *= batch_size if d in (-1, None) else int(d)
    return max(n, 1) * _itemsize(var.dtype)


def _fmt(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.2f} {unit}"
        n /= 1024
    return f"{n:.2f} TiB"


@dataclass
class MemoryPlan:
    """Static per-step HBM model of one program."""

    #: estimated peak bytes across the dependency-ordered step,
    #: including transient temporaries
    peak_bytes: int = 0
    #: dependency-order position of the peak (len(ops) = end of step)
    peak_pos: int = 0
    #: op type at the peak position ("<end-of-step>" past the last op)
    peak_op: str = "<end-of-step>"
    #: bytes resident across the WHOLE step: persistables (rw counted
    #: once — donated) + staged feeds
    resident_bytes: int = 0
    #: bytes still live at the step boundary: resident + fetch buffers
    #: (+ donation-aliasing fetch copies) — what ``memory.live_bytes``
    #: measures between steps
    steady_bytes: int = 0
    #: per-op live-byte footprint in dependency order:
    #: (pos, op_type, live_bytes_while_running, transient_bytes)
    per_op: List[tuple] = field(default_factory=list)
    #: name -> (def_pos, last_use_pos, bytes) for every counted interval
    intervals: Dict[str, tuple] = field(default_factory=dict)
    #: vars live at the peak, largest first: (name, bytes, kind)
    peak_live: List[tuple] = field(default_factory=list)
    batch_size: int = 1

    def top_ops(self, k: int = 10) -> List[tuple]:
        """The k ops with the largest live-byte footprint while running."""
        return sorted(self.per_op, key=lambda r: -r[2])[:k]

    def attribution(self, k: int = 10):
        """Top-K per-op attribution as verifier ``Diagnostic`` records —
        renderable by ``debugger.format_diagnostics`` (one ``[info]
        hbm_peak`` row per op, largest live footprint first)."""
        from .verifier import Diagnostic
        rows = []
        for pos, op_type, live, transient in self.top_ops(k):
            extra = (f" (+{_fmt(transient)} transient)"
                     if transient else "")
            rows.append(Diagnostic(
                "hbm_peak", "info",
                f"{_fmt(live)} live while this op runs{extra}",
                op_type=op_type, op_index=pos))
        return rows

    def report(self, k: int = 10) -> str:
        """Human-readable plan: headline peak + top-K attribution table
        rendered through ``debugger.format_diagnostics``."""
        from .. import debugger
        head = (f"static HBM plan (batch={self.batch_size}): peak "
                f"{_fmt(self.peak_bytes)} at op #{self.peak_pos} "
                f"({self.peak_op}); resident {_fmt(self.resident_bytes)}"
                f"; steady {_fmt(self.steady_bytes)}")
        lines = [head]
        top = [(n, b, kind) for n, b, kind in self.peak_live[:k]]
        if top:
            lines.append("live at peak: " + ", ".join(
                f"{n} {_fmt(b)} [{kind}]" for n, b, kind in top))
        lines.append(debugger.format_diagnostics(self.attribution(k)))
        return "\n".join(lines)


# (program fingerprint, fetch tuple, batch) -> MemoryPlan; bounded FIFO,
# guarded — same rationale as the verifier cache
_CACHE: Dict[tuple, MemoryPlan] = {}  # guarded-by: _CACHE_LOCK
_CACHE_CAP = 128
_CACHE_LOCK = threading.Lock()


def clear_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()


def _subblock_local_peak(program: Program, block: Block,
                         batch_size: int) -> int:
    """Transient footprint of one while/cond body: the sum-free interval
    peak over its LOCAL vars only (names declared in the sub-block —
    carried/captured vars resolve to the parent and are counted there).
    Nested bodies add their own local peak at their enclosing op."""
    from ..framework.core import Block as _Block
    local = set(block.vars)
    last_use: Dict[str, int] = {}
    def_pos: Dict[str, int] = {}
    nested: Dict[int, int] = {}
    for i, op in enumerate(block.ops):
        for n in op.input_arg_names():
            if n in local:
                last_use[n] = i
        for n in op.output_arg_names():
            if n in local:
                def_pos.setdefault(n, i)
                last_use[n] = max(last_use.get(n, i), i)
        for v in op.attrs.values():
            if isinstance(v, _Block):
                nested[i] = nested.get(i, 0) + _subblock_local_peak(
                    program, v, batch_size)
    # difference-array sweep (same linear form as _plan's main sweep)
    n_ops = len(block.ops)
    delta = [0] * (n_ops + 2)
    for n in local:
        last = last_use.get(n, -1)
        if last < 0:
            continue
        d = min(def_pos.get(n, 0), last)
        delta[d] += _var_bytes(block.vars.get(n), batch_size)
        delta[last + 1] -= _var_bytes(block.vars.get(n), batch_size)
    peak = running = 0
    for i in range(n_ops):
        running += delta[i]
        peak = max(peak, running + nested.get(i, 0))
    return peak


def plan_memory(program: Program, fetch_names=(),
                batch_size: int = 1) -> MemoryPlan:
    """Interval-liveness HBM plan for one program (see module docstring).
    Cached on (program fingerprint, fetch tuple, batch_size)."""
    fetch_names = tuple(
        f.name if hasattr(f, "name") else f for f in (fetch_names or ()))
    key = (program.fingerprint(), fetch_names, int(batch_size))
    with _CACHE_LOCK:
        cached = _CACHE.get(key)
    if cached is not None:
        _PLAN_HIT.inc()
        return cached
    _PLAN_MISS.inc()
    with _monitor.TRACER.span("memory.plan", "compile",
                              fetches=len(fetch_names)):
        plan = _plan(program, fetch_names, int(batch_size))
    _PEAK_GAUGE.set(float(plan.peak_bytes))
    with _CACHE_LOCK:
        if key not in _CACHE:
            if len(_CACHE) >= _CACHE_CAP:
                _CACHE.pop(next(iter(_CACHE)))
            _CACHE[key] = plan
        plan = _CACHE[key]
    return plan


def plan_sharded_memory(program: Program, fetch_names=(),
                        batch_size: int = 1, specs=None,
                        axis_sizes=None) -> MemoryPlan:
    """PER-SHARD variant of :func:`plan_memory` for the GSPMD rule-table
    planner (``parallel.partitioner.choose_rules``): every var named in
    ``specs`` ({name -> dist_spec tuple}) is charged its per-device
    slice — bytes divided by the product of the mesh axis sizes
    (``axis_sizes``) appearing in its spec — instead of its global size.
    Unlisted vars are replicated and cost full bytes on every shard.
    Cached alongside the unsharded plans, with the sharding layout
    folded into the key."""
    fetch_names = tuple(
        f.name if hasattr(f, "name") else f for f in (fetch_names or ()))
    axis_sizes = dict(axis_sizes or {})
    shard_div: Dict[str, int] = {}
    for name, spec in (specs or {}).items():
        d = 1
        for ax in (spec or ()):
            for a in (ax if isinstance(ax, (tuple, list)) else (ax,)):
                d *= max(int(axis_sizes.get(a, 1) or 1), 1)
        if d > 1:
            shard_div[name] = d
    key = (program.fingerprint(), fetch_names, int(batch_size),
           tuple(sorted(shard_div.items())))
    with _CACHE_LOCK:
        cached = _CACHE.get(key)
    if cached is not None:
        _PLAN_HIT.inc()
        return cached
    _PLAN_MISS.inc()
    with _monitor.TRACER.span("memory.plan_sharded", "compile",
                              fetches=len(fetch_names),
                              sharded=len(shard_div)):
        plan = _plan(program, fetch_names, int(batch_size),
                     shard_div=shard_div)
    with _CACHE_LOCK:
        if key not in _CACHE:
            if len(_CACHE) >= _CACHE_CAP:
                _CACHE.pop(next(iter(_CACHE)))
            _CACHE[key] = plan
        plan = _CACHE[key]
    return plan


def _plan(program: Program, fetch_names: tuple,
          batch_size: int, shard_div=None) -> MemoryPlan:
    from ..framework import ir
    from ..framework.core import Block as _Block
    block = program.global_block()
    graph = ir.Graph(program)
    order = graph.topology_sort()
    pos = {n.id: i for i, n in enumerate(order)}
    n_ops = len(order)
    end = n_ops                      # end-of-step boundary position

    shard_div = shard_div or {}

    def vb(v, name=None):
        """_var_bytes, divided down to the per-shard slice when the
        caller supplied a sharding layout for this var (ceil — GSPMD
        pads the ragged shard)."""
        b = _var_bytes(v, batch_size)
        d = shard_div.get(name, 1) if name else 1
        return -(-b // d) if d > 1 else b

    fetched = set(fetch_names)
    # rw persistables: donated, so old+new share ONE buffer all step
    written = set()
    for b in program.blocks:
        for op in b.ops:
            written.update(n for n in op.output_arg_names() if n)
    resident = 0
    resident_names = []
    seen = set()
    for b in program.blocks:
        for op in b.ops:
            for name in op.input_arg_names() + op.output_arg_names():
                if not name or name in seen or not block.has_var(name):
                    continue
                seen.add(name)
                v = block.var(name)
                if v.persistable:
                    resident += vb(v, name)
                    resident_names.append((name, vb(v, name), "persist"))
                elif getattr(v, "is_data", False):
                    resident += vb(v, name)
                    resident_names.append((name, vb(v, name), "feed"))

    # inplace aliases: the pair's output shares the input buffer — count
    # the output's bytes zero and stretch the input's interval instead
    ali_graph = ir.get_pass("buffer_shared_inplace_pass").apply(graph)
    alias_of = {out: src
                for src, out in ali_graph.attrs.get("inplace_pairs", [])}

    def resolve_alias(name, depth=8):
        while name in alias_of and depth > 0:
            name = alias_of[name]
            depth -= 1
        return name

    # temporary intervals over the SSA var nodes (one node per write)
    intervals: Dict[str, List] = {}   # name -> [def, last, bytes, kind]
    sub_extra: Dict[int, int] = {}    # op pos -> sub-block local peak
    for node in order:
        i = pos[node.id]
        for attr in node.op.attrs.values():
            if isinstance(attr, _Block):
                sub_extra[i] = sub_extra.get(i, 0) + _subblock_local_peak(
                    program, attr, batch_size)
    for vnode in graph.all_var_nodes():
        name = vnode.name
        if not name or not block.has_var(name):
            continue
        v = block.var(name)
        if v.persistable or getattr(v, "is_data", False):
            continue                  # counted resident above
        producers = [pos[p.id] for p in vnode.inputs if p.id in pos]
        consumers = [pos[c.id] for c in vnode.outputs if c.id in pos]
        if not producers and not consumers:
            continue
        d = min(producers) if producers else 0
        last = max(consumers) if consumers else d
        if name in fetched:
            last = end               # a fetch pins its buffer past the step
        root = resolve_alias(name)
        entry = intervals.get(name)
        if root != name:
            # the inplace output shares the root's buffer: stretch the
            # root's interval over this reuse instead of counting a
            # second allocation.  A resident root (feed/persistable) is
            # already charged for the whole step — nothing to stretch.
            rv = block.vars.get(root) or (
                block.var(root) if block.has_var(root) else None)
            if rv is not None and (rv.persistable or
                                   getattr(rv, "is_data", False)):
                continue
            rentry = intervals.get(root)
            if rentry is not None:
                rentry[1] = max(rentry[1], last)
            else:
                intervals[root] = [d, last, vb(rv, root)
                                   if rv is not None else 0, "temp"]
            continue
        if entry is not None:
            entry[0] = min(entry[0], d)
            entry[1] = max(entry[1], last)
        else:
            intervals[name] = [d, last, vb(v, name), "temp"]

    # fetched rw persistables cost one defensive copy (executor's
    # donation-aliasing jnp.copy), live from step end onward
    copy_bytes = sum(
        vb(block.var(n), n) for n in fetched
        if block.has_var(n) and block.var(n).persistable and n in written)

    # difference-array sweep: O(ops + vars), not O(ops * vars) — this
    # runs inside every fresh verify, so a BERT-sized program must not
    # pay a quadratic Python loop
    delta = [0] * (n_ops + 2)
    for e in intervals.values():
        delta[e[0]] += e[2]
        delta[min(e[1], end) + 1] -= e[2]
    per_op: List[tuple] = []
    peak, peak_pos = resident, end
    running = resident
    for i in range(n_ops + 1):
        running += delta[i]
        transient = sub_extra.get(i, 0)
        total = running + transient + (copy_bytes if i == end else 0)
        if i < n_ops:
            per_op.append((i, order[i].name, total, transient))
        if total >= peak:
            peak, peak_pos = total, i
    steady = resident + copy_bytes + sum(
        e[2] for e in intervals.values() if e[1] >= end)

    plan = MemoryPlan(
        peak_bytes=int(peak), peak_pos=int(peak_pos),
        peak_op=(order[peak_pos].name if peak_pos < n_ops
                 else "<end-of-step>"),
        resident_bytes=int(resident), steady_bytes=int(steady),
        per_op=per_op,
        intervals={n: (e[0], e[1], e[2]) for n, e in intervals.items()},
        batch_size=batch_size)
    live_at_peak = [(n, e[2], "temp") for n, e in intervals.items()
                    if e[0] <= peak_pos <= e[1] and e[2]]
    plan.peak_live = sorted(resident_names + live_at_peak,
                            key=lambda r: -r[1])
    return plan
