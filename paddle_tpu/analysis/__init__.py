"""Static program analysis (no direct reference counterpart — the
reference validates ProgramDesc graphs ad hoc at kernel launch; here the
whole class of launch-time defects is caught at ``compiler.optimize``
time, before anything is lowered).

- :mod:`paddle_tpu.analysis.verifier` — the program verifier: def-before-
  use, dangling feed/fetch targets, shape/dtype re-inference consistency,
  dead-op liveness, use-after-donate hazards on rw persistables, static
  int64 feed-wrap classification, and the per-rank collective-ordering
  fingerprint.  Whole-program: ``while``/``cond`` sub-blocks verify
  recursively in their enclosing scope context, and loop-body
  collectives fold into the fingerprint stamped with their block path.
  Runs on the ``framework.ir`` Graph, behind ``FLAGS_program_verify``
  (default on), cached on the source-program fingerprint so steady-state
  dispatch never re-verifies.
- :mod:`paddle_tpu.analysis.memory` — the static HBM peak-memory
  planner: interval liveness over the dependency-ordered Graph,
  donation- and alias-aware, producing per-program estimated peak bytes
  with a top-K per-op attribution table.  Feeds the verifier's
  ``memory_budget`` check, ``bench.py``'s ``memory:<workload>``
  estimate-vs-measured lines, and ``tools/analyze.py``.
- :mod:`paddle_tpu.analysis.cost` — the analytic per-op flops/bytes
  model: 2·MAC matmul/conv formulas, grad-op inheritance, per-op-class
  roofline shares, cached on the program fingerprint.  Feeds the
  executor's live ``paddle_tpu_step_mfu`` gauge, ``bench.py``'s
  ``mfu:<workload>`` runtime-vs-offline cross-check, the
  ``FLAGS_cost_crosscheck`` parity gate against XLA's own
  ``compiled.cost_analysis()``, and the fusion pass's candidate
  ranking.
- :mod:`paddle_tpu.analysis.numerics` — the numerics observability
  plane (``FLAGS_numerics``): in-graph tensor-health statistics packed
  into one per-step output (NaN/Inf sentinels, grad norms, update
  ratios, dynamic-range histograms), the anomaly engine (spike
  detection, profiler auto-capture, checkpoint quarantine), and the
  ``gnorm``/``nanf`` gang-digest keys — the value-domain counterpart of
  the cost/attribution plane.
- :mod:`paddle_tpu.analysis.device_profile` — MEASURED device-time
  attribution from the sampling profiler's captured windows: a
  chrome-trace + xplane.pb (dependency-free wire-format) parser joined
  to framework steps by the ``paddle_tpu.step`` ids, HLO/fusion kernel
  names mapped back to the cost-model op classes, per-step device time
  / idle fraction / per-class shares, measured MFU
  (``paddle_tpu_step_mfu_measured``, the ``mfu_m`` gang-digest key),
  and the measured-vs-analytic divergence table persisted as
  ``<window>/summary.json`` — the autotune search's objective oracle.
  NOT imported eagerly here: it is the profiler's lazy post-close
  dependency.
- :mod:`paddle_tpu.analysis.fusion` — the cost-guided training-safe
  graph fusion pass (``FLAGS_graph_fusion``): PDPattern-matched
  candidates (conv+bn+relu, dense epilogues, embedding+layernorm),
  static legality analysis with grad-chain rewrite-or-reject, roofline
  ranking, and the ``FLAGS_fusion_autotune`` measured fallback; runs in
  ``compiler.optimize``'s pass slot with the verifier before and after.
"""

from .comms import (  # noqa: F401
    CommsPlan, device_link_bandwidth, plan_comms,
)
from .cost import CostPlan, device_peak_flops, plan_cost  # noqa: F401
from .fusion import (  # noqa: F401
    FusionDecision, FusionReport, analyze_program, fuse_program,
)
from .memory import MemoryPlan, plan_memory  # noqa: F401
from .numerics import (  # noqa: F401
    NumericsEngine, NumericsFrame, StatsLayout, loss_fingerprint,
    plan_numerics, record_anomaly,
)
from .verifier import (  # noqa: F401
    CHECKS, Diagnostic, ProgramVerificationError, VerifyResult,
    clear_cache, collective_fingerprint, dynamic_int64_feeds,
    verify_or_raise, verify_program,
)

__all__ = [
    "CHECKS", "CommsPlan", "CostPlan", "Diagnostic", "FusionDecision",
    "FusionReport", "MemoryPlan", "NumericsEngine", "NumericsFrame",
    "ProgramVerificationError", "StatsLayout", "VerifyResult",
    "analyze_program", "clear_cache", "collective_fingerprint",
    "device_link_bandwidth", "device_peak_flops", "dynamic_int64_feeds",
    "fuse_program", "loss_fingerprint", "plan_comms", "plan_cost",
    "plan_memory", "plan_numerics", "record_anomaly", "verify_or_raise",
    "verify_program",
]
