"""Program verifier: static checks over the ``framework.ir`` Graph.

The reference validates ProgramDesc graphs ad hoc at kernel launch
(``framework/operator.cc`` enforce macros firing mid-run); this verifier
moves that whole defect class to ``compiler.optimize`` time, where a bad
program costs one diagnostic instead of a dispatch-time crash — or, for
the cross-rank ordering defects, a silent multi-process hang.

Checks (one ``verifier.*`` counter series per check in the telemetry
registry; see README "Static analysis" for the table):

==================  =========  ==============================================
check               severity   flags
==================  =========  ==============================================
def_before_use      error      op input var not declared anywhere in the
                               block (would KeyError mid-trace)
uninitialized_read  warning    declared non-persistable, non-data var read
                               before any op writes it (must be fed or
                               pre-seeded in the scope at run time)
dangling_fetch      error      fetch target never produced: not a block
                               var, or declared but neither written nor
                               persistable
dangling_feed       warning    declared data var consumed by no op in any
                               block (its fed value is dropped)
shape_consistency   warning    a var's recorded shape/dtype disagrees with
                               re-running build-time inference over the
                               block (a mutation bypassed ``append_op``)
dead_op             warning    op unreachable from the fetch + persistable
                               + side-effect liveness roots (the
                               ``dead_op_eliminate`` pass removes these)
use_after_donate    warning    fetch target is a read-write persistable:
                               the executor donates rw buffers to the next
                               step and must defensively copy the fetch out
                               of the donated buffer every step
int64_feed          (none)     classification, not a diagnostic: its
                               counter tracks feeds that KEPT the runtime
                               wrap check (verifier-dynamic)
collective_order    error/     collective ops not totally ordered by data
                    warning    dependencies: error when an unordered pair
                               has the SAME signature (cross-rank pairing
                               is ambiguous — the documented ``.numpy()``
                               ordering deadlock class), warning otherwise
memory_budget       warning    the static HBM peak-memory estimate
                               (analysis.memory, batch=1 lower bound)
                               exceeds FLAGS_memory_budget_mb
==================  =========  ==============================================

The graph-walking checks are INTERPROCEDURAL: ``while``/``cond`` bodies
verify recursively in their enclosing scope context (outer defs visible,
inner defs scoped, loop-carried body writes never read as
uninitialized), sub-block collectives fold into the fingerprint stamped
with their block path, and dead body compute is flagged/pruned without
touching live loop-carried vars.

``verify_program`` is cached on the source-program fingerprint
(``Program.fingerprint()`` — the PR-4 dispatch-plan key), so a program is
verified once per mutation and steady-state dispatch never re-enters the
verifier.  Results additionally stamp ``program._attrs["verify"]`` (which
rides ``Program.clone``) with the machine-readable artifacts other layers
consume: the int64 feed classification (the executor keeps its runtime
feed-wrap check only for feeds marked dynamic) and the collective
fingerprint (ranks can compare it out of band before entering a gang).
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Tuple

from .. import monitor as _monitor
from ..framework.core import Block, Program

__all__ = [
    "CHECKS", "Diagnostic", "ProgramVerificationError", "VerifyResult",
    "clear_cache", "collective_fingerprint", "dynamic_int64_feeds",
    "verify_or_raise", "verify_program",
]

#: every check name, in report order (one counter series per entry)
CHECKS = (
    "def_before_use", "uninitialized_read", "dangling_fetch",
    "dangling_feed", "shape_consistency", "dead_op", "use_after_donate",
    "int64_feed", "collective_order", "memory_budget",
    "spec_conflict", "shard_divisibility", "mesh_axis_overuse",
)

_FINDINGS = _monitor.REGISTRY.counter(
    "paddle_tpu_verifier_findings_total",
    "program-verifier findings by check", ("check",))
#: bound once per check: a verify pass bumps these, never resolves labels
_FINDING_CELLS = {c: _FINDINGS.labels(check=c) for c in CHECKS}
_RUNS = _monitor.REGISTRY.counter(
    "paddle_tpu_verifier_runs_total",
    "verify_program calls by fingerprint-cache outcome", ("cache",))
_RUNS_HIT = _RUNS.labels(cache="hit")
_RUNS_MISS = _RUNS.labels(cache="miss")

#: int64 feeds whose every consumer bounds VALID values below this are
#: static-safe: with the bound under 2**31, every valid index fits int32,
#: so the int64->int32 feed conversion can only alter values that were
#: already out of range — and those the consumer already mishandles
#: identically with or without the wrap (XLA gather clamps out-of-bounds
#: ids silently; the runtime wrap check never diagnosed table-bounds
#: violations inside the int32 range either).  The wrap check therefore
#: adds no protection for these feeds that the bound itself doesn't.
_INT32_BOUND = 2 ** 31

#: collective ops whose cross-rank launch order must match on every rank
#: (init/sync shims are host no-ops and carry no ordering constraint)
_COLLECTIVE_OPS = frozenset({
    "c_allreduce_sum", "c_allreduce_max", "c_allreduce_min",
    "c_allreduce_prod", "c_broadcast", "c_allgather", "c_reducescatter",
    "c_split",
})


class ProgramVerificationError(RuntimeError):
    """Raised by :func:`verify_or_raise` when any error-severity
    diagnostic is present.  ``.result`` carries the full
    :class:`VerifyResult`."""

    def __init__(self, msg: str, result: "VerifyResult"):
        super().__init__(msg)
        self.result = result


@dataclass(frozen=True)
class Diagnostic:
    """One structured finding: which check, how bad, where, and what to do
    about it (ref platform/enforce.h — the reference enriches launch-time
    errors with op context; here the context is attached pre-launch)."""

    check: str                 # one of CHECKS
    severity: str              # "error" | "warning"
    message: str
    op_type: Optional[str] = None
    op_index: Optional[int] = None   # program-order index in its block
    var: Optional[str] = None
    fix_hint: Optional[str] = None
    #: block path for sub-block findings ("0" is the top block; a loop
    #: body reads e.g. "0/while@5/1": the while op at block-0 index 5,
    #: sub-block 1).  None means block 0 (back-compat).
    block: Optional[str] = None


@dataclass
class VerifyResult:
    diagnostics: List[Diagnostic] = field(default_factory=list)
    #: int64/uint64 data feeds that still need the runtime wrap check
    int64_dynamic: FrozenSet[str] = frozenset()
    #: int64/uint64 data feeds proven bounded by every consumer
    int64_static: FrozenSet[str] = frozenset()
    #: sha1 over the dependency-ordered, block-path-stamped collective
    #: sequence + fetch list (None when no block launches a collective)
    collective_fingerprint: Optional[str] = None
    dead_ops: Tuple[int, ...] = ()   # block-0 indices of dead ops
    #: {sub-block idx: (op indices...)} of dead body compute
    dead_subblock_ops: Dict[int, tuple] = field(default_factory=dict)
    #: static HBM plan (analysis.memory.MemoryPlan; None if planning
    #: failed — the plan must never block verification)
    memory_plan: Optional[object] = None
    #: analytic flops/bytes plan (analysis.cost.CostPlan; None if
    #: planning failed — same never-blocks contract as the memory plan)
    cost_plan: Optional[object] = None
    #: static comms plan (analysis.comms.CommsPlan; None for programs
    #: that launch no collectives or when planning failed).  Its
    #: fingerprint folds into ``collective_fingerprint``, so ranks whose
    #: COMMS PLANS diverge (payload bytes, nranks) refuse at the gang
    #: barrier exactly like divergent collective sequences.
    comms_plan: Optional[object] = None
    #: static GSPMD sharding plan (analysis.sharding.ShardingPlan; None
    #: for unpartitioned programs or when planning failed).  UNLIKE the
    #: planners above this one contributes blocking diagnostics
    #: (spec_conflict / mesh_axis_overuse errors refuse a bad rule table
    #: at optimize time with zero dispatches), and its ``#resh=`` token
    #: folds into ``collective_fingerprint`` so divergent reshard plans
    #: refuse at the step barrier even under IDENTICAL rule-table names.
    sharding_plan: Optional[object] = None

    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors()

    def by_check(self, check: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.check == check]


# ---------------------------------------------------------------------------
# fingerprint cache
# ---------------------------------------------------------------------------

#: (program fingerprint, fetch TUPLE) -> VerifyResult.  The fetch list is
#: keyed in ORDER, not as a set: the collective fingerprint hashes the
#: materialization order, so a reordered fetch list is a different verify.
#: Bounded FIFO: every program MUTATION mints a new fingerprint, so an
#: unbounded dict would grow per version in a build-mutate-verify loop.
#: Guarded: concurrent first compiles of different programs verify in
#: parallel, and an unguarded evict could pop a key another thread just
#: took from next(iter(...)).
_CACHE: Dict[tuple, VerifyResult] = {}  # guarded-by: _CACHE_LOCK
_CACHE_CAP = 256
_CACHE_LOCK = threading.Lock()


def clear_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()


# ---------------------------------------------------------------------------
# individual checks (each takes the block-0 graph + context, appends diags)
# ---------------------------------------------------------------------------

def _is_data(v) -> bool:
    return bool(getattr(v, "is_data", False))


def sub_blocks_of(op) -> List[Tuple[str, Block]]:
    """The Block-valued attrs of one op, sorted by attr name (while/cond
    bodies and any future multi-block control flow)."""
    return sorted(((k, v) for k, v in op.attrs.items()
                   if isinstance(v, Block)), key=lambda kv: kv[0])


def _check_def_before_use(program: Program, diags: List[Diagnostic]):
    """Interprocedural program-order def-before-use: block 0 first, then
    every ``while``/``cond`` sub-block recursively IN ITS ENCLOSING SCOPE
    CONTEXT — outer defs written before the control-flow op are visible
    inside the body, inner defs stay scoped to it.  Feed/fetch shim ops
    participate as writers only (the executor skips them at trace time).

    Loop-body semantics: a body read of a var some body op writes LATER
    is a loop-carried use (iteration *n* reads iteration *n-1*'s write,
    and the carry's initial value comes from the parent scope), so only
    block-0 order violations earn ``uninitialized_read`` — sub-blocks
    suppress it for names written anywhere in the same body."""

    def walk(block: Block, written: set, path: str):
        local = set(written)
        body_writes = {n for op in block.ops
                       for n in op.output_arg_names() if n}
        for idx, op in enumerate(block.ops):
            if op.type not in ("feed", "fetch"):
                for slot, names in op.inputs.items():
                    # OG$ (output-grad) slots may legally be absent: an
                    # output unused downstream has no grad, and the
                    # lowering reads them with .get(), treating None as
                    # zero
                    if slot.startswith("OG$"):
                        continue
                    for name in names:
                        if not name or name in local:
                            continue
                        if not block.has_var(name):
                            diags.append(Diagnostic(
                                "def_before_use", "error",
                                f"op input var {name!r} is not declared "
                                "in the block (or an enclosing block) "
                                "and no preceding op produces it",
                                op_type=op.type, op_index=idx, var=name,
                                block=path,
                                fix_hint="declare the var "
                                         "(block.create_var / "
                                         "layers.data) or fix the "
                                         "producing op's output name"))
                            continue
                        v = block.var(name)
                        if v.persistable or _is_data(v) or \
                                v.initializer is not None:
                            continue
                        if block.idx != 0 and name in body_writes:
                            continue       # loop-carried body write
                        diags.append(Diagnostic(
                            "uninitialized_read", "warning",
                            f"var {name!r} is read before any op writes "
                            "it and is neither persistable nor a "
                            "declared data var — it must be fed (or "
                            "pre-seeded in the scope) at every run",
                            op_type=op.type, op_index=idx, var=name,
                            block=path,
                            fix_hint="declare it via layers.data if it "
                                     "is fed, or mark it persistable if "
                                     "it lives in the scope"))
            # recurse into sub-block bodies with the defs visible HERE
            # (outer writes up to and including earlier ops); the body's
            # own writes never leak back out — the enclosing op's
            # declared outputs carry them
            for _, sub in sub_blocks_of(op):
                walk(sub, local, f"{path}/{op.type}@{idx}/{sub.idx}")
            for name in op.output_arg_names():
                if name:
                    local.add(name)

    walk(program.global_block(), set(), "0")


def _check_feed_fetch(program: Program, fetch_names, diags):
    block = program.global_block()
    written = {n for op in block.ops
               for n in op.output_arg_names() if n}
    for name in fetch_names:
        if name in written:
            continue
        if not block.has_var(name):
            diags.append(Diagnostic(
                "dangling_fetch", "error",
                f"fetch target {name!r} is not a var of the program",
                var=name,
                fix_hint="fetch an existing var (typo?) or rebuild the "
                         "program that defines it"))
        elif not block.var(name).persistable and \
                not _is_data(block.var(name)):
            # data vars are legal passthrough fetches: the lowered step
            # materializes fetches from the value environment, which
            # includes the feeds (dangling_feed below blesses exactly
            # this echo/debug pattern)
            diags.append(Diagnostic(
                "dangling_fetch", "error",
                f"fetch target {name!r} is declared but no op produces it "
                "and it is not persistable — materialization would fail "
                "at dispatch",
                var=name,
                fix_hint="fetch the op output you meant, or mark the var "
                         "persistable if its value lives in the scope"))
    consumed = {n for b in program.blocks for op in b.ops
                for n in op.input_arg_names() if n}
    for name, v in block.vars.items():
        if _is_data(v) and name not in consumed and name not in fetch_names:
            diags.append(Diagnostic(
                "dangling_feed", "warning",
                f"data var {name!r} is consumed by no op in any block — "
                "its fed value is dropped every step",
                var=name,
                fix_hint="remove the layers.data declaration (and the "
                         "feed) or wire it into the model"))


def _check_shape_consistency(program: Program, diags):
    """Re-run build-time inference over a clone of block 0 and diff the
    recorded Variable shape/dtype metadata.  Catches mutations that
    bypassed ``append_op`` (whose inline InferShape keeps metadata live —
    the invariant ``tests/test_shape_inference.py`` pins).  Only concrete
    dims are compared: -1/None stay symbolic on both sides."""
    from ..framework import registry
    try:
        clone = program.clone()
    except Exception:
        return
    src = program.global_block()
    blk = clone.global_block()
    for idx, op in enumerate(blk.ops):
        if op.type in ("feed", "fetch"):
            continue
        try:
            registry.infer_op(op, blk)
        except Exception:
            continue             # not re-inferable out of build context
        for name in op.output_arg_names():
            if not name or name not in blk.vars or name not in src.vars:
                continue
            iv, sv = blk.vars[name], src.vars[name]
            ishape, sshape = iv.shape, sv.shape
            if ishape is not None and sshape is not None:
                if len(ishape) != len(sshape) or any(
                        a != b for a, b in zip(ishape, sshape)
                        if a not in (-1, None) and b not in (-1, None)):
                    diags.append(Diagnostic(
                        "shape_consistency", "warning",
                        f"var {name!r} records shape {list(sshape)} but "
                        f"inference over op {op.type!r} derives "
                        f"{list(ishape)}",
                        op_type=op.type, op_index=idx, var=name,
                        fix_hint="the shape was mutated after build; "
                                 "rebuild the op (append_op re-infers) "
                                 "instead of patching Variable.shape"))
            if iv.dtype and sv.dtype and iv.dtype != sv.dtype:
                diags.append(Diagnostic(
                    "shape_consistency", "warning",
                    f"var {name!r} records dtype {sv.dtype!r} but "
                    f"inference over op {op.type!r} derives {iv.dtype!r}",
                    op_type=op.type, op_index=idx, var=name,
                    fix_hint="rebuild the op instead of patching "
                             "Variable.dtype"))


def _check_dead_ops(graph, fetch_names, diags):
    from ..framework import ir
    dead = ir.dead_op_analysis(graph, protected=frozenset(fetch_names))
    dead_ids = {n.id for n in dead}
    indices = tuple(i for i, n in enumerate(graph.op_nodes)
                    if n.id in dead_ids)
    for i in indices:
        op = graph.op_nodes[i]
        # auto-generated backward leftovers (grads of non-parameter
        # inputs append_backward materializes and nothing consumes) are
        # framework-made, not a user defect: the dead_op_eliminate pass
        # still removes them, but only user-authored dead FORWARD compute
        # earns a diagnostic
        if op.name.endswith("_grad") or \
                op.op.attrs.get("op_role") == "backward":
            continue
        diags.append(Diagnostic(
            "dead_op", "warning",
            f"op {op.name!r} reaches no fetch target, persistable write, "
            "or side-effecting op — its outputs are computed and dropped",
            op_type=op.name, op_index=i,
            fix_hint="fetch its output if you need it; the "
                     "dead_op_eliminate pass removes it otherwise"))
    # sub-block bodies: dead body compute re-runs EVERY iteration — the
    # liveness keeps carried vars (their writers root through the
    # enclosing op's var lists) and flags only compute no carry, fetch,
    # or persistable observes
    sub_dead = ir.dead_subblock_op_analysis(
        graph.program, protected=frozenset(fetch_names))
    for blk_idx, sub_indices in sub_dead.items():
        block = graph.program.blocks[blk_idx]
        for i in sub_indices:
            op = block.ops[i]
            if op.type.endswith("_grad") or \
                    op.attrs.get("op_role") == "backward":
                continue
            diags.append(Diagnostic(
                "dead_op", "warning",
                f"op {op.type!r} inside sub-block {blk_idx} reaches no "
                "loop-carried var, fetch target, persistable write, or "
                "side-effecting op — it recomputes a dropped value EVERY "
                "iteration",
                op_type=op.type, op_index=i, block=str(blk_idx),
                fix_hint="carry or fetch its output if you need it; the "
                         "dead_op_eliminate pass prunes it otherwise"))
    return indices, sub_dead


def _rw_persistables(program: Program) -> set:
    block = program.global_block()
    written = set()
    for b in program.blocks:
        for op in b.ops:
            written.update(n for n in op.output_arg_names() if n)
    return {n for n in written
            if block.has_var(n) and block.var(n).persistable}


def _check_use_after_donate(program: Program, fetch_names, diags):
    rw = _rw_persistables(program)
    for name in fetch_names:
        if name in rw:
            diags.append(Diagnostic(
                "use_after_donate", "warning",
                f"fetch target {name!r} is a read-write persistable: the "
                "executor donates rw buffers to the next step, so every "
                "step must defensively copy this fetch out of the donated "
                "buffer",
                var=name,
                fix_hint="fetch a non-persistable snapshot (e.g. "
                         "layers.assign the value) or read it from the "
                         "scope at a step boundary instead"))


#: value-preserving ops the int64 classification propagates THROUGH: the
#: output carries the same fed values (reshaped/selected/concatenated),
#: so safety is decided by the OUTPUT's consumers.  concat is included
#: because the fed values survive verbatim into the merged var — a
#: bounded downstream index consumer bounds them exactly as it bounds a
#: direct feed.
_INT64_PASS_OPS = frozenset({
    "reshape", "reshape2", "squeeze", "squeeze2", "unsqueeze",
    "unsqueeze2", "flatten", "flatten2", "slice", "strided_slice",
    "split", "concat", "assign", "transpose", "transpose2",
})


def _classify_int64_feeds(program: Program, fetch_names=()):
    """Static feed-wrap classification v2: an int64/uint64 data feed
    whose every (transitively reached) consumer bounds its VALID values
    below 2**31 is ``static``: every in-range id fits int32, so the
    feed conversion only alters ids that were already invalid — and the
    consumer treats those identically with or without the wrap (see the
    _INT32_BOUND note; XLA gather clamps silently either way).

    v2 over the PR-5 classifier:

    - **bounded index consumers** now include the gather/scatter family
      (``gather``/``gather_nd``/``scatter``/``scatter_nd_add``) — the
      indexed operand's static dims are the bound, exactly as the
      embedding row count bounds ``lookup_table`` ids;
    - **dataflow propagation** through value-preserving chains
      (:data:`_INT64_PASS_OPS`: reshape/squeeze/flatten/slice/split/
      concat/transpose/assign) and integer-to-integer ``cast``: the
      chain's OUTPUT consumers decide, so ``reshape(ids) -> gather``
      classifies like a direct gather;
    - grad-op inheritance preserved: a grad op replays the forward's
      reads of the SAME fed values (``X$<slot>``), so it classifies
      exactly as its forward op.

    Everything else stays ``dynamic`` and keeps the executor's
    first-batch runtime min/max check."""
    block = program.global_block()
    feeds = [v for v in block.vars.values()
             if _is_data(v) and v.dtype in ("int64", "uint64")]
    if not feeds:
        return frozenset(), frozenset()

    def _shape(name, blk):
        if not blk.has_var(name):
            return None
        return blk.var(name).shape

    def _dim_bounded(name, blk, axis=None):
        """True when the indexed extent of var ``name`` is statically
        known and addressable by int32: the consumer clamps/ignores
        anything outside it, wrapped or not."""
        shape = _shape(name, blk)
        if not shape:
            return False
        if axis is None:
            dims = shape
        else:
            # normalize negative axes — a raw shape[-1:0] slice would
            # be EMPTY and all(...) vacuously true (unbounded extents
            # would classify static)
            axis = axis % len(shape) if -len(shape) <= axis < len(shape) \
                else None
            if axis is None:
                return False
            dims = shape[axis:axis + 1]
        return bool(dims) and all(
            d is not None and 0 < d < _INT32_BOUND for d in dims)

    def consumer_verdict(op, blk, name) -> str:
        """'safe' (bounded index consumer) | 'pass' (value-preserving,
        judge the outputs' consumers) | 'ignore' (harmless read that
        neither bounds nor propagates the values — a pass-through op's
        grad reads shape metadata only) | 'unsafe'."""
        typ = op.type
        is_grad = typ.endswith("_grad")
        if is_grad:
            # a grad op replays the forward's reads of the SAME fed
            # values (make_grad_ops forwards them under "X$<slot>"), so
            # it is exactly as safe as its forward op
            typ = typ[: -len("_grad")]

            def slot(s, _op=op):
                return _op.input("X$" + s) or _op.input(s)
        else:
            def slot(s, _op=op):
                return _op.input(s)
        if typ in ("lookup_table", "lookup_table_v2",
                   "fused_embedding_layer_norm") and \
                name in slot("Ids"):
            # the fused embedding+LN op (analysis.fusion) gathers rows
            # exactly like lookup_table: the table's row count bounds
            # valid ids, so fusion must not demote a static feed
            w = slot("W")
            return "safe" if w and _dim_bounded(w[0], blk, axis=0) \
                else "unsafe"
        if typ in ("one_hot", "one_hot_v2") and name in slot("X"):
            depth = op.attrs.get("depth")
            return "safe" if depth and int(depth) < _INT32_BOUND \
                else "unsafe"
        if typ == "gather" and name in slot("Index"):
            x = slot("X")
            axis = int(op.attrs.get("axis", 0))
            return "safe" if x and _dim_bounded(x[0], blk, axis=axis) \
                else "unsafe"
        if typ == "gather_nd" and name in slot("Index"):
            # the trailing index dim addresses the leading dims of X:
            # every statically-known dim under int32 bounds the tuple
            x = slot("X")
            return "safe" if x and _dim_bounded(x[0], blk) else "unsafe"
        if typ == "scatter" and name in slot("Ids"):
            x = slot("X")
            return "safe" if x and _dim_bounded(x[0], blk, axis=0) \
                else "unsafe"
        if typ == "scatter_nd_add" and name in slot("Index"):
            x = slot("X")
            return "safe" if x and _dim_bounded(x[0], blk) else "unsafe"
        if typ == "cast" and name in slot("X"):
            # int->int cast preserves in-range values; a float target
            # means the VALUES are data and a wrap would corrupt them
            outs = op.output_arg_names()
            out_dt = (blk.var(outs[0]).dtype
                      if outs and outs[0] and blk.has_var(outs[0])
                      else None)
            if not (out_dt and "int" in str(out_dt)):
                return "unsafe"
            return "ignore" if is_grad else "pass"
        if typ in _INT64_PASS_OPS:
            # the GRAD of a value-preserving op reads the fed values for
            # shape metadata only (reshape_grad reshapes the cotangent,
            # concat_grad splits it) — its outputs are float gradients,
            # not the fed values, so there is nothing to propagate to;
            # but neither does it BOUND the values, so it must not make
            # a chain static by itself ('ignore', not 'safe')
            return "ignore" if is_grad else "pass"
        return "unsafe"

    # consumer index over EVERY block (loop/cond bodies consume feeds
    # too — sub-block consumers classify exactly like top-level ones)
    consumers: Dict[str, list] = {}
    for b in program.blocks:
        for op in b.ops:
            if op.type in ("feed", "fetch"):
                continue
            for name in op.input_arg_names():
                if name:
                    consumers.setdefault(name, []).append((op, b))

    fetched = frozenset(fetch_names)

    def feed_static(feed_name: str) -> bool:
        # static requires a BOUNDED terminal consumer, not merely any
        # consumer: a chain of pure pass-through ops (reshape -> fetch)
        # re-exposes the raw values with nothing to clamp them, so it
        # must keep the runtime wrap check exactly as v1 did.  The same
        # exposure applies to ANY fetched name in the pass-through
        # closure (including the feed itself): the fetch materializes
        # the post-wrap device values even when a bounded SIBLING
        # consumer exists, so a fetched alias forces dynamic.
        seen = {feed_name}
        frontier = [feed_name]
        any_bounded = False
        while frontier:
            name = frontier.pop()
            if name in fetched:
                return False
            for op, blk in consumers.get(name, ()):
                verdict = consumer_verdict(op, blk, name)
                if verdict == "unsafe":
                    return False
                if verdict == "safe":
                    any_bounded = True
                if verdict == "pass":
                    for out in op.output_arg_names():
                        if out and out not in seen:
                            seen.add(out)
                            frontier.append(out)
        return any_bounded

    static, dynamic = set(), set()
    for v in feeds:
        (static if feed_static(v.name) else dynamic).add(v.name)
    return frozenset(static), frozenset(dynamic)


def _collective_signature(op_node, block: Block):
    op = op_node.op
    x = op.input("X")
    shape = dtype = None
    if x and block.has_var(x[0]):
        v = block.var(x[0])
        shape, dtype = v.shape, v.dtype
    return (op.type, op.attrs.get("ring_id", 0), dtype,
            tuple(shape) if shape else None)


def _check_collective_order(program: Program, graph, fetch_names, diags):
    """Dependency-order the collective ops of the WHOLE program, block 0
    and every ``while``/``cond`` sub-block recursively.  Pairs with no
    path between them can launch in different orders on different ranks
    (the compiler is free to schedule independent collectives for
    latency); when an unordered pair has the SAME signature the
    cross-rank pairing itself is ambiguous — the static form of the
    documented cross-rank ``.numpy()`` materialization deadlock — and
    the check applies per block: two identical unordered allreduces
    INSIDE a loop body mispair exactly like top-level ones.

    Returns the fingerprint of the dependency-ordered collective
    sequence, which every rank of a gang compares over the coordinator
    heartbeat and at ``step_barrier``.  Sub-block collectives fold in at
    their enclosing op's position, stamped with the block path
    (``0/while@5/1``): a loop-body collective is part of the rank's
    launch sequence even though the top-level graph never sees it, so a
    rank whose peer runs a different body refuses before the hang."""
    from ..framework import ir
    entries: List[tuple] = []   # (block path, signature), execution order

    def gather(block_graph, path: str):
        block = program.blocks[block_graph.block_idx]
        nodes = [n for n in block_graph.op_nodes
                 if n.name in _COLLECTIVE_OPS]
        if nodes:
            # forward-reachable op-id sets, by BFS from each collective
            reach: Dict[int, set] = {}
            for n in nodes:
                seen = set()
                stack = [n]
                while stack:
                    cur = stack.pop()
                    for v in cur.outputs:
                        for consumer in v.outputs:
                            if consumer.id not in seen:
                                seen.add(consumer.id)
                                stack.append(consumer)
                reach[n.id] = seen
            unordered, ambiguous = [], []
            for i in range(len(nodes)):
                for j in range(i + 1, len(nodes)):
                    a, b = nodes[i], nodes[j]
                    if b.id in reach[a.id] or a.id in reach[b.id]:
                        continue
                    sig_a = _collective_signature(a, block)
                    sig_b = _collective_signature(b, block)
                    (ambiguous if sig_a == sig_b else unordered).append(
                        (a.name, b.name, sig_a))
            where = "" if path == "0" else f" in sub-block {path!r}"
            if ambiguous:
                a, b, sig = ambiguous[0]
                diags.append(Diagnostic(
                    "collective_order", "error",
                    f"{len(ambiguous)} pair(s) of collective ops share "
                    f"a signature {sig!r} but have no dependency path "
                    f"between them{where} (first pair: {a!r}/{b!r}) — "
                    "ranks can launch them in different orders and "
                    "mispair, deadlocking the gang",
                    op_type=a, block=path,
                    fix_hint="chain them (feed one's output into the "
                             "other's input chain) or give each a "
                             "distinct ring_id"))
            elif unordered:
                diags.append(Diagnostic(
                    "collective_order", "warning",
                    f"{len(unordered)} pair(s) of collective ops have "
                    f"no dependency path between them{where}; their "
                    "launch order is compiler-chosen — verify the "
                    "collective fingerprint matches across ranks before "
                    "entering the gang",
                    op_type=unordered[0][0], block=path,
                    fix_hint="compare program._attrs['verify']"
                             "['collective_fingerprint'] across ranks"))
        # dependency order with a stable program-order tie-break
        # (topology_sort is deterministic for a fixed program); fold
        # sub-block collectives at the enclosing op's position
        order = {n.id: i for i, n in enumerate(
            block_graph.topology_sort())}
        pos = {id(op): i for i, op in enumerate(block.ops)}
        for n in sorted(block_graph.op_nodes,
                        key=lambda n: (order.get(n.id, 0), n.id)):
            if n.name in _COLLECTIVE_OPS:
                entries.append((path, _collective_signature(n, block)))
            subs = sub_blocks_of(n.op)
            if subs:
                idx = pos.get(id(n.op), order.get(n.id, 0))
                for _, sub in subs:
                    gather(ir.Graph(program, sub.idx),
                           f"{path}/{n.name}@{idx}/{sub.idx}")

    gather(graph, "0")
    if not entries and not program._attrs.get("collective"):
        return None
    h = hashlib.sha1()
    for path, sig in entries:
        h.update(repr((path, sig)).encode())
    h.update(repr(tuple(fetch_names)).encode())
    return h.hexdigest()


def _check_memory(program: Program, fetch_names, diags):
    """Static HBM plan (analysis.memory): batch=1 per-example lower
    bound, cached on the fingerprint alongside this verify result.  A
    ``memory_budget`` warning fires when FLAGS_memory_budget_mb is set
    and even the lower bound exceeds it.  Planning failures never block
    verification."""
    from . import memory as _memory
    try:
        plan = _memory.plan_memory(program, fetch_names, batch_size=1)
    except Exception:
        return None
    from ..flags import get_flags
    try:
        budget_mb = int(get_flags("FLAGS_memory_budget_mb")
                        ["FLAGS_memory_budget_mb"])
    except Exception:
        budget_mb = 0
    if budget_mb > 0 and plan.peak_bytes > budget_mb << 20:
        top = ", ".join(f"{t} #{p}" for p, t, _, _ in plan.top_ops(3))
        diags.append(Diagnostic(
            "memory_budget", "warning",
            f"static peak-memory estimate {plan.peak_bytes >> 20} MiB "
            f"(batch=1 lower bound) exceeds FLAGS_memory_budget_mb="
            f"{budget_mb}; heaviest ops: {top}",
            op_type=plan.peak_op, op_index=plan.peak_pos,
            fix_hint="shrink the model/batch, enable sharding, or raise "
                     "the budget; see analysis.memory.plan_memory("
                     "...).report() for the full attribution table"))
    return plan


def _check_cost(program: Program, fetch_names):
    """Analytic per-op flops/bytes plan (analysis.cost): batch=1
    per-example baseline, cached on the fingerprint alongside this
    verify result.  Purely informational — it stamps the attribution the
    executor's live MFU gauge and the fusion arc read; planning failures
    never block verification."""
    from . import cost as _cost
    try:
        return _cost.plan_cost(program, fetch_names, batch_size=1)
    except Exception:
        return None


def _check_comms(program: Program, fetch_names):
    """Static comms plan (analysis.comms): per-collective payload bytes,
    algorithm-bandwidth wire traffic, and the analytic comm-vs-compute
    bound at batch=1.  Same contract as the memory/cost planners:
    informational, fingerprint-cached, never blocks verification."""
    from . import comms as _comms
    try:
        return _comms.plan_comms(program, fetch_names, batch_size=1)
    except Exception:
        return None


def _comms_attrs(plan):
    from . import comms as _comms
    try:
        return _comms.stamp_attrs(plan)
    except Exception:
        return None


def _check_sharding(program: Program, fetch_names, diags):
    """Static GSPMD sharding plan (analysis.sharding): PartitionSpec
    propagation + per-edge reshard pricing over the partition stamp.
    Unlike the memory/cost/comms planners this check CAN block
    verification — its spec_conflict / mesh_axis_overuse errors are
    exactly the optimize-time rule-table refusal — but a planner CRASH
    still never blocks (same contract as the others)."""
    from . import sharding as _sharding
    try:
        plan = _sharding.plan_sharding(program, fetch_names,
                                       batch_size=1)
    except Exception:
        return None
    if plan is not None:
        diags.extend(plan.diagnostics)
    return plan


def _sharding_attrs(plan):
    from . import sharding as _sharding
    try:
        return _sharding.stamp_attrs(plan)
    except Exception:
        return None


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _partition_token(program: Program) -> Optional[str]:
    """GSPMD partition fingerprint of ``program``'s partition stamp
    (``with_gspmd``'s ``_attrs["partition"]``), or None when the program
    is unpartitioned."""
    stamp = program._attrs.get("partition")
    if not stamp:
        return None
    try:
        from ..parallel.partitioner import partition_fingerprint
        return partition_fingerprint(stamp)
    except Exception:
        return None


def _verify_cached(program: Program, fetch_names) -> \
        Tuple[VerifyResult, bool]:
    """(result, fresh): ``fresh`` is True for exactly ONE caller per
    cache key — the thread whose result entered the cache — so warning
    emission can be deduped without re-deriving the key outside."""
    fetch_names = tuple(
        f.name if hasattr(f, "name") else f for f in (fetch_names or ()))
    # keyed on the fetch TUPLE: order matters — the collective
    # fingerprint hashes the materialization (fetch) order, so a
    # reordered fetch list must re-verify, not hit a stale result.
    # The GSPMD partition stamp joins the key: it lives in _attrs (not
    # the structural fingerprint), and a re-partitioned program must
    # re-derive its folded fingerprint, not hit the old table's.
    ptok = _partition_token(program)
    key = (program.fingerprint(), fetch_names, ptok)
    with _CACHE_LOCK:
        cached = _CACHE.get(key)
    if cached is not None:
        _RUNS_HIT.inc()
        return cached, False
    _RUNS_MISS.inc()
    with _monitor.TRACER.span("verifier.verify", "compile",
                              fetches=len(fetch_names)):
        from ..framework import ir
        result = VerifyResult()
        diags = result.diagnostics
        # one read-only Graph shared by the graph-walking checks
        graph = ir.Graph(program)
        _check_def_before_use(program, diags)
        _check_feed_fetch(program, fetch_names, diags)
        try:
            _check_shape_consistency(program, diags)
        except Exception:            # re-inference must never block verify
            pass
        result.dead_ops, result.dead_subblock_ops = \
            _check_dead_ops(graph, fetch_names, diags)
        _check_use_after_donate(program, fetch_names, diags)
        result.int64_static, result.int64_dynamic = \
            _classify_int64_feeds(program, fetch_names)
        result.collective_fingerprint = _check_collective_order(
            program, graph, fetch_names, diags)
        result.memory_plan = _check_memory(program, fetch_names, diags)
        result.cost_plan = _check_cost(program, fetch_names)
        result.comms_plan = _check_comms(program, fetch_names)
        result.sharding_plan = _check_sharding(program, fetch_names,
                                               diags)
        if result.comms_plan is not None and \
                result.collective_fingerprint is not None:
            # fold the comms plan (nranks + ordered per-collective
            # payload bytes) into the cross-rank fingerprint: the gang
            # compares ONE token over the heartbeat/step-barrier, and a
            # divergent comms plan must refuse exactly like a divergent
            # collective sequence.  Every rank derives it through this
            # same function, so matching programs keep matching.
            result.collective_fingerprint = hashlib.sha1(
                (result.collective_fingerprint + "|"
                 + result.comms_plan.fingerprint).encode()).hexdigest()
        if ptok:
            # fold the GSPMD partition stamp (mesh shape + per-param
            # PartitionSpecs) the same way: ranks that chose divergent
            # rule tables refuse at the step barrier instead of
            # deadlocking inside mismatched collectives.  Base may be
            # None — a pjit-partitioned program has no explicit
            # collective ops.  The "#rules=<table>" suffix survives the
            # hash so the coordinator's mismatch detail, which prints
            # both raw fingerprints, NAMES both tables.
            # the "#resh=<edges>x<sha8>" token joins the fold: two ranks
            # running the SAME rule table over structurally divergent
            # programs (different models, different zero stage) carry
            # different reshard plans — the barrier refusal names both
            # plans instead of deadlocking inside mismatched implicit
            # collectives.  It precedes "#rules=" so the rules suffix
            # stays the FINAL token (coordinator's _gspmd_rules_of
            # parses split("#rules=")[1] verbatim).
            resh = ""
            if result.sharding_plan is not None:
                resh = "#resh=" + result.sharding_plan.resh_token
            base = result.collective_fingerprint or ""
            digest = hashlib.sha1(
                (base + "|" + ptok + resh).encode()).hexdigest()
            result.collective_fingerprint = \
                digest + resh + ptok[ptok.index("#"):]
    for d in diags:
        _FINDING_CELLS[d.check].inc()
    # int64_feed "findings" are classifications, not diagnostics: the
    # counter tracks how many feeds KEPT the runtime wrap check
    if result.int64_dynamic:
        _FINDING_CELLS["int64_feed"].inc(len(result.int64_dynamic))
    plan = result.memory_plan
    program._attrs["verify"] = {
        "int64_dynamic": sorted(result.int64_dynamic),
        "int64_static": sorted(result.int64_static),
        "collective_fingerprint": result.collective_fingerprint,
        # static HBM model (batch=1 lower bound): the numbers other
        # layers read without re-planning — tools/analyze.py, the OOM
        # report, the GSPMD/fusion arc's placement heuristics
        "memory": None if plan is None else {
            "peak_bytes": plan.peak_bytes,
            "resident_bytes": plan.resident_bytes,
            "steady_bytes": plan.steady_bytes,
            "peak_op": plan.peak_op,
            "top_ops": [(p, t, b) for p, t, b, _ in plan.top_ops(5)],
        },
        # analytic flops/bytes model (batch=1 baseline): the per-step
        # numbers the executor's live MFU gauge scales by the real
        # batch, and the per-class roofline share the fusion arc ranks
        # rewrite candidates by
        "cost": None if result.cost_plan is None else {
            "flops": result.cost_plan.flops,
            "bytes": result.cost_plan.bytes,
            "per_class": dict(result.cost_plan.per_class),
            "intensity": result.cost_plan.intensity(),
        },
        # static comms model (batch=1 baseline): per-collective payload/
        # wire bytes, the analytic comm-time estimate at link peak, and
        # the comm-vs-compute bound verdict — what the executor's
        # collective launch telemetry, bench.py's comms: lines, and the
        # quantized-collectives gate read without re-planning
        "comms": _comms_attrs(result.comms_plan),
        # static GSPMD sharding model: propagated specs + priced reshard
        # edges + the #resh= parity token — what tools/analyze.py
        # --sharding, the gspmd/sharding smokes, and choose_rules
        # auditing read without re-planning
        "sharding": _sharding_attrs(result.sharding_plan),
    }
    with _CACHE_LOCK:
        fresh = key not in _CACHE
        if fresh:
            if len(_CACHE) >= _CACHE_CAP:   # FIFO bound, see _CACHE note
                _CACHE.pop(next(iter(_CACHE)))
            _CACHE[key] = result
        result = _CACHE[key]   # concurrent misses converge on one object
    return result, fresh


def verify_program(program: Program, fetch_names=()) -> VerifyResult:
    """Run every check; cached on (program fingerprint, fetch tuple).

    Also stamps ``program._attrs["verify"]`` with the machine-readable
    artifacts (int64 classification, collective fingerprint) — the attrs
    ride ``Program.clone``, so the optimized program the executor caches
    in its dispatch plan carries them too."""
    return _verify_cached(program, fetch_names)[0]


def verify_or_raise(program: Program, fetch_names=()) -> VerifyResult:
    """``verify_program`` + enforcement: error-severity findings raise
    :class:`ProgramVerificationError` (with the full debugger-formatted
    report), warning-severity findings emit one ``warnings.warn`` per
    fresh verify (the fingerprint cache dedupes steady-state repeats,
    and ``_verify_cached`` marks exactly one caller fresh per key)."""
    result, fresh = _verify_cached(program, fetch_names)
    from .. import debugger
    if not result.ok:
        raise ProgramVerificationError(
            "program verification failed:\n"
            + debugger.format_diagnostics(result.diagnostics), result)
    if fresh and result.warnings():
        import warnings
        warnings.warn(
            "program verifier warnings:\n"
            + debugger.format_diagnostics(result.warnings()),
            stacklevel=2)
    return result


def dynamic_int64_feeds(program: Program) -> Optional[FrozenSet[str]]:
    """The int64/uint64 feed names still needing the runtime wrap check,
    or None when the program was never verified (caller falls back to
    checking every int64 feed — the legacy behavior)."""
    va = program._attrs.get("verify")
    if va is None or va.get("int64_dynamic") is None:
        return None
    return frozenset(va["int64_dynamic"])


def collective_fingerprint(program: Program) -> Optional[str]:
    va = program._attrs.get("verify")
    if va is not None and va.get("collective_fingerprint"):
        return va["collective_fingerprint"]
    result = verify_program(program)
    return result.collective_fingerprint
