"""Analytic per-op flops/bytes cost model over the dependency-ordered
``framework.ir`` Graph — the device-time attribution half of the
observability stack.

``bench.py`` has always computed MFU offline from hand-written per-model
FLOP formulas; this module generalizes that accounting to ANY program:
each op gets an analytic flop count and a logical byte-traffic estimate
from its inferred shapes (TPP, arxiv 2104.05755, frames exactly this
flops/bytes efficiency ledger per primitive; TVM, arxiv 1802.04799, uses
the same per-op cost shape to drive schedule selection — the upcoming
fusion pass picks candidates from these numbers).  The model is the
denominator source for the executor's live ``paddle_tpu_step_mfu`` gauge
and the roofline attribution (``per_class`` flop shares) the fusion arc
will rank rewrite candidates by.

Accounting rules:

- **matmul family** (``mul``/``matmul``/``matmul_v2``): 2·M·K·N over the
  batch-resolved shapes (transpose attrs honored);
- **conv2d**: 2·C_in·kh·kw per output element (the same 2·MAC rule
  ``bench.py`` applies to ResNet);
- **grad ops** inherit their forward op's formula ×2 (a matmul backward
  is two matmuls of the forward's size; conv backward likewise — the
  standard fwd:bwd 1:2 flop ratio bench.py's ×3 total encodes);
- **normalization/softmax/activation/elementwise**: a small per-element
  factor (the VPU work is real but MXU-irrelevant; it matters for the
  bytes-bound ops the roofline flags);
- **lookup/gather family**: zero flops, bytes = gathered rows (pure
  HBM traffic — exactly the ops the roofline calls memory-bound);
- **bytes** per op = input bytes read + output bytes written at the
  resolved batch (symbolic dims resolve through ``batch_size``, same as
  the memory planner).

Results are cached on the program fingerprint (the memory planner's key
discipline) and stamped into ``program._attrs["verify"]["cost"]`` by the
verifier, so steady-state dispatch never re-plans and the executor reads
flops-per-step with one dict probe.  ``compiled.cost_analysis()`` — the
XLA-reported flop count — is the cross-check: ``FLAGS_cost_crosscheck``
makes the executor compare the two at compile time and count divergence
(``paddle_tpu_cost_crosscheck_total{verdict}``), so the analytic model
can never silently drift from what the compiler actually emits.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import monitor as _monitor
from ..framework.core import Block, Program

__all__ = ["CostPlan", "plan_cost", "clear_cache", "device_peak_flops",
           "xla_cost_breakdown", "xla_cost_totals"]

_PLAN_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_cost_plans_total",
    "plan_cost calls by fingerprint-cache outcome", ("cache",))
_PLAN_HIT = _PLAN_CTR.labels(cache="hit")
_PLAN_MISS = _PLAN_CTR.labels(cache="miss")

#: op type -> roofline class.  Grad ops inherit their forward's class;
#: anything unlisted is "other".
_CLASS_OF = {
    "conv2d": "conv", "depthwise_conv2d": "conv", "conv2d_transpose": "conv",
    "mul": "matmul", "matmul": "matmul", "matmul_v2": "matmul",
    "lookup_table": "embedding", "lookup_table_v2": "embedding",
    "gather": "embedding", "gather_nd": "embedding",
    "scatter": "embedding", "scatter_nd_add": "embedding",
    "batch_norm": "norm", "layer_norm": "norm", "group_norm": "norm",
    "softmax": "softmax", "softmax_with_cross_entropy": "softmax",
    "cross_entropy": "softmax", "cross_entropy2": "softmax",
    "reduce_sum": "reduce", "reduce_mean": "reduce", "reduce_max": "reduce",
    "mean": "reduce", "sum": "reduce",
    "adam": "optimizer", "momentum": "optimizer", "sgd": "optimizer",
    "adagrad": "optimizer", "lamb": "optimizer", "rmsprop": "optimizer",
    "flash_attention": "attention", "fused_attention": "attention",
    # analysis.fusion rewrite targets keep their source chain's class so
    # the roofline shares (and the live MFU numerator) survive fusion
    "fused_conv1x1_bn": "conv", "fused_dense_act": "matmul",
    "fused_embedding_layer_norm": "embedding",
}

#: per-element flop factors for the cheap (VPU) classes; everything not
#: matched by a structural formula below falls back to one of these
_ELEM_FLOPS = {
    "softmax": 5.0, "softmax_with_cross_entropy": 7.0,
    "cross_entropy": 3.0, "cross_entropy2": 3.0,
    "layer_norm": 8.0, "batch_norm": 4.0, "group_norm": 8.0,
    "gelu": 9.0, "tanh": 6.0, "sigmoid": 4.0, "erf": 6.0,
    "exp": 2.0, "log": 2.0, "sqrt": 2.0, "rsqrt": 2.0, "pow": 3.0,
    "dropout": 2.0, "adam": 10.0, "lamb": 14.0, "momentum": 4.0,
    "fused_embedding_layer_norm": 8.0,
}

_ITEMSIZE = {"bfloat16": 2, "float16": 2, "bool": 1}


def _itemsize(dtype) -> int:
    d = str(dtype or "float32")
    if d in _ITEMSIZE:
        return _ITEMSIZE[d]
    try:
        return int(np.dtype(d).itemsize)
    except TypeError:
        return 4


def _shape(block: Block, name, batch_size: int) -> Optional[Tuple[int, ...]]:
    if not name or not block.has_var(name):
        return None
    v = block.var(name)
    if v.shape is None:
        return None
    return tuple(batch_size if d in (-1, None) else int(d)
                 for d in v.shape)


def _numel(shape) -> int:
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= max(int(d), 1)
    return n


def _var_bytes(block: Block, name, batch_size: int) -> int:
    s = _shape(block, name, batch_size)
    if s is None:
        return 0
    v = block.var(name)
    return max(_numel(s), 1) * _itemsize(v.dtype)


def device_peak_flops(device=None) -> float:
    """Peak dense bf16 FLOP/s of one chip — the MFU denominator shared by
    ``bench.py``'s offline lines and the executor's live gauge (the two
    accountings must divide by the SAME peak or the bench tolerance gate
    is meaningless).  CPU backends get a nominal 1e12 smoke constant,
    matching bench.py's CPU fallback."""
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            return 1e12
    platform = getattr(device, "platform", "cpu")
    if platform not in ("tpu", "axon"):
        return 1e12
    peak = {"v5e": 197e12, "v5lite": 197e12, "v5": 197e12,
            "v4": 275e12, "v5p": 459e12}
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    # longest key first so 'v5p' wins over its prefix 'v5'
    return next((peak[k] for k in sorted(peak, key=len, reverse=True)
                 if k in kind), 197e12)


@dataclass
class CostPlan:
    """Analytic per-step flops/bytes model of one program."""

    #: total analytic flops per step (forward + backward + optimizer)
    flops: int = 0
    #: total logical bytes accessed per step (inputs read + outputs
    #: written, not deduplicated across ops — an upper bound on traffic)
    bytes: int = 0
    #: per-op attribution in dependency order:
    #: (pos, op_type, op_class, flops, bytes)
    per_op: List[tuple] = field(default_factory=list)
    #: op_class -> total flops (the roofline share the fusion arc ranks
    #: candidates by; ``share()`` normalizes)
    per_class: Dict[str, int] = field(default_factory=dict)
    #: op_class -> total bytes
    per_class_bytes: Dict[str, int] = field(default_factory=dict)
    batch_size: int = 1

    def share(self) -> Dict[str, float]:
        """Per-class flop share in [0, 1] (empty program: {})."""
        total = float(self.flops) or 1.0
        return {c: f / total for c, f in self.per_class.items()}

    def intensity(self) -> float:
        """Arithmetic intensity (flops per logical byte accessed)."""
        return self.flops / self.bytes if self.bytes else 0.0

    def top_ops(self, k: int = 10) -> List[tuple]:
        return sorted(self.per_op, key=lambda r: -r[3])[:k]

    def report(self, k: int = 10) -> str:
        lines = [
            f"analytic cost (batch={self.batch_size}): "
            f"{self.flops / 1e9:.3f} GFLOP, "
            f"{self.bytes / 1e6:.1f} MB accessed, "
            f"intensity {self.intensity():.1f} flop/B"]
        share = self.share()
        if share:
            lines.append("flop share: " + ", ".join(
                f"{c}={s * 100:.1f}%" for c, s in
                sorted(share.items(), key=lambda kv: -kv[1])))
        for pos, typ, cls, fl, by in self.top_ops(k):
            lines.append(f"  #{pos:<4} {typ:<28} [{cls}] "
                         f"{fl / 1e6:10.2f} MFLOP  {by / 1e6:8.2f} MB")
        return "\n".join(lines)


def _slot(op, name):
    """Input slot resolution that also sees a GRAD op's forwarded
    forward-inputs (``make_grad_ops`` re-feeds them under ``X$<slot>`` —
    the same convention the verifier's int64 classifier follows)."""
    return op.input("X$" + name) or op.input(name)


def _matmul_flops(block, op, batch_size) -> Optional[int]:
    """2·M·K·N for the mul/matmul family; None when shapes are unknown."""
    xs = _slot(op, "X")
    ys = _slot(op, "Y")
    if not xs or not ys:
        return None
    x = _shape(block, xs[0], batch_size)
    y = _shape(block, ys[0], batch_size)
    if not x or not y:
        return None
    if op.type == "mul":
        # mul flattens X to 2-D at num_col_dims: [prod(lead), K] @ [K, N]
        ncd = int(op.attrs.get("x_num_col_dims", 1))
        m = _numel(x[:ncd])
        k = _numel(x[ncd:])
        n = _numel(y[1:]) if len(y) > 1 else 1
        return 2 * m * k * n
    tx = bool(op.attrs.get("transpose_X") or op.attrs.get("trans_x"))
    ty = bool(op.attrs.get("transpose_Y") or op.attrs.get("trans_y"))
    if len(x) == 1:                       # vector promotes to [1, K]
        x = (1,) + x
    if len(y) == 1:                       # vector promotes to [K, 1]
        y = y + (1,)
    xm, xk = (x[-1], x[-2]) if tx else (x[-2], x[-1])
    yn = y[-2] if ty else y[-1]
    lead = _numel(x[:-2]) if len(x) > 2 else \
        (_numel(y[:-2]) if len(y) > 2 else 1)
    return 2 * lead * xm * xk * yn


def _conv_flops(block, op, batch_size) -> Optional[int]:
    f = _slot(op, "Filter")
    # a conv grad has no "Output" slot; the output GRADIENT it consumes
    # has the forward output's shape, which is all the formula needs
    o = op.output("Output") or op.input("OG$Output") or \
        op.input("Output@GRAD")
    if not f or not o:
        return None
    w = _shape(block, f[0], batch_size)
    out = _shape(block, o[0], batch_size)
    if not w or not out or len(w) < 4 or len(out) < 4:
        return None
    # out [N, C_out, H, W]; filter [C_out, C_in/groups, kh, kw]
    return 2 * _numel(out) * w[1] * w[2] * w[3]


def _fused_conv1x1_flops(block, op, batch_size) -> Optional[int]:
    """fused_conv1x1_bn: the 1x1 conv is 2·Cin MACs per output element
    (the BN epilogue is VPU noise the conv formula dominates)."""
    f = _slot(op, "Filter")
    y = op.output("Y") or op.input("OG$Y")
    if not f or not y:
        return None
    w = _shape(block, f[0], batch_size)
    out = _shape(block, y[0], batch_size)
    if not w or not out or len(w) < 2:
        return None
    return 2 * _numel(out) * w[1]


def _fused_dense_flops(block, op, batch_size) -> Optional[int]:
    """fused_dense_act: 2·M·K·N over the flattened x (mul semantics at
    ``x_num_col_dims``; -1 = matmul over the trailing dim)."""
    xs = _slot(op, "X")
    ws = _slot(op, "W")
    if not xs or not ws:
        return None
    x = _shape(block, xs[0], batch_size)
    w = _shape(block, ws[0], batch_size)
    if not x or not w:
        return None
    ncd = int(op.attrs.get("x_num_col_dims", 1))
    if ncd < 0:
        ncd = len(x) - 1
    m = _numel(x[:ncd])
    k = _numel(x[ncd:])
    n = _numel(w[1:]) if len(w) > 1 else 1
    return 2 * m * k * n


def _op_cost(block: Block, op, batch_size: int) -> Tuple[int, int, str]:
    """(flops, bytes, op_class) of one op at the resolved batch."""
    typ = op.type
    is_grad = typ.endswith("_grad")
    fwd = typ[: -len("_grad")] if is_grad else typ
    grad_mult = 2 if is_grad else 1

    in_bytes = sum(_var_bytes(block, n, batch_size)
                   for n in op.input_arg_names())
    out_bytes = sum(_var_bytes(block, n, batch_size)
                    for n in op.output_arg_names())
    bytes_ = in_bytes + out_bytes
    cls = _CLASS_OF.get(fwd, "other")

    flops = None
    if fwd in ("mul", "matmul", "matmul_v2"):
        flops = _matmul_flops(block, op, batch_size)
    elif fwd in ("conv2d", "depthwise_conv2d", "conv2d_transpose"):
        flops = _conv_flops(block, op, batch_size)
    elif fwd == "fused_conv1x1_bn":
        flops = _fused_conv1x1_flops(block, op, batch_size)
    elif fwd == "fused_dense_act":
        flops = _fused_dense_flops(block, op, batch_size)
    elif fwd in ("lookup_table", "lookup_table_v2", "gather", "gather_nd",
                 "scatter", "scatter_nd_add"):
        flops = 0
    if flops is None:
        # per-element fallback on the dominant output (grad ops read the
        # forward's output names through the same var set, so the element
        # count is comparable)
        elems = max((_numel(_shape(block, n, batch_size))
                     for n in op.output_arg_names() if n), default=0)
        if not elems:
            elems = max((_numel(_shape(block, n, batch_size))
                         for n in op.input_arg_names() if n), default=0)
        flops = int(elems * _ELEM_FLOPS.get(fwd, 1.0))
    return int(flops) * grad_mult, int(bytes_), cls


# (program fingerprint, fetch tuple, batch) -> CostPlan; bounded FIFO —
# same discipline as the verifier and memory-planner caches
_CACHE: Dict[tuple, CostPlan] = {}  # guarded-by: _CACHE_LOCK
_CACHE_CAP = 128
_CACHE_LOCK = threading.Lock()


def clear_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()


def plan_cost(program: Program, fetch_names=(),
              batch_size: int = 1) -> CostPlan:
    """Analytic flops/bytes plan for one program (see module docstring).
    Cached on (program fingerprint, fetch tuple, batch_size); symbolic
    (-1/None) dims resolve through ``batch_size``."""
    fetch_names = tuple(
        f.name if hasattr(f, "name") else f for f in (fetch_names or ()))
    key = (program.fingerprint(), fetch_names, int(batch_size))
    with _CACHE_LOCK:
        cached = _CACHE.get(key)
    if cached is not None:
        _PLAN_HIT.inc()
        return cached
    _PLAN_MISS.inc()
    with _monitor.TRACER.span("cost.plan", "compile",
                              fetches=len(fetch_names)):
        plan = _plan(program, int(batch_size))
    with _CACHE_LOCK:
        if key not in _CACHE:
            if len(_CACHE) >= _CACHE_CAP:
                _CACHE.pop(next(iter(_CACHE)))
            _CACHE[key] = plan
        plan = _CACHE[key]
    return plan


def _plan(program: Program, batch_size: int) -> CostPlan:
    from ..framework import ir
    from ..framework.core import Block as _Block
    block = program.global_block()
    graph = ir.Graph(program)
    order = graph.topology_sort()

    plan = CostPlan(batch_size=batch_size)
    per_class: Dict[str, int] = {}
    per_class_bytes: Dict[str, int] = {}

    def add(pos, blk, op):
        if op.type in ("feed", "fetch"):
            return
        fl, by, cls = _op_cost(blk, op, batch_size)
        plan.flops += fl
        plan.bytes += by
        per_class[cls] = per_class.get(cls, 0) + fl
        per_class_bytes[cls] = per_class_bytes.get(cls, 0) + by
        plan.per_op.append((pos, op.type, cls, fl, by))
        # sub-block bodies (while/cond) count ONCE — a static model
        # cannot know the trip count; the per-iteration cost is the
        # honest per-step lower bound (same convention as the planner)
        for v in op.attrs.values():
            if isinstance(v, _Block):
                for sop in v.ops:
                    add(pos, v, sop)

    for i, node in enumerate(order):
        add(i, block, node.op)
    plan.per_class = per_class
    plan.per_class_bytes = per_class_bytes
    return plan


def xla_cost_totals(cost_analysis) -> Tuple[float, float]:
    """(flops, bytes accessed) out of a ``Compiled.cost_analysis()``
    result, which jax returns as a dict or a one-element list of dicts
    depending on version.  Missing keys read as 0."""
    ca = cost_analysis
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return 0.0, 0.0
    return float(ca.get("flops", 0.0) or 0.0), \
        float(ca.get("bytes accessed", 0.0) or 0.0)


def xla_cost_breakdown(cost_analysis) -> Dict[str, object]:
    """The FULL utilization breakdown of a ``cost_analysis()`` result —
    not just the totals: transcendentals (XLA bills RNG/gelu erf here,
    a common totals-divergence cause) and the per-operand ``bytes
    accessedN{}``/``utilizationN{}`` keys, parsed into nested dicts the
    crosscheck attaches to its tracer record and divergence warning."""
    ca = cost_analysis
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    if not isinstance(ca, dict):
        return {}
    out: Dict[str, object] = {
        "flops": float(ca.get("flops", 0.0) or 0.0),
        "transcendentals": float(ca.get("transcendentals", 0.0) or 0.0),
        "bytes_accessed": float(ca.get("bytes accessed", 0.0) or 0.0),
    }
    operand_bytes: Dict[str, float] = {}
    operand_util: Dict[str, float] = {}
    for k, v in ca.items():
        try:
            fv = float(v)
        except (TypeError, ValueError):
            continue
        tag = k.replace("{}", "").strip()
        if k.startswith("bytes accessed") and k != "bytes accessed":
            operand_bytes[tag[len("bytes accessed"):] or "out"] = fv
        elif k.startswith("utilization"):
            operand_util[tag[len("utilization"):] or "out"] = fv
    if operand_bytes:
        out["operand_bytes"] = operand_bytes
    if operand_util:
        out["operand_utilization"] = operand_util
    return out
