"""Measured device-time attribution from captured profiler windows —
the xprof half of the observability stack, closing the loop PR 8 opened.

``profiler.SamplingProfiler`` captures real ``jax.profiler`` windows
(``<window>/plugins/profile/<run>/*.trace.json.gz`` + ``*.xplane.pb``,
step-annotated via ``StepTraceAnnotation("paddle_tpu.step")``), but until
this module nothing in the repo ever parsed them: MFU was analytic-only
(``paddle_tpu_step_mfu`` divides model flops by the dispatch interval),
with no measured breakdown of compute vs memory vs idle.  This module
turns a captured window into *attribution*:

- **Trace parser** (:func:`parse_trace`): the chrome-trace JSON the
  profiler writes per window, with process/thread metadata resolved.
  Device lanes are the ``/device:*`` processes on real TPU captures and
  the XLA runtime execution threads (``tf_XLATfrtCpuClient*``) on the
  CPU smoke — host python frames and compile threads never count as
  device time.

- **XPlane wire reader** (:func:`read_xplane`): a dependency-free
  protobuf *wire-format* parser for ``*.xplane.pb`` (XSpace → XPlane →
  XLine → XEvent durations + event-metadata names) — no TensorFlow or
  generated proto import, because the container has neither.  Used for
  kernel durations on device planes and cross-checking the JSON trace.

- **Step join** (:func:`step_intervals`): ``paddle_tpu.step`` spans
  carry the executor's process-global step id (``args.step_num``) — the
  SAME id stamped on the host ``executor.dispatch`` span and the
  sampling-window manifest — so device kernels attribute to framework
  steps by interval containment on the shared trace clock.

- **Op-class attribution** (:func:`classify_kernel`): HLO/fusion kernel
  names map back to the PR-8 cost-model op classes
  (matmul/conv/attention/embedding/collective/infeed/elementwise), per
  arxiv 2104.05755's observation that a few op classes dominate device
  time.  Per-step measured device time, per-class shares, idle/gap
  fraction, and **measured MFU** — analytic flops/step over measured
  device-busy time × chip peak — published as
  ``paddle_tpu_step_mfu_measured`` next to the analytic gauge.

- **Objective oracle** (:func:`summarize_and_publish`): the post-close
  hook in ``SamplingProfiler`` calls this to persist
  ``<window>/summary.json`` — per-class measured shares, the
  measured-vs-analytic divergence table, and per-kernel
  wasted-roofline-headroom ranking the autotune search (TVM-style,
  arxiv 1802.04799) consumes as its measurement objective.  The hook
  path NEVER raises: malformed/truncated captures warn and skip.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

from .. import monitor as _monitor

__all__ = [
    "classify_kernel", "parse_trace", "step_intervals", "device_lanes",
    "read_xplane", "xplane_kernel_ms", "attribute", "summarize_window",
    "write_summary", "summarize_and_publish", "latest_profile_run",
    "MEASURED_CLASSES",
]

#: measured device-time classes, the attribution buckets kernels map to
MEASURED_CLASSES = ("matmul", "conv", "attention", "embedding",
                    "collective", "infeed", "elementwise", "other")

MFU_MEASURED_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_step_mfu_measured",
    "measured model-flops utilization in [0,1]: analytic flops/step "
    "over MEASURED per-step device-busy time x chip peak, from the last "
    "parsed profiler window — the companion of the analytic "
    "paddle_tpu_step_mfu gauge (divergence = dispatch-interval slack "
    "the analytic estimate cannot see)")
IDLE_FRAC_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_step_device_idle_frac",
    "measured idle/gap fraction of the step span (device lanes quiet) "
    "from the last parsed profiler window")
DEVICE_SHARE_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_step_device_time_share",
    "measured device-time share by op class from the last parsed "
    "profiler window — the MEASURED counterpart of the analytic "
    "paddle_tpu_step_flops_share", ("op_class",))
_SUMMARY_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_profile_summaries_total",
    "post-close window summaries by outcome (ok / empty / error)",
    ("outcome",))

#: wall time of the last successful publish — monitor.metrics_digest
#: freshness-gates the ``mfu_m`` digest key on this (same discipline as
#: the hbm/comms planes: a rank that stopped capturing windows must not
#: report its last measured MFU forever)
last_publish_wall = 0.0


# ---------------------------------------------------------------------------
# kernel-name -> op-class attribution
# ---------------------------------------------------------------------------

#: ordered (regex, class) rules: FIRST match wins, so collectives beat
#: the embedded 'scatter' in 'reduce-scatter' and fused attention beats
#: the 'dot' inside its fusion name
_KERNEL_RULES: Tuple[Tuple[re.Pattern, str], ...] = tuple(
    (re.compile(p, re.IGNORECASE), c) for p, c in (
        (r"all-?reduce|all-?gather|reduce-?scatter|all-?to-?all|"
         r"collective-?permute|psum|ppermute|cross-replica", "collective"),
        (r"infeed|outfeed|host-?transfer|copy-start|copy-done|"
         r"send\b|send-done|recv\b|recv-done", "infeed"),
        (r"attention|flash|mha\b", "attention"),
        (r"conv", "conv"),
        (r"\bdot\b|dot[._]|[^a-z]dot$|gemm|matmul|einsum|cublas|mxu",
         "matmul"),
        (r"gather|scatter|dynamic-?slice|dynamic-?update-?slice|"
         r"embedding|one-?hot|take\b", "embedding"),
        (r"fusion|loop|elementwise|add|sub[^s]|mult|div|exp|log|tanh|"
         r"sigmoid|gelu|relu|erf|rsqrt|sqrt|pow|max|min|select|compare|"
         r"broadcast|reduce|transpose|reshape|convert|bitcast|concat|"
         r"slice|pad|iota|rng|sort|tuple|copy|clamp|negate|and|or|xor",
         "elementwise"),
    ))


def classify_kernel(name: str) -> str:
    """Map one HLO/fusion/thunk kernel name to a measured op class (the
    PR-8 cost-model classes, measured flavor).  Unrecognized -> 'other'."""
    n = str(name)
    # custom-call / pallas kernels keep their payload name ("%fusion.3",
    # "custom-call.7 @flash_attention" ...) — strip HLO sigils so the
    # rules see the meat
    n = n.lstrip("%").strip()
    for rx, cls in _KERNEL_RULES:
        if rx.search(n):
            return cls
    return "other"


#: non-kernel infrastructure spans on device/runtime lanes — scheduler
#: bookkeeping and blocking waits, never device work
_INFRA_RX = re.compile(
    r"ThreadpoolListener|ThunkExecutor|ExecuteThunks|wait for completion|"
    r"^\$|^process_|^thread_|^paddle_tpu\.step$|^PjitFunction|"
    r"^ThreadRun|XlaModule|^Steps?$", re.IGNORECASE)


# ---------------------------------------------------------------------------
# chrome-trace (trace.json.gz) parsing
# ---------------------------------------------------------------------------

def parse_trace(path: str) -> Optional[Dict[str, Any]]:
    """Load one chrome-trace JSON (optionally gzipped) into
    ``{"events": [...], "processes": {pid: name},
    "threads": {(pid, tid): name}}``.  Malformed or truncated files
    warn and return None — the post-close hook path must never raise."""
    try:
        if path.endswith(".gz"):
            with gzip.open(path, "rt", encoding="utf-8",
                           errors="replace") as f:
                data = json.load(f)
        else:
            with open(path, encoding="utf-8", errors="replace") as f:
                data = json.load(f)
    except (OSError, EOFError, ValueError) as e:
        warnings.warn(f"device_profile: unreadable trace {path!r}: {e!r}")
        return None
    if not isinstance(data, dict):
        warnings.warn(f"device_profile: trace {path!r} is not an object")
        return None
    events = data.get("traceEvents")
    if not isinstance(events, list):
        warnings.warn(f"device_profile: trace {path!r} has no traceEvents")
        return None
    processes: Dict[int, str] = {}
    threads: Dict[Tuple[int, int], str] = {}
    spans: List[Dict[str, Any]] = []
    for ev in events:
        if not isinstance(ev, dict):
            continue
        ph = ev.get("ph")
        if ph == "M":
            args = ev.get("args") or {}
            if ev.get("name") == "process_name":
                processes[ev.get("pid")] = str(args.get("name", ""))
            elif ev.get("name") == "thread_name":
                threads[(ev.get("pid"), ev.get("tid"))] = \
                    str(args.get("name", ""))
        elif ph == "X":
            try:
                ts = float(ev.get("ts", 0.0))
                dur = float(ev.get("dur", 0.0))
            except (TypeError, ValueError):
                continue
            spans.append({"name": str(ev.get("name", "")),
                          "pid": ev.get("pid"), "tid": ev.get("tid"),
                          "ts": ts, "dur": dur,
                          "args": ev.get("args") or {}})
    return {"events": spans, "processes": processes, "threads": threads}


def step_intervals(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Framework-step intervals from the ``paddle_tpu.step``
    StepTraceAnnotation spans (``args.step_num`` is the executor's
    process-global step id).  Duplicate annotations for one id (nested
    re-entry) collapse to the widest span.  Sorted by start time."""
    by_id: Dict[int, Tuple[float, float]] = {}
    for ev in trace["events"]:
        if ev["name"] != "paddle_tpu.step":
            continue
        try:
            step = int(ev["args"].get("step_num"))
        except (TypeError, ValueError):
            continue
        t0, t1 = ev["ts"], ev["ts"] + ev["dur"]
        if step in by_id:
            o0, o1 = by_id[step]
            by_id[step] = (min(t0, o0), max(t1, o1))
        else:
            by_id[step] = (t0, t1)
    return [{"step": s, "ts": t0, "dur": t1 - t0}
            for s, (t0, t1) in sorted(by_id.items(),
                                      key=lambda kv: kv[1][0])]


def device_lanes(trace: Dict[str, Any]) -> List[Tuple[int, int]]:
    """(pid, tid) lanes that carry device/kernel execution events: any
    thread of a ``/device:*`` process (real TPU capture), else the XLA
    runtime execution threads of the host process (CPU smoke —
    ``tf_XLATfrtCpuClient*``; the llvm-codegen threads are COMPILE time
    and never count)."""
    dev_pids = {pid for pid, name in trace["processes"].items()
                if str(name).startswith("/device:")}
    lanes = {(ev["pid"], ev["tid"]) for ev in trace["events"]
             if ev["pid"] in dev_pids}
    if lanes:
        return sorted(lanes)
    for (pid, tid), tname in trace["threads"].items():
        n = str(tname)
        if n.startswith("tf_XLA") and "codegen" not in n.lower():
            lanes.add((pid, tid))
    return sorted(lanes)


def _union_ms(intervals: List[Tuple[float, float]]) -> float:
    """Total length (ms) of the union of [t0, t1) microsecond intervals
    — overlapping kernels on parallel lanes count once (wall busy time,
    the roofline's denominator), not summed."""
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    total = 0.0
    cur0, cur1 = intervals[0]
    for t0, t1 in intervals[1:]:
        if t0 > cur1:
            total += cur1 - cur0
            cur0, cur1 = t0, t1
        else:
            cur1 = max(cur1, t1)
    total += cur1 - cur0
    return total / 1e3


def attribute(trace: Dict[str, Any]) -> Dict[str, Any]:
    """Attribute device-lane kernel time to framework steps and op
    classes.  Returns per-step rows (measured device ms, idle fraction,
    per-class ms), window-level per-class totals/shares, and the
    per-kernel aggregation the divergence table ranks."""
    steps = step_intervals(trace)
    lanes = set(device_lanes(trace))
    kernels = [ev for ev in trace["events"]
               if (ev["pid"], ev["tid"]) in lanes and ev["dur"] > 0
               and not _INFRA_RX.search(ev["name"])]

    per_kernel: Dict[str, Dict[str, Any]] = {}
    per_class_ms: Dict[str, float] = {}
    step_rows: List[Dict[str, Any]] = []
    unattributed_ms = 0.0

    def _step_of(ev):
        mid = ev["ts"] + ev["dur"] / 2.0
        for s in steps:
            if s["ts"] <= mid < s["ts"] + s["dur"]:
                return s["step"]
        return None

    by_step: Dict[Optional[int], List[dict]] = {}
    for ev in kernels:
        cls = classify_kernel(ev["name"])
        ms = ev["dur"] / 1e3
        k = per_kernel.setdefault(
            ev["name"], {"name": ev["name"], "op_class": cls,
                         "ms": 0.0, "count": 0})
        k["ms"] += ms
        k["count"] += 1
        per_class_ms[cls] = per_class_ms.get(cls, 0.0) + ms
        sid = _step_of(ev)
        by_step.setdefault(sid, []).append(ev)
        if sid is None:
            unattributed_ms += ms

    for s in steps:
        evs = by_step.get(s["step"], [])
        busy = _union_ms([(max(e["ts"], s["ts"]),
                           min(e["ts"] + e["dur"], s["ts"] + s["dur"]))
                          for e in evs])
        span_ms = s["dur"] / 1e3
        cls_ms: Dict[str, float] = {}
        for e in evs:
            c = classify_kernel(e["name"])
            cls_ms[c] = cls_ms.get(c, 0.0) + e["dur"] / 1e3
        step_rows.append({
            "step": s["step"],
            "span_ms": round(span_ms, 6),
            "device_ms": round(busy, 6),
            "idle_frac": round(1.0 - busy / span_ms, 6)
            if span_ms > 0 else None,
            "per_class_ms": {c: round(v, 6)
                             for c, v in sorted(cls_ms.items())}})

    total_ms = sum(per_class_ms.values())
    share = {c: v / total_ms for c, v in per_class_ms.items()} \
        if total_ms > 0 else {}
    spans = [r["span_ms"] for r in step_rows if r["span_ms"] > 0]
    busy_in_steps = [r["device_ms"] for r in step_rows]
    idle = (1.0 - sum(busy_in_steps) / sum(spans)) if spans else None
    return {
        "steps": step_rows,
        "n_steps": len(step_rows),
        "per_class_ms": {c: round(v, 6)
                         for c, v in sorted(per_class_ms.items())},
        "per_class_share": {c: round(v, 6)
                            for c, v in sorted(share.items())},
        "device_ms_total": round(total_ms, 6),
        "unattributed_ms": round(unattributed_ms, 6),
        "idle_frac": round(idle, 6) if idle is not None else None,
        "kernels": sorted(
            ({**k, "ms": round(k["ms"], 6)} for k in per_kernel.values()),
            key=lambda k: -k["ms"]),
    }


# ---------------------------------------------------------------------------
# xplane.pb: dependency-free protobuf wire-format reader
# ---------------------------------------------------------------------------
# XSpace{1: planes} / XPlane{2: name, 3: lines, 4: event_metadata map
# {1: key, 2: XEventMetadata{1: id, 2: name}}} / XLine{1: id, 2: name,
# 3: timestamp_ns, 4: events} / XEvent{1: metadata_id, 2: offset_ps,
# 3: duration_ps}.  Verified against real jax.profiler captures; no
# TensorFlow import — the wire format is stable, generated protos are
# a dependency the container does not carry.

def _varint(b: bytes, i: int) -> Tuple[int, int]:
    r = s = 0
    while True:
        if i >= len(b):
            raise ValueError("truncated varint")
        x = b[i]
        i += 1
        r |= (x & 0x7F) << s
        if not x & 0x80:
            return r, i
        s += 7
        if s > 70:
            raise ValueError("varint overflow")


def _fields(b: bytes):
    """Yield (field_no, wire_type, value) over one message's bytes."""
    i, n = 0, len(b)
    while i < n:
        tag, i = _varint(b, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(b, i)
        elif wt == 2:
            ln, i = _varint(b, i)
            if i + ln > n:
                raise ValueError("truncated length-delimited field")
            v = b[i:i + ln]
            i += ln
        elif wt == 5:
            v, i = b[i:i + 4], i + 4
        elif wt == 1:
            v, i = b[i:i + 8], i + 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        if i > n:
            raise ValueError("truncated field")
        yield fn, wt, v


def read_xplane(path: str) -> Optional[List[Dict[str, Any]]]:
    """Parse an ``*.xplane.pb`` XSpace into
    ``[{"name", "lines": [{"name", "timestamp_ns", "events":
    [{"name", "offset_ps", "duration_ps"}]}]}]``.  Malformed/truncated
    input warns and returns None (post-close-hook discipline)."""
    try:
        with open(path, "rb") as f:
            data = f.read()
        planes = []
        for fn, wt, v in _fields(data):
            if fn != 1 or wt != 2:
                continue
            name, lines, emeta = "", [], {}
            for f2, w2, v2 in _fields(v):
                if f2 == 2 and w2 == 2:
                    name = v2.decode("utf-8", "replace")
                elif f2 == 3 and w2 == 2:
                    lines.append(v2)
                elif f2 == 4 and w2 == 2:
                    key = mname = mid = None
                    for f3, w3, v3 in _fields(v2):
                        if f3 == 1 and w3 == 0:
                            key = v3
                        elif f3 == 2 and w3 == 2:
                            for f4, w4, v4 in _fields(v3):
                                if f4 == 1 and w4 == 0:
                                    mid = v4
                                elif f4 == 2 and w4 == 2:
                                    mname = v4.decode("utf-8", "replace")
                    k = key if key is not None else mid
                    if k is not None and mname is not None:
                        emeta[k] = mname
            out_lines = []
            for ln in lines:
                lname, ts_ns, evs = "", 0, []
                for f3, w3, v3 in _fields(ln):
                    if f3 == 2 and w3 == 2:
                        lname = v3.decode("utf-8", "replace")
                    elif f3 == 3 and w3 == 0:
                        ts_ns = v3
                    elif f3 == 4 and w3 == 2:
                        mid = off = dur = 0
                        for f4, w4, v4 in _fields(v3):
                            if w4 != 0:
                                continue
                            if f4 == 1:
                                mid = v4
                            elif f4 == 2:
                                off = v4
                            elif f4 == 3:
                                dur = v4
                        evs.append({"name": emeta.get(mid, f"#{mid}"),
                                    "offset_ps": off, "duration_ps": dur})
                out_lines.append({"name": lname, "timestamp_ns": ts_ns,
                                  "events": evs})
            planes.append({"name": name, "lines": out_lines})
        return planes
    except (OSError, ValueError, IndexError) as e:
        warnings.warn(f"device_profile: unreadable xplane {path!r}: {e!r}")
        return None


def xplane_kernel_ms(path: str) -> Optional[Dict[str, float]]:
    """Per-kernel total durations (ms) from the DEVICE planes of one
    xplane.pb (``/device:*``; infrastructure spans filtered the same way
    as the JSON-trace path).  None when no device plane exists or the
    file is malformed — the trace.json.gz attribution then stands
    alone."""
    planes = read_xplane(path)
    if planes is None:
        return None

    def _lane_events(device_only):
        for plane in planes:
            pname = str(plane["name"])
            if device_only:
                if not pname.startswith("/device:"):
                    continue
                for line in plane["lines"]:
                    yield from line["events"]
            else:
                # CPU capture: the XLA client execution lines of the
                # host plane (codegen lines are compile time)
                for line in plane["lines"]:
                    lname = str(line["name"])
                    if lname.startswith("tf_XLA") and \
                            "codegen" not in lname.lower():
                        yield from line["events"]

    out: Dict[str, float] = {}
    for device_only in (True, False):
        for ev in _lane_events(device_only):
            if _INFRA_RX.search(ev["name"]):
                continue
            out[ev["name"]] = out.get(ev["name"], 0.0) + \
                ev["duration_ps"] / 1e9
        if out:
            break
    return {k: round(v, 6) for k, v in out.items()} if out else None


# ---------------------------------------------------------------------------
# window summary: the persisted objective oracle
# ---------------------------------------------------------------------------

#: analytic cost-model classes folded into the measured buckets for the
#: divergence table (norm/softmax/reduce/optimizer are VPU work a fused
#: device kernel bills as elementwise time)
_ANALYTIC_TO_MEASURED = {
    "matmul": "matmul", "conv": "conv", "attention": "attention",
    "embedding": "embedding",
}


def latest_profile_run(window_dir: str) -> Optional[str]:
    """Newest ``plugins/profile/<run>/`` under a capture window (a
    re-used window dir holds one run per capture; run names are
    timestamps, so lexical order is capture order)."""
    runs = sorted(glob.glob(os.path.join(
        window_dir, "plugins", "profile", "*")))
    runs = [r for r in runs if os.path.isdir(r)]
    return runs[-1] if runs else None


def summarize_window(window_dir: str,
                     flops_per_step: Optional[float] = None,
                     peak_flops: Optional[float] = None,
                     analytic_share: Optional[Dict[str, float]] = None,
                     ) -> Optional[Dict[str, Any]]:
    """Parse one captured window into the summary dict (the schema
    ``<window>/summary.json`` persists).  ``flops_per_step`` /
    ``peak_flops`` enable measured MFU; ``analytic_share`` (the
    ``paddle_tpu_step_flops_share`` per-class flop shares) enables the
    measured-vs-analytic divergence table and the per-kernel
    wasted-roofline-headroom ranking.  Warns and returns None when the
    window holds no parseable capture — never raises."""
    run = latest_profile_run(window_dir)
    if run is None:
        warnings.warn(
            f"device_profile: no plugins/profile run under {window_dir!r}")
        return None
    traces = sorted(glob.glob(os.path.join(run, "*.trace.json.gz"))) + \
        sorted(glob.glob(os.path.join(run, "*.trace.json")))
    trace = None
    trace_path = None
    for cand in traces:
        trace = parse_trace(cand)
        if trace is not None:
            trace_path = cand
            break
    if trace is None:
        warnings.warn(
            f"device_profile: no parseable trace under {run!r}")
        return None
    summary: Dict[str, Any] = {
        "window": window_dir,
        "profile_run": run,
        "trace": os.path.basename(trace_path),
        **attribute(trace),
    }
    for xp in sorted(glob.glob(os.path.join(run, "*.xplane.pb"))):
        km = xplane_kernel_ms(xp)
        if km:
            summary["xplane_kernel_ms"] = km
            summary["xplane"] = os.path.basename(xp)
            break

    # measured MFU: analytic flops/step over measured device-busy time
    # per step x peak.  Steps with zero measured device time drop out
    # (a window tail can clip a step's kernels).
    busy = [r["device_ms"] for r in summary["steps"]
            if r["device_ms"] > 0]
    mfu_measured = None
    if busy and flops_per_step and peak_flops:
        mean_busy_s = sum(busy) / len(busy) / 1e3
        mfu_measured = flops_per_step / mean_busy_s / peak_flops
    spans = [r["span_ms"] for r in summary["steps"] if r["span_ms"] > 0]
    mfu_analytic = None
    if spans and flops_per_step and peak_flops:
        mfu_analytic = flops_per_step / (sum(spans) / len(spans) / 1e3) \
            / peak_flops
    summary["measured"] = {
        "flops_per_step": flops_per_step,
        "peak_flops": peak_flops,
        "mfu_measured": round(mfu_measured, 6)
        if mfu_measured is not None else None,
        "mfu_analytic_over_span": round(mfu_analytic, 6)
        if mfu_analytic is not None else None,
    }

    if analytic_share:
        summary["divergence"] = _divergence(
            summary, analytic_share, flops_per_step, peak_flops)
    return summary


def _divergence(summary: Dict[str, Any],
                analytic_share: Dict[str, float],
                flops_per_step: Optional[float],
                peak_flops: Optional[float]) -> Dict[str, Any]:
    """Measured-vs-analytic attribution: per-class time share against
    flop share (a class burning far more time than its flop share is
    memory/latency-bound — the fusion arc's candidate list), and the
    per-kernel wasted-roofline-headroom ranking (measured ms minus the
    roofline-minimum ms for the flops the class attributes to it) — the
    autotune search's objective, largest headroom first."""
    folded: Dict[str, float] = {}
    for cls, share in analytic_share.items():
        m = _ANALYTIC_TO_MEASURED.get(cls, "elementwise")
        folded[m] = folded.get(m, 0.0) + float(share)
    measured_share = summary.get("per_class_share", {})
    classes = sorted(set(folded) | set(measured_share))
    table = [{
        "op_class": c,
        "measured_time_share": round(measured_share.get(c, 0.0), 6),
        "analytic_flop_share": round(folded.get(c, 0.0), 6),
        "time_over_flop_ratio": round(
            measured_share.get(c, 0.0) / folded[c], 4)
        if folded.get(c, 0.0) > 0 else None,
    } for c in classes]

    ranking: List[Dict[str, Any]] = []
    n_steps = max(summary.get("n_steps") or 0, 1)
    per_class_ms = summary.get("per_class_ms", {})
    if flops_per_step and peak_flops:
        for k in summary.get("kernels", []):
            cls_ms = per_class_ms.get(k["op_class"], 0.0)
            # window-total class flops (per-step x steps): kernel ms
            # totals span the whole window, so the proportional split
            # below needs both sides on the same window-total basis
            cls_flops = flops_per_step * n_steps * \
                folded.get(k["op_class"], 0.0)
            # class flops attribute to kernels proportionally by time —
            # honest without per-kernel flop counts, and exact when a
            # class is one kernel
            est_flops = cls_flops * (k["ms"] / cls_ms) if cls_ms > 0 \
                else 0.0
            ms_per_step = k["ms"] / n_steps
            ideal_ms = est_flops / n_steps / peak_flops * 1e3
            ranking.append({
                "kernel": k["name"], "op_class": k["op_class"],
                "ms_per_step": round(ms_per_step, 6),
                "est_flops_per_step": round(est_flops / n_steps, 3),
                "roofline_min_ms": round(ideal_ms, 6),
                "wasted_ms": round(ms_per_step - ideal_ms, 6),
            })
        ranking.sort(key=lambda r: -r["wasted_ms"])
    return {"per_class": table, "wasted_headroom": ranking}


def write_summary(window_dir: str, summary: Dict[str, Any]) -> str:
    """Persist ``<window>/summary.json`` atomically (same tmp+replace
    discipline as the manifest — a concurrent reader never sees a torn
    file)."""
    path = os.path.join(window_dir, "summary.json")
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(summary, f, indent=1, sort_keys=False)
    os.replace(tmp, path)
    return path


def _live_analytic() -> Tuple[Optional[float], Optional[float],
                              Dict[str, float]]:
    """(flops/step, peak flops, per-class flop share) from the live
    gauges the executor publishes at compile time — the denominators
    the post-close hook joins to the freshly captured window."""
    flops = peak = None
    fam = _monitor.REGISTRY.get("paddle_tpu_analytic_step_flops")
    if fam is not None:
        v = fam.value()
        if v:
            flops = float(v)
    try:
        from .cost import device_peak_flops
        peak = float(device_peak_flops())
    except Exception:
        peak = None
    share: Dict[str, float] = {}
    sfam = _monitor.REGISTRY.get("paddle_tpu_step_flops_share")
    if sfam is not None:
        for labels, cell in sfam.series():
            c = labels.get("op_class")
            if c:
                share[c] = float(cell.get())
    return flops, peak, share


def summarize_and_publish(window_dir: str) -> Optional[str]:
    """The SamplingProfiler post-close hook: parse the just-closed
    window, persist ``summary.json`` (the autotune search's objective
    oracle), and publish the measured gauges —
    ``paddle_tpu_step_mfu_measured``, idle fraction, per-class measured
    device-time shares (the ``mfu_m`` gang-digest key reads the first).
    Returns the summary path, or None (warn + skip) on any failure —
    this path must NEVER fail the training step."""
    global last_publish_wall
    try:
        flops, peak, share = _live_analytic()
        summary = summarize_window(window_dir, flops_per_step=flops,
                                   peak_flops=peak,
                                   analytic_share=share or None)
        if summary is None:
            _SUMMARY_CTR.inc(1, outcome="empty")
            return None
        path = write_summary(window_dir, summary)
        mfu = summary["measured"]["mfu_measured"]
        if mfu is not None:
            MFU_MEASURED_GAUGE.set(float(mfu))
        if summary["idle_frac"] is not None:
            IDLE_FRAC_GAUGE.set(float(summary["idle_frac"]))
        # stale classes zero out: the gauge reflects THIS window only
        for labels, cell in DEVICE_SHARE_GAUGE.series():
            cell.set(0.0)
        for c, v in summary["per_class_share"].items():
            DEVICE_SHARE_GAUGE.set(float(v), op_class=c)
        last_publish_wall = time.time()
        _SUMMARY_CTR.inc(1, outcome="ok")
        if _monitor.TRACER.enabled:
            _monitor.TRACER.instant(
                "profile.window_summary", "profile",
                {"window": window_dir, "mfu_measured": mfu,
                 "idle_frac": summary["idle_frac"],
                 "n_steps": summary["n_steps"]})
        return path
    except Exception as e:       # never fail the step/close path
        _SUMMARY_CTR.inc(1, outcome="error")
        warnings.warn(
            f"device_profile: window summary failed for "
            f"{window_dir!r}: {e!r}")
        return None
