"""Cost-guided, training-safe graph fusion over the ``framework.ir`` Graph.

The reference repo's fusion passes (``ir/conv_bn_fuse_pass.cc``,
``ir/fc_fuse_pass.cc``, ...) fire unconditionally on any structural
match; TVM (arxiv 1802.04799) showed cost-driven candidate selection
beats fixed rewrite rules, and Tensor Processing Primitives
(arxiv 2104.05755) motivates the fused micro-kernel target shape the
``paddle_tpu.pallas`` library provides.  This pass combines the three
ideas into the PR-5 pass-before-lowering slot:

1. **Match** candidate subgraphs with the existing
   ``PDPattern``/``GraphPatternDetector`` machinery:

   ======================  =================================  ==========
   pattern                 subgraph                           fused op
   ======================  =================================  ==========
   conv_bn_relu            conv2d(1x1) + batch_norm(train)    fused_conv1x1_bn
                           [+ relu]
   dense_epilogue          mul/matmul + bias add +            fused_dense_act
                           gelu/relu [+ tagged dropout]
   embedding_layer_norm    lookup_table [+ adds] +            fused_embedding_
                           layer_norm                         layer_norm
   ======================  =================================  ==========

2. **Prove each match legal for training** with a static analysis —
   every internal var must be single-consumer, non-fetched,
   non-persistable, not referenced by a control-flow sub-block
   (the dead-op liveness preconditions), and alias/donation-safe per
   the memory planner's inplace-pair interval model; in a program
   containing grad ops, the forward rewrite must come with a complete
   matching grad-op rewrite (the backward chain is located, checked
   single-consumer, and replaced by the fused op's generic-vjp grad) or
   the candidate is REJECTED.  Rejections carry the failing rule and
   are reported through ``debugger.format_diagnostics``.

3. **Rank survivors by the PR-8 cost model's per-class roofline
   shares** (``analysis.cost.CostPlan.share``): a candidate whose op
   class is below ``FLAGS_fusion_rank_threshold`` of the step's
   flop+byte budget is not worth a rewrite ("ranked_out").

4. **Autotune** (``FLAGS_fusion_autotune``): a fingerprint+shape-keyed
   cached micro-benchmark lowers the matched chain and the fused op
   side by side (both jitted) and applies the rewrite only when the
   fused kernel measurably beats the XLA default; verdicts persist next
   to the XLA compile cache (``<FLAGS_xla_compile_cache_dir>/
   fusion_autotune.json``), so a process restart re-decides nothing.
   With autotune OFF (the default) the pass applies on static legality
   + rank alone.

Safety rails: the verifier runs before and after the pass, the
collective fingerprint must be UNCHANGED by fusion (fusion never
touches collectives — a changed fingerprint rolls the rewrite back),
``_attrs["verify"]`` rides the rewritten program, and every decision is
counted in ``paddle_tpu_fusion_candidates_total{pattern,verdict}``.
``FLAGS_graph_fusion`` (default on) is the master gate; the executor
and ``compiler.optimize`` key their caches on :func:`config_token`, so
flipping any fusion flag invalidates stale plans.
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import monitor as _monitor
from ..framework.core import Block, Program

__all__ = [
    "FusionDecision", "FusionReport", "analyze_program", "clear_cache",
    "config_token", "fuse_program",
]

_CAND_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_fusion_candidates_total",
    "graph-fusion candidate decisions by pattern and verdict "
    "(applied / rejected / ranked_out / autotune_lost / overlapped / "
    "verify_failed)", ("pattern", "verdict"))
_AUTOTUNE_CTR = _monitor.REGISTRY.counter(
    "paddle_tpu_fusion_autotune_total",
    "fusion autotune micro-benchmark lookups by cache outcome",
    ("cache",))
_AUTOTUNE_HIT = _AUTOTUNE_CTR.labels(cache="hit")
_AUTOTUNE_MISS = _AUTOTUNE_CTR.labels(cache="miss")

#: collective op prefixes fusion must never touch (the fingerprint
#: invariance check backstops this structurally)
_COLLECTIVE_PREFIX = "c_"

#: activations the dense epilogue folds
_DENSE_ACTS = ("gelu", "relu")


def config_token() -> tuple:
    """The fusion configuration visible to cache keys: executor dispatch
    plans and ``compiler.optimize`` results keyed on this token are
    invalidated by any fusion-flag change."""
    from ..flags import get_flags
    fl = get_flags(["FLAGS_graph_fusion", "FLAGS_fusion_autotune",
                    "FLAGS_fusion_rank_threshold"])
    return (bool(fl["FLAGS_graph_fusion"]),
            bool(fl["FLAGS_fusion_autotune"]),
            float(fl["FLAGS_fusion_rank_threshold"]))


@dataclass
class FusionDecision:
    """One candidate's fate, machine-readable for tools/analyze.py and
    the bench fusion line."""

    pattern: str
    anchor: str                 # the chain's output var (display name)
    verdict: str                # applied|rejected|ranked_out|...
    rule: Optional[str] = None  # failing legality rule for 'rejected'
    rank: float = 0.0           # per-class roofline share in [0, 1]
    autotune: Optional[dict] = None   # {fused_ms, base_ms, cached}

    def as_dict(self) -> dict:
        out = {"pattern": self.pattern, "anchor": self.anchor,
               "verdict": self.verdict, "rank": round(self.rank, 4)}
        if self.rule:
            out["rule"] = self.rule
        if self.autotune:
            out["autotune"] = dict(self.autotune)
        return out


@dataclass
class FusionReport:
    decisions: List[FusionDecision] = field(default_factory=list)
    applied: int = 0
    collective_fingerprint_ok: bool = True

    def by_verdict(self, verdict: str) -> List[FusionDecision]:
        return [d for d in self.decisions if d.verdict == verdict]

    def as_dict(self) -> dict:
        return {"applied": self.applied,
                "collective_fingerprint_ok":
                    self.collective_fingerprint_ok,
                "candidates": [d.as_dict() for d in self.decisions]}


# ---------------------------------------------------------------------------
# candidate model
# ---------------------------------------------------------------------------

class _Candidate:
    """One matched subgraph plus everything needed to judge and apply it.

    ``fwd_ops``/``grad_ops`` are the op Nodes the rewrite removes;
    ``internal`` the var Nodes that disappear (their consumers must all
    be inside the candidate); ``build(graph)`` applies the forward AND
    grad rewrite; ``base_descs``/``fused_descs`` are
    (type, inputs, outputs, attrs) op descs the autotuner replays;
    ``ext_inputs`` maps external input names to (shape, dtype)."""

    def __init__(self, pattern: str, op_class: str, anchor: str):
        self.pattern = pattern
        self.op_class = op_class
        self.anchor = anchor
        self.fwd_ops: List = []
        self.grad_ops: List = []
        self.internal: List = []
        self.dead_outputs: List = []    # side-output var nodes that die
        self.reject_rule: Optional[str] = None   # structural pre-reject
        self.build = None               # set by the matcher when legal
        self.base_descs: List[tuple] = []
        self.fused_descs: List[tuple] = []
        self.ext_inputs: Dict[str, tuple] = {}
        self.shape_key: tuple = ()

    def all_ops(self) -> List:
        return self.fwd_ops + self.grad_ops


def _desc(op) -> tuple:
    """Autotune replay desc of one Operator."""
    return (op.type,
            {s: list(n) for s, n in op.inputs.items()},
            {s: list(n) for s, n in op.outputs.items()},
            {k: v for k, v in op.attrs.items()})


def _has_grad_ops(program: Program) -> bool:
    return any(op.type.endswith("_grad")
               for op in program.global_block().ops)


def _node_by_name(op_node, name):
    return next((v for v in op_node.inputs if v.name == name), None)


def _fwd_consumers(var_node):
    """A var's FORWARD consumers: grad ops re-read forward intermediates
    (``X$<slot>`` replay inputs), so a match's exclusive-consumer checks
    must not count them — legality separately proves every grad-side
    consumer belongs to the candidate's own grad chain."""
    return [c for c in var_node.outputs
            if not c.name.endswith("_grad")]


def _out_node_by_name(op_node, name):
    return next((v for v in op_node.outputs if v.name == name), None)


def _grad_consumer(graph, grad_name: str, type_: str, slot: str):
    """The op node of ``type_`` whose ``slot`` input is ``grad_name`` —
    how the backward chain is walked (grad var names are plain
    ``<var>@GRAND`` only for single-consumer vars, which legality
    requires anyway)."""
    for n in graph.op_nodes:
        if n.name != type_:
            continue
        names = n.op.input(slot)
        if names and names[0] == grad_name:
            return n
    return None


# ---------------------------------------------------------------------------
# pattern matchers
# ---------------------------------------------------------------------------

def _match_conv_bn_relu(graph, program, fetch_names) -> List[_Candidate]:
    """conv2d + batch_norm(train) [+ relu] → ``fused_conv1x1_bn``.

    The structural spine matches via PDPattern; kernel-shape limits of
    the Pallas target (1x1, stride-square, no pad/dilation/groups,
    NCHW, bias-free) are LEGALITY rules so near-misses surface in the
    report instead of silently not matching."""
    from ..framework import ir

    pat = ir.PDPattern()
    conv = pat.new_op("conv2d")
    conv_out = pat.new_var("conv_out").as_intermediate()
    bn = pat.new_op("batch_norm")
    pat.link(conv, conv_out)
    pat.link(conv_out, bn)
    cands = []
    for m in ir.GraphPatternDetector(pat)(graph):
        conv_n, bn_n, cout_n = m[conv], m[bn], m[conv_out]
        y_node = next((v for v in bn_n.outputs
                       if v.name in bn_n.op.output("Y")), None)
        if y_node is None:
            continue
        cand = _Candidate("conv_bn_relu", "conv",
                          anchor=y_node.name)
        cand.fwd_ops = [conv_n, bn_n]
        cand.internal = [cout_n]
        a = bn_n.op.attrs
        ca = conv_n.op.attrs
        strides = ca.get("strides", [1, 1])
        w_node = ir._input_node(conv_n, "Filter")
        x_node = ir._input_node(conv_n, "Input")
        wshape = getattr(getattr(w_node, "var", None), "shape", None) \
            if w_node is not None else None
        # structural legality of the Pallas target
        if a.get("is_test") or a.get("use_global_stats") or \
                a.get("data_layout", "NCHW") != "NCHW":
            cand.reject_rule = "bn_mode_unsupported"
        elif ca.get("groups", 1) != 1 or \
                any(p != 0 for p in ca.get("paddings", [0, 0])) or \
                any(d != 1 for d in ca.get("dilations", [1, 1])) or \
                strides[0] != strides[1] or conv_n.op.input("Bias"):
            cand.reject_rule = "kernel_unsupported"
        elif not wshape or len(wshape) != 4 or wshape[2] != 1 or \
                wshape[3] != 1:
            cand.reject_rule = "kernel_unsupported"
        elif w_node is None or x_node is None:
            cand.reject_rule = "kernel_unsupported"
        cands.append(cand)
        if cand.reject_rule:
            continue
        # optional exclusive relu tail folds into the fused act
        out_node, relu_n = y_node, None
        y_fwd = _fwd_consumers(y_node)
        if len(y_fwd) == 1 and y_fwd[0].is_op("relu") \
                and y_node.name not in fetch_names:
            relu_n = y_fwd[0]
            cand.fwd_ops.append(relu_n)
            cand.internal.append(y_node)
            out_node = relu_n.outputs[0]
        cand.anchor = out_node.name
        by_name = {v.name: v for v in bn_n.inputs}

        def bn_in(slot):
            names = bn_n.op.input(slot)
            return by_name.get(names[0]) if names else None

        scale_n, bias_n = bn_in("Scale"), bn_in("Bias")
        mean_n, var_n = bn_in("Mean"), bn_in("Variance")
        if None in (scale_n, bias_n, mean_n, var_n):
            cand.reject_rule = "kernel_unsupported"
            continue
        fused_attrs = {"momentum": a.get("momentum", 0.9),
                       "epsilon": a.get("epsilon", 1e-5),
                       "act": "relu" if relu_n is not None else "",
                       "stride": int(strides[0]),
                       "is_test": False, "use_global_stats": False}
        outs = {"Y": [out_node]}
        for slot in ("MeanOut", "VarianceOut", "SavedMean",
                     "SavedVariance"):
            names = bn_n.op.output(slot)
            node = next((v for v in bn_n.outputs if names and
                         v.name in names), None)
            if node is not None:
                outs[slot] = [node]
        ins = {"X": [x_node], "Filter": [w_node], "Scale": [scale_n],
               "Bias": [bias_n], "Mean": [mean_n], "Variance": [var_n]}

        _finish_candidate(
            graph, program, cand,
            fused_type="fused_conv1x1_bn",
            fused_ins=ins, fused_outs=outs, fused_attrs=fused_attrs,
            out_node=out_node, og_slot_name="Y",
            grad_chain=_conv_bn_grad_chain(graph, cand, conv_n, bn_n,
                                           relu_n, out_node),
            grad_ig={"X": ("conv2d_grad", "IG$Input"),
                     "Filter": ("conv2d_grad", "IG$Filter"),
                     "Scale": ("batch_norm_explicit_grad", "IG$Scale"),
                     "Bias": ("batch_norm_explicit_grad", "IG$Bias")})
    return cands


def _conv_bn_grad_chain(graph, cand, conv_n, bn_n, relu_n, out_node):
    """Locate the relu_grad → batch_norm_explicit_grad → conv2d_grad
    chain for one matched forward, or None when absent/ineligible."""
    chain = []
    g = out_node.name + "@GRAD"
    if relu_n is not None:
        rg = _grad_consumer(graph, g, "relu_grad", "OG$Out")
        if rg is None or rg.op.attrs.get("__fwd_type__") != "relu":
            return None
        chain.append(rg)
        igx = rg.op.output("IG$X")
        if not igx or not igx[0]:
            return None
        g = igx[0]
    bg = _grad_consumer(graph, g, "batch_norm_explicit_grad", "OG$Y")
    if bg is None:
        return None
    chain.append(bg)
    igx = bg.op.output("IG$X")
    if not igx or not igx[0]:
        return None
    cg = _grad_consumer(graph, igx[0], "conv2d_grad", "OG$Output")
    if cg is None or cg.op.attrs.get("__fwd_type__") != "conv2d":
        return None
    chain.append(cg)
    return chain


def _match_dense_epilogue(graph, program, fetch_names) -> List[_Candidate]:
    """mul/matmul + elementwise_add(bias) + gelu/relu [+ tagged dropout]
    → ``fused_dense_act``."""
    from ..framework import ir

    cands = []
    for mm_type in ("mul", "matmul"):
        pat = ir.PDPattern()
        mm = pat.new_op(mm_type)
        mm_out = pat.new_var("mm_out").as_intermediate()
        add = pat.new_op("elementwise_add")
        bias = pat.new_var("bias", persistable=True)
        add_out = pat.new_var("add_out").as_intermediate()
        pat.link(mm, mm_out)
        pat.link(mm_out, add)
        pat.link(bias, add)
        pat.link(add, add_out)
        for m in ir.GraphPatternDetector(pat)(graph):
            mm_n, add_n = m[mm], m[add]
            mm_out_n, add_out_n, bias_n = m[mm_out], m[add_out], m[bias]
            # the detector links by edges only: confirm the bias var is
            # the add's Y slot (not its X), and the mm output its X
            if not add_n.op.input("Y") or \
                    add_n.op.input("Y")[0] != bias_n.name or \
                    add_n.op.input("X")[0] != mm_out_n.name:
                continue
            add_fwd = _fwd_consumers(add_out_n)
            if len(add_fwd) != 1 or add_fwd[0].name not in _DENSE_ACTS:
                continue
            act_n = add_fwd[0]
            act_out = act_n.outputs[0]
            cand = _Candidate("dense_epilogue", "matmul",
                              anchor=act_out.name)
            cand.fwd_ops = [mm_n, add_n, act_n]
            cand.internal = [mm_out_n, add_out_n]
            x_node = _node_by_name(mm_n, mm_n.op.input("X")[0])
            w_node = _node_by_name(mm_n, mm_n.op.input("Y")[0])
            if x_node is None or w_node is None or \
                    not w_node.persistable:
                cand.reject_rule = "kernel_unsupported"
                cands.append(cand)
                continue
            ma = mm_n.op.attrs
            if mm_type == "matmul" and (
                    ma.get("transpose_X") or ma.get("transpose_Y") or
                    ma.get("alpha", 1.0) != 1.0):
                cand.reject_rule = "kernel_unsupported"
                cands.append(cand)
                continue
            if mm_type == "mul" and \
                    int(ma.get("y_num_col_dims", 1)) != 1:
                # the fused lowering reshapes W at y_num_col_dims=1
                cand.reject_rule = "kernel_unsupported"
                cands.append(cand)
                continue
            bshape = getattr(getattr(bias_n, "var", None), "shape", None)
            wshape = getattr(getattr(w_node, "var", None), "shape", None)
            if not bshape or len(bshape) != 1 or \
                    not wshape or len(wshape) != 2:
                # the fused lowering is the 2-D-weight [K, N] form with
                # a per-feature bias; anything else is a different op
                cand.reject_rule = "kernel_unsupported"
                cands.append(cand)
                continue
            # the fused lowering broadcasts the bias over the LAST
            # (feature) dim of the 2-D flattened matmul: the add's axis
            # must resolve to the output's last dim and the bias length
            # must be the matmul's N, or the composition is not the
            # same computation
            out_rank = (int(ma.get("x_num_col_dims", 1)) + 1
                        if mm_type == "mul"
                        else len(getattr(getattr(x_node, "var", None),
                                         "shape", None) or ()) or None)
            axis = int(add_n.op.attrs.get("axis", -1))
            if out_rank is None or (axis != -1 and axis != out_rank - 1):
                cand.reject_rule = "kernel_unsupported"
                cands.append(cand)
                continue
            if wshape and bshape[0] not in (-1, None) and \
                    wshape[-1] not in (-1, None) and \
                    bshape[0] != wshape[-1]:
                cand.reject_rule = "kernel_unsupported"
                cands.append(cand)
                continue
            out_node = act_out
            drop_n = None
            # optional exclusive TAGGED dropout tail: the tag makes the
            # fused op regenerate the identical mask (rng is a pure
            # function of step seed + tag), keeping fused-vs-unfused
            # loss parity exact; an untagged dropout stores its mask
            # and cannot be replayed — stays unfused
            act_fwd = _fwd_consumers(act_out)
            if len(act_fwd) == 1 and act_fwd[0].is_op("dropout") and \
                    act_out.name not in fetch_names:
                dn = act_fwd[0]
                if dn.op.attrs.get("seed", 0):
                    drop_n = dn
                    cand.fwd_ops.append(drop_n)
                    cand.internal.append(act_out)
                    out_node = next(
                        (v for v in drop_n.outputs
                         if v.name in drop_n.op.output("Out")), None)
                    mask = next(
                        (v for v in drop_n.outputs
                         if v.name in drop_n.op.output("Mask")), None)
                    if out_node is None:
                        cand.reject_rule = "kernel_unsupported"
                        cands.append(cand)
                        continue
                    if mask is not None:
                        cand.dead_outputs.append(mask)
            cand.anchor = out_node.name
            fused_attrs = {
                "x_num_col_dims": int(ma.get("x_num_col_dims", 1))
                if mm_type == "mul" else -1,
                "bias_axis": int(add_n.op.attrs.get("axis", -1)),
                "act": act_n.name,
                "approximate": bool(
                    act_n.op.attrs.get("approximate", False)),
                "dropout_prob": float(
                    drop_n.op.attrs.get("dropout_prob", 0.0))
                if drop_n is not None else 0.0,
                "seed": int(drop_n.op.attrs.get("seed", 0))
                if drop_n is not None else 0,
                "is_test": bool(drop_n.op.attrs.get("is_test", False))
                if drop_n is not None else False,
                "dropout_implementation":
                    str(drop_n.op.attrs.get("dropout_implementation",
                                            "downgrade_in_infer"))
                if drop_n is not None else "downgrade_in_infer",
                "use_pallas": False,
            }
            grad_chain = _dense_grad_chain(graph, mm_type, out_node,
                                           drop_n, act_n)
            _finish_candidate(
                graph, program, cand,
                fused_type="fused_dense_act",
                fused_ins={"X": [x_node], "W": [w_node],
                           "Bias": [bias_n]},
                fused_outs={"Out": [out_node]},
                fused_attrs=fused_attrs,
                out_node=out_node, og_slot_name="Out",
                grad_chain=grad_chain,
                grad_ig={"X": (mm_type + "_grad", "IG$X"),
                         "W": (mm_type + "_grad", "IG$Y"),
                         "Bias": ("elementwise_add_grad", "IG$Y")})
            cands.append(cand)
    return cands


def _dense_grad_chain(graph, mm_type, out_node, drop_n, act_n):
    chain = []
    g = out_node.name + "@GRAD"
    if drop_n is not None:
        dg = _grad_consumer(graph, g, "dropout_grad", "OutGrad")
        if dg is None or dg.op.input("Mask"):
            return None         # untagged dropout replays via its mask
        chain.append(dg)
        xg = dg.op.output("XGrad")
        if not xg or not xg[0]:
            return None
        g = xg[0]
    ag_t = act_n.name + "_grad"
    actg = _grad_consumer(graph, g, ag_t, "OG$Out")
    if actg is None or actg.op.attrs.get("__fwd_type__") != act_n.name:
        return None
    chain.append(actg)
    igx = actg.op.output("IG$X")
    if not igx or not igx[0]:
        return None
    addg = _grad_consumer(graph, igx[0], "elementwise_add_grad",
                          "OG$Out")
    if addg is None or \
            addg.op.attrs.get("__fwd_type__") != "elementwise_add":
        return None
    chain.append(addg)
    igx = addg.op.output("IG$X")
    if not igx or not igx[0]:
        return None
    mmg = _grad_consumer(graph, igx[0], mm_type + "_grad", "OG$Out")
    if mmg is None or mmg.op.attrs.get("__fwd_type__") != mm_type:
        return None
    chain.append(mmg)
    return chain


def _match_embedding_layer_norm(graph, program,
                                fetch_names) -> List[_Candidate]:
    """lookup_table [+ elementwise_adds] + layer_norm →
    ``fused_embedding_layer_norm``.

    The BERT-shaped chain is ``emb + pos [+ sent] -> layer_norm``; the
    fused op gathers the rows, applies the adds, and normalizes in one
    op (the Pallas fused LN backward becomes reachable via autotune).
    The chain side must be each add's X slot with default axis, and
    every collapsed intermediate is legality-checked like any other
    internal var."""
    from ..framework import ir

    cands = []
    for ln_n in graph.ops_of_type("layer_norm"):
        x_in = ir._input_node(ln_n, "X")
        if x_in is None:
            continue
        # walk the producer chain: up to 2 adds over the lookup output
        chain_ops: List = []          # adds, outermost first
        addends: List = []            # external addend var nodes
        internal: List = []
        cur = x_in
        lt_n = None
        for _ in range(3):
            if not cur.inputs:
                break
            p = cur.inputs[0]
            if p.is_op(("lookup_table", "lookup_table_v2")):
                lt_n = p
                internal.append(cur)
                break
            if p.is_op("elementwise_add") and \
                    int(p.op.attrs.get("axis", -1)) == -1:
                xn = _node_by_name(p, p.op.input("X")[0])
                yn = _node_by_name(p, p.op.input("Y")[0])
                if xn is None or yn is None:
                    break
                chain_ops.append(p)
                addends.append(yn)
                internal.append(cur)
                cur = xn
                continue
            break
        if lt_n is None:
            continue
        chain_ops.reverse()
        addends.reverse()
        cand = _Candidate("embedding_layer_norm", "embedding",
                          anchor="")
        y_node = next((v for v in ln_n.outputs
                       if v.name in ln_n.op.output("Y")), None)
        if y_node is None:
            continue
        cand.anchor = y_node.name
        cand.fwd_ops = [lt_n] + chain_ops + [ln_n]
        cand.internal = list(internal)
        ids_n = ir._input_node(lt_n, "Ids")
        w_node = ir._input_node(lt_n, "W")
        scale_n = ir._input_node(ln_n, "Scale")
        bias_n = ir._input_node(ln_n, "Bias")
        la = lt_n.op.attrs
        if ids_n is None or w_node is None or not w_node.persistable:
            cand.reject_rule = "kernel_unsupported"
            cands.append(cand)
            continue
        if la.get("is_sparse") or la.get("is_distributed"):
            # sparse/PS tables lower through the parameter-server path;
            # a fused dense gather would change the distribution story
            cand.reject_rule = "distributed_table"
            cands.append(cand)
            continue
        fused_attrs = {
            "padding_idx": la.get("padding_idx", -1),
            "epsilon": ln_n.op.attrs.get("epsilon", 1e-5),
            "begin_norm_axis": ln_n.op.attrs.get("begin_norm_axis", 1),
            "use_pallas": False,
        }
        ins = {"Ids": [ids_n], "W": [w_node], "Addends": list(addends)}
        if scale_n is not None:
            ins["Scale"] = [scale_n]
        if bias_n is not None:
            ins["Bias"] = [bias_n]
        outs = {"Out": [y_node]}
        for slot in ("Mean", "Variance"):
            names = ln_n.op.output(slot)
            node = next((v for v in ln_n.outputs
                         if names and v.name in names), None)
            if node is not None:
                outs[slot] = [node]
        grad = _embedding_ln_grad_chain(graph, y_node, ln_n, chain_ops,
                                        lt_n)
        grad_ig = {"W": (lt_n.name + "_grad", "IG$W")}
        if scale_n is not None:
            grad_ig["Scale"] = ("layer_norm_grad", "IG$Scale")
        if bias_n is not None:
            grad_ig["Bias"] = ("layer_norm_grad", "IG$Bias")
        _finish_candidate(
            graph, program, cand,
            fused_type="fused_embedding_layer_norm",
            fused_ins=ins, fused_outs=outs, fused_attrs=fused_attrs,
            out_node=y_node, og_slot_name="Out",
            grad_chain=grad, grad_ig=grad_ig,
            addend_grads=grad[1] if grad else None)
        cands.append(cand)
    return cands


def _embedding_ln_grad_chain(graph, y_node, ln_n, chain_ops, lt_n):
    """(chain grad ops, per-addend grad names) for the embedding+LN
    match, or None.  The add grads' IG$Y outputs carry the external
    addends' gradients, which the fused grad op must keep producing."""
    lt_grad = lt_n.name + "_grad"
    lng = _grad_consumer(graph, y_node.name + "@GRAD",
                         "layer_norm_grad", "OG$Y")
    if lng is None or \
            lng.op.attrs.get("__fwd_type__") != "layer_norm":
        return None
    chain = [lng]
    igx = lng.op.output("IG$X")
    if not igx or not igx[0]:
        return None
    g = igx[0]
    addend_gnames = []
    for add_n in reversed(chain_ops):
        ag = _grad_consumer(graph, g, "elementwise_add_grad", "OG$Out")
        if ag is None or \
                ag.op.attrs.get("__fwd_type__") != "elementwise_add":
            return None
        chain.append(ag)
        igy = ag.op.output("IG$Y")
        addend_gnames.append(igy[0] if igy else "")
        igx = ag.op.output("IG$X")
        if not igx or not igx[0]:
            return None
        g = igx[0]
    ltg = _grad_consumer(graph, g, lt_grad, "OG$Out")
    if ltg is None or \
            ltg.op.attrs.get("__fwd_type__") != lt_n.name:
        return None
    chain.append(ltg)
    addend_gnames.reverse()
    return chain, addend_gnames


# ---------------------------------------------------------------------------
# shared candidate finishing: grads, descs, shapes, build closure
# ---------------------------------------------------------------------------

def _finish_candidate(graph, program, cand, *, fused_type, fused_ins,
                      fused_outs, fused_attrs, out_node, og_slot_name,
                      grad_chain, grad_ig, addend_grads=None):
    """Attach the grad chain, autotune descs, and the build() closure to
    a structurally-matched candidate.  ``grad_ig`` maps fused input slot
    -> (original grad op type, its IG slot) for recovering the external
    gradient names the fused grad op must keep producing."""
    if cand.reject_rule:
        return
    has_grads = _has_grad_ops(program)
    chain = grad_chain
    if isinstance(chain, tuple):
        chain = chain[0]
    if has_grads and not chain:
        cand.reject_rule = "missing_grad_rewrite"
        return
    cand.grad_ops = list(chain or ())
    if addend_grads and chain:
        # every REAL addend gradient must resolve to an output node on
        # one of the add grad ops being removed — an unresolvable name
        # would leave the fused grad op's output outside the graph's
        # dependency edges (topology could order its consumers first)
        adds = [n for n in cand.grad_ops
                if n.name == "elementwise_add_grad"]
        for gname in addend_grads:
            if gname and not any(
                    _out_node_by_name(gop, gname) is not None
                    for gop in adds):
                cand.reject_rule = "missing_grad_rewrite"
                return

    # grad-side internal vars: every @GRAD produced by one chain op and
    # consumed by the next — they vanish with the chain
    grad_internal = []
    removed = {n.id for n in cand.grad_ops}
    for gop in cand.grad_ops:
        for v in gop.outputs:
            if all(c.id in removed for c in v.outputs) and v.outputs:
                grad_internal.append(v)
    cand.grad_internal = grad_internal

    # autotune replay material
    block = program.global_block()
    # the micro-benchmark must replay in the SAME dtype regime the real
    # dispatch will use: an amp program runs its chains through bf16
    # casts, and benching them in f32 would hand the (internally
    # bf16-casting) Pallas kernels a dtype advantage they won't have
    cand.amp = bool(program._attrs.get("amp", False))
    cand.base_descs = [_desc(n.op) for n in cand.fwd_ops]
    fused_in_names = {s: [v.name for v in vs]
                      for s, vs in fused_ins.items()}
    fused_out_names = {s: [v.name for v in vs]
                       for s, vs in fused_outs.items()}
    cand.fused_descs = [(fused_type, fused_in_names, fused_out_names,
                         dict(fused_attrs))]
    ext = {}
    internal_names = {v.name for v in cand.internal}
    for n in cand.fwd_ops:
        for v in n.inputs:
            if v.name in internal_names or v.name in ext:
                continue
            var = v.var if v.var is not None else (
                block.var(v.name) if block.has_var(v.name) else None)
            if var is None or var.shape is None:
                cand.ext_inputs = {}
                break
            ext[v.name] = (tuple(var.shape), str(var.dtype or "float32"))
        else:
            continue
        break
    else:
        cand.ext_inputs = ext
    out_var = getattr(out_node, "var", None)
    cand.shape_key = tuple(sorted(
        (n, s) for n, (s, _) in (cand.ext_inputs or {}).items())) + (
        ("out", tuple(out_var.shape) if out_var is not None and
         out_var.shape else ()),)

    def build(g, use_pallas=False):
        attrs = dict(fused_attrs)
        if "use_pallas" in attrs:
            attrs["use_pallas"] = bool(use_pallas)
        fused_node = g.create_op_node(fused_type, inputs=fused_ins,
                                      outputs=fused_outs, attrs=attrs)
        doomed = list(cand.fwd_ops) + list(cand.internal) + \
            list(cand.dead_outputs)
        if cand.grad_ops:
            # synthesize the fused op's generic-vjp grad desc (the
            # make_grad_ops X$/OG$/IG$ convention) wired to the ORIGINAL
            # external grad names, so downstream accumulation/optimizer
            # ops are untouched
            g_ins = {}
            for slot, nodes in fused_ins.items():
                g_ins["X$" + slot] = list(nodes)
            og_name = out_node.name + "@GRAD"
            og_node = None
            for gop in cand.grad_ops:
                og_node = _node_by_name(gop, og_name)
                if og_node is not None:
                    break
            g_ins["OG$" + og_slot_name] = [og_node]
            g_outs = {}
            by_type = {}
            for gop in cand.grad_ops:
                by_type.setdefault(gop.name, gop)
            for slot, (gtype, ig_slot) in grad_ig.items():
                gop = by_type.get(gtype)
                if gop is None:
                    continue
                names = gop.op.output(ig_slot)
                if not names or not names[0]:
                    continue
                node = _out_node_by_name(gop, names[0])
                if node is not None:
                    g_outs["IG$" + slot] = [node]
            addend_nodes = []
            if addend_grads:
                adds = [n for n in cand.grad_ops
                        if n.name == "elementwise_add_grad"]
                for gname in addend_grads:
                    node = None
                    for gop in adds:
                        node = _out_node_by_name(gop, gname)
                        if node is not None:
                            break
                    addend_nodes.append(node)
                real = [n for n in addend_nodes if n is not None]
                if real:
                    g_outs["IG$Addends"] = real
            g_attrs = dict(attrs)
            g_attrs["__fwd_type__"] = fused_type
            gnode = g.create_op_node(fused_type + "_grad", inputs=g_ins,
                                     outputs=g_outs, attrs=g_attrs)
            if addend_grads and any(n is None for n in addend_nodes):
                # POSITIONAL alignment with the generic-grad convention:
                # generic_grad_lower returns one gradient per addend in
                # slot order, and the executor zips them against the
                # output NAME list — a stop-gradient addend must keep
                # its '' placeholder or a surviving addend would receive
                # its neighbor's gradient.  Graph edges track only the
                # real nodes (created above); the name list is restored
                # here with the placeholders.
                gnode.op.outputs["IG$Addends"] = [
                    (g or "") for g in addend_grads]
            doomed += list(cand.grad_ops) + list(cand.grad_internal)
        g.safe_remove_nodes(doomed)
        return fused_node

    cand.build = build


# ---------------------------------------------------------------------------
# legality
# ---------------------------------------------------------------------------

#: rules worth a user-facing warning (structural kernel limits are not —
#: a 3x3 conv not matching the 1x1 Pallas target is expected, not a bug)
_WARN_RULES = frozenset({
    "fetched_internal", "multi_consumer", "persistable_internal",
    "subblock_ref", "missing_grad_rewrite", "alias_hazard",
})


def _legality(cand: _Candidate, graph, program, fetch_names,
              alias_pairs) -> Optional[str]:
    """None when the candidate is provably training-safe, else the
    failing rule name."""
    if cand.reject_rule:
        return cand.reject_rule
    fetched = set(fetch_names)
    member_ids = {n.id for n in cand.all_ops()}
    member_ops = {id(n.op) for n in cand.all_ops()}
    for op_n in cand.all_ops():
        if op_n.name.startswith(_COLLECTIVE_PREFIX):
            return "collective"
        if any(isinstance(v, Block)
               for v in op_n.op.attrs.values()):
            return "subblock_op"
    for v in cand.internal + getattr(cand, "grad_internal", []):
        if v.name in fetched:
            return "fetched_internal"
        if v.persistable:
            return "persistable_internal"
        if any(c.id not in member_ids for c in v.outputs):
            return "multi_consumer"
        from ..framework.ir import _referenced_outside_block0
        if _referenced_outside_block0(program, v.name):
            return "subblock_ref"
        # donation/alias interval model (memory planner semantics): an
        # internal var sharing a buffer through an inplace pair whose
        # consumer op SURVIVES the rewrite cannot disappear — the
        # surviving op would extend an interval the fused program no
        # longer expresses.  Pairs whose consumer is itself fused away
        # (e.g. the folded dropout aliasing its own input) are fine.
        for src, out, consumer_op in alias_pairs:
            if v.name in (src, out) and id(consumer_op) not in \
                    member_ops:
                return "alias_hazard"
    for v in cand.dead_outputs:
        if v.name in fetched:
            return "fetched_internal"
        if v.persistable:
            return "persistable_internal"
        if any(c.id not in member_ids for c in v.outputs):
            return "multi_consumer"
    return None


# ---------------------------------------------------------------------------
# autotune
# ---------------------------------------------------------------------------

_AUTOTUNE_MEM: Dict[str, dict] = {}     # guarded-by: _AUTOTUNE_LOCK
_AUTOTUNE_LOADED = [False]              # guarded-by: _AUTOTUNE_LOCK
_AUTOTUNE_LOCK = threading.Lock()


def _autotune_path() -> Optional[str]:
    from ..flags import get_flags
    d = get_flags("FLAGS_xla_compile_cache_dir")[
        "FLAGS_xla_compile_cache_dir"]
    return os.path.join(str(d), "fusion_autotune.json") if d else None


def _device_key() -> str:
    """Autotune cache key component naming the ACTUAL hardware:
    ``<device_kind>x<device_count>`` (e.g. ``TPU_v5ex4``, ``cpux8``).
    A backend name alone ("tpu") would let a v4 verdict steer a v5e —
    different MXU shapes, different winners (ROADMAP carried-over
    follow-on)."""
    import jax
    try:
        devs = jax.devices()
        kind = str(devs[0].device_kind).replace(" ", "_")
        return f"{kind}x{len(devs)}"
    except Exception:
        return str(jax.default_backend())


def _migrate_autotune_key(key: str) -> str:
    """Re-key a pre-device-kind cache entry: old keys carried the bare
    backend name ("cpu"/"gpu"/"tpu") in slot 3; entries recorded on THIS
    backend migrate to the current :func:`_device_key` (best available
    interpretation — the measurements came from some device of this
    backend), foreign-backend entries are kept as-is for their own
    process to migrate."""
    import jax
    try:
        parts = json.loads(key)
    except ValueError:
        return key
    if (isinstance(parts, list) and len(parts) == 5
            and parts[3] in ("cpu", "gpu", "tpu")
            and parts[3] == jax.default_backend()):
        parts[3] = _device_key()
        return json.dumps(parts, default=str)
    return key


def _autotune_load_locked():   # guarded-by-caller: _AUTOTUNE_LOCK
    if _AUTOTUNE_LOADED[0]:
        return
    _AUTOTUNE_LOADED[0] = True
    path = _autotune_path()
    if not path:
        return
    try:
        with open(path) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            return
        # two passes so a measurement already taken under a new-style
        # key is never clobbered by a migrated old one, regardless of
        # the entries' order in the file
        migrated = False
        deferred = []
        for k, v in data.items():
            if not isinstance(v, dict):
                continue
            nk = _migrate_autotune_key(k)
            if nk != k:
                migrated = True
                deferred.append((nk, v))
            else:
                _AUTOTUNE_MEM.setdefault(k, v)
        for nk, v in deferred:
            _AUTOTUNE_MEM.setdefault(nk, v)
        if migrated:
            _autotune_persist_locked()   # one-shot cache migration
    except (OSError, ValueError):
        pass


def _autotune_persist_locked():   # guarded-by-caller: _AUTOTUNE_LOCK
    path = _autotune_path()
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(_AUTOTUNE_MEM, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        pass          # a read-only cache dir must not fail the compile


def _fill_value(name: str, shape, dtype, batch: int):
    import jax.numpy as jnp
    import numpy as np
    rs = tuple(batch if d in (-1, None) else int(d) for d in shape)
    d = str(dtype)
    if "int" in d:
        return jnp.zeros(rs, jnp.int32)
    # positive fill: variance-like operands must survive rsqrt
    return jnp.full(rs, np.float32(0.5),
                    jnp.bfloat16 if d == "bfloat16" else jnp.float32)


def _replay(descs, env, ctx):
    """Run a straight-line chain of op descs through the registered
    lowerings on a value environment — the autotuner's common harness
    for the base chain and the fused op."""
    from .. import amp as _amp
    from ..framework import registry as _reg
    outs_all = []
    for typ, ins_names, outs_names, attrs in descs:
        info = _reg.get_op_info(typ)
        ins = {s: [env.get(n) for n in names]
               for s, names in ins_names.items()}
        if ctx.amp:
            # the executor's per-op cast (run_op) — the fused lowerings
            # handle amp internally, exactly as in real dispatch
            ins = _amp.cast_ins(typ, ins)
        outs = info.lower(ctx, ins, attrs) or {}
        for s, names in outs_names.items():
            for n, v in zip(names, outs.get(s, [])):
                if n:
                    env[n] = v
                    outs_all.append(v)
    return outs_all


def _time_chain(descs, ext_vals, reps=3, amp=False):
    import jax

    from ..framework.executor import LowerCtx

    names = sorted(ext_vals)

    def run(*arrs):
        env = dict(zip(names, arrs))
        return _replay(descs, env, LowerCtx(0, amp=amp))

    fn = jax.jit(run)
    args = [ext_vals[n] for n in names]
    jax.block_until_ready(fn(*args))            # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def _autotune(cand: _Candidate, batch: int) -> Optional[dict]:
    """Measured fused-vs-base verdict for one candidate, cached on
    (pattern, shape key, batch, device kind x topology, amp regime).
    None when the candidate cannot be
    replayed (unknown shapes) — callers fall back to rank-only."""
    if not cand.ext_inputs or not cand.base_descs:
        return None
    amp = bool(getattr(cand, "amp", False))
    key = json.dumps([cand.pattern, cand.shape_key, batch, _device_key(),
                      "amp" if amp else "f32"], default=str)
    with _AUTOTUNE_LOCK:
        _autotune_load_locked()
        hit = _AUTOTUNE_MEM.get(key)
    if hit is not None:
        _AUTOTUNE_HIT.inc()
        return dict(hit, cached=True)
    _AUTOTUNE_MISS.inc()
    try:
        ext_vals = {n: _fill_value(n, s, d, batch)
                    for n, (s, d) in cand.ext_inputs.items()}
        # the fused candidate benches its preferred kernel config
        fused_descs = [
            (t, i, o, dict(a, use_pallas=True) if "use_pallas" in a
             else a)
            for t, i, o, a in cand.fused_descs]
        base_ms = _time_chain(cand.base_descs, ext_vals, amp=amp)
        fused_ms = _time_chain(fused_descs, ext_vals, amp=amp)
    except Exception:
        return None              # unbenchable: caller falls back
    rec = {"base_ms": round(base_ms, 4), "fused_ms": round(fused_ms, 4),
           "win": bool(fused_ms <= base_ms), "cached": False}
    with _AUTOTUNE_LOCK:
        _AUTOTUNE_MEM[key] = {k: rec[k] for k in
                              ("base_ms", "fused_ms", "win")}
        _autotune_persist_locked()
    if _monitor.TRACER.enabled:
        _monitor.TRACER.instant(
            "fusion.autotune", "compile",
            {"pattern": cand.pattern, "base_ms": rec["base_ms"],
             "fused_ms": rec["fused_ms"], "win": rec["win"]})
    return rec


# ---------------------------------------------------------------------------
# main entry
# ---------------------------------------------------------------------------

_MATCHERS = (
    _match_conv_bn_relu,
    _match_dense_epilogue,
    _match_embedding_layer_norm,
)

# (program fingerprint, fetch tuple, config token, batch) -> program or
# None (None = fusion left the program untouched).  Bounded FIFO: every
# program mutation mints a new fingerprint (verifier-cache discipline).
_RESULT_CACHE: Dict[tuple, Optional[Program]] = {}  # guarded-by: _RESULT_LOCK
_RESULT_CAP = 64
_RESULT_LOCK = threading.Lock()

#: (fingerprint, token) pairs whose rejection warnings already fired
_WARNED: set = set()                    # guarded-by: _RESULT_LOCK


def clear_cache() -> None:
    with _RESULT_LOCK:
        _RESULT_CACHE.clear()
        _WARNED.clear()
    with _AUTOTUNE_LOCK:
        _AUTOTUNE_MEM.clear()
        _AUTOTUNE_LOADED[0] = False


def analyze_program(program: Program, fetch_names=(),
                    batch_size: int = 1) -> FusionReport:
    """Report-only mode for ``tools/analyze.py --fusion``: candidates,
    legality verdicts, cost ranks and autotune decisions, with NO
    rewrite applied and no caching."""
    fetch_names = tuple(
        f.name if hasattr(f, "name") else f for f in (fetch_names or ()))
    _, report = _fuse(program, fetch_names, batch_size, dry_run=True)
    return report


def fuse_program(program: Program, fetch_names=(),
                 feed_shapes=None) -> Program:
    """The pass entry: returns the fused program (a new Program) when
    any candidate was applied and survived re-verification, else the
    original object.  Cached on (fingerprint, fetch tuple, config
    token, batch) so the executor's slow path re-enters at dict-probe
    cost."""
    from ..flags import get_flags
    if not get_flags("FLAGS_graph_fusion")["FLAGS_graph_fusion"]:
        return program
    fetch_names = tuple(
        f.name if hasattr(f, "name") else f for f in (fetch_names or ()))
    batch = _batch_of(feed_shapes)
    token = config_token()
    key = (program.fingerprint(), fetch_names, token, batch)
    with _RESULT_LOCK:
        if key in _RESULT_CACHE:
            cached = _RESULT_CACHE[key]
            return cached if cached is not None else program
    fused, report = _fuse(program, fetch_names, batch, dry_run=False)
    result = fused if fused is not program else None
    with _RESULT_LOCK:
        # concurrent first compiles of the same program can race here:
        # only the insert winner counts decisions and warns, so the
        # counters stay once-per-(program, config) exact
        won = key not in _RESULT_CACHE
        if won:
            if len(_RESULT_CACHE) >= _RESULT_CAP:
                _RESULT_CACHE.pop(next(iter(_RESULT_CACHE)))
            _RESULT_CACHE[key] = result
        else:
            cached = _RESULT_CACHE[key]
        warn_key = (program.fingerprint(), token)
        do_warn = won and warn_key not in _WARNED
        if do_warn:
            if len(_WARNED) >= 4 * _RESULT_CAP:
                # bounded like the result cache it shadows: a long-lived
                # service minting programs must not leak dedup keys; a
                # rare repeat warning after the reset is harmless
                _WARNED.clear()
            _WARNED.add(warn_key)
    if not won:
        return cached if cached is not None else program
    _count_decisions(report)
    if do_warn:
        _warn_rejections(report)
    return fused


def _batch_of(feed_shapes) -> int:
    if feed_shapes:
        for shape in (feed_shapes.values()
                      if isinstance(feed_shapes, dict) else feed_shapes):
            if shape:
                return max(int(shape[0]), 1)
    return 1


def _warn_rejections(report: FusionReport) -> None:
    from .verifier import Diagnostic
    diags = [
        Diagnostic("fusion_reject", "warning",
                   f"fusion candidate {d.pattern!r} at {d.anchor!r} "
                   f"rejected by legality rule {d.rule!r}",
                   var=d.anchor,
                   fix_hint="see README 'Graph fusion' legality table; "
                            "tools/analyze.py --fusion shows the full "
                            "candidate report")
        for d in report.decisions
        if d.verdict == "rejected" and d.rule in _WARN_RULES]
    if diags:
        import warnings

        from .. import debugger
        warnings.warn("graph fusion rejections:\n"
                      + debugger.format_diagnostics(diags), stacklevel=3)


def _fuse(program: Program, fetch_names, batch: int,
          dry_run: bool) -> Tuple[Program, FusionReport]:
    from ..flags import get_flags
    from ..framework import ir
    from . import cost as _cost
    from . import verifier as _verifier

    fl = get_flags(["FLAGS_fusion_autotune",
                    "FLAGS_fusion_rank_threshold"])
    autotune_on = bool(fl["FLAGS_fusion_autotune"])
    threshold = float(fl["FLAGS_fusion_rank_threshold"])

    report = FusionReport()
    with _monitor.TRACER.span("fusion.plan", "compile",
                              fetches=len(fetch_names)):
        graph = ir.Graph(program)
        candidates: List[_Candidate] = []
        for matcher in _MATCHERS:
            candidates.extend(matcher(graph, program, fetch_names))
        if not candidates:
            return program, report

        # verify BEFORE the pass: fusion never applies to a broken
        # program, and the pre-fingerprint anchors the invariance check
        pre = _verifier.verify_program(program, fetch_names)
        if not pre.ok:
            return program, report
        pre_fp = pre.collective_fingerprint

        plan = _cost.plan_cost(program, fetch_names, batch_size=batch)
        fshare = plan.share()
        btotal = float(plan.bytes) or 1.0
        bshare = {c: b / btotal
                  for c, b in plan.per_class_bytes.items()}
        alias_graph = ir.get_pass("buffer_shared_inplace_pass").apply(
            ir.Graph(program))
        # (src, out, consumer Operator): the pair plus the op that would
        # compute in place — legality compares it against candidate
        # membership (Operator objects are shared across Graph builds)
        alias_pairs = []
        for src, out in alias_graph.attrs.get("inplace_pairs", []):
            consumer = next(
                (op for op in program.global_block().ops
                 if src in op.input_arg_names()
                 and out in op.output_arg_names()), None)
            if consumer is not None:
                alias_pairs.append((src, out, consumer))

        def rank_of(c):
            return max(fshare.get(c.op_class, 0.0),
                       bshare.get(c.op_class, 0.0))

        applied: List[Tuple[_Candidate, bool]] = []
        taken: set = set()
        for cand in sorted(candidates, key=rank_of, reverse=True):
            rank = rank_of(cand)
            dec = FusionDecision(cand.pattern, cand.anchor,
                                 verdict="", rank=rank)
            report.decisions.append(dec)
            rule = _legality(cand, graph, program, fetch_names,
                             alias_pairs)
            if rule is not None:
                dec.verdict, dec.rule = "rejected", rule
                continue
            if any(n.id in taken for n in cand.all_ops()):
                dec.verdict = "overlapped"
                continue
            if rank < threshold:
                dec.verdict = "ranked_out"
                continue
            use_pallas = False
            if autotune_on:
                verdict = _autotune(cand, batch)
                if verdict is not None:
                    dec.autotune = verdict
                    if not verdict["win"]:
                        dec.verdict = "autotune_lost"
                        continue
                    use_pallas = True
            dec.verdict = "applied"
            taken.update(n.id for n in cand.all_ops())
            applied.append((cand, use_pallas))

        if dry_run or not applied:
            report.applied = len(applied) if dry_run else 0
            if not dry_run:
                program._attrs["fusion"] = report.as_dict()
            return program, report

        for cand, use_pallas in applied:
            cand.build(graph, use_pallas=use_pallas)
        fused = graph.to_program()
        report.applied = len(applied)

        # verify AFTER the pass: the fused program must be clean and its
        # collective fingerprint unchanged (fusion never touches
        # collectives) — anything else rolls the whole rewrite back
        post = _verifier.verify_program(fused, fetch_names)
        fp_ok = post.collective_fingerprint == pre_fp
        report.collective_fingerprint_ok = fp_ok
        if not post.ok or not fp_ok:
            for dec in report.decisions:
                if dec.verdict == "applied":
                    dec.verdict = "verify_failed"
            report.applied = 0
            import warnings
            warnings.warn(
                "graph fusion rolled back: the fused program "
                + ("failed verification" if not post.ok
                   else "changed the collective fingerprint")
                + " — running unfused", stacklevel=3)
            program._attrs["fusion"] = report.as_dict()
            return program, report
        fused._attrs["fusion"] = report.as_dict()
    return fused, report


def _count_decisions(report: FusionReport) -> None:
    """Final-verdict counting — called ONLY by ``fuse_program`` on a
    result-cache insert win, so decisions count once per
    (program, config) even under concurrent first compiles, and the
    report-only ``analyze_program`` path never skews the counters."""
    for dec in report.decisions:
        _CAND_CTR.inc(1, pattern=dec.pattern, verdict=dec.verdict)
