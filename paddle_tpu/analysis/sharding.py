"""Static sharding analysis: PartitionSpec propagation + GSPMD reshard
planning over the Fluid graph (ISSUE 20).

PR 16's partitioner stamps ``_attrs["partition"]`` (per-param
PartitionSpecs + per-activation sharding constraints) and lowers through
pjit — but nothing verified the specs COMPOSE.  This pass closes that
gap with a forward dataflow walk over the dependency-ordered ``ir``
graph: seeded from the stamped param specs and dp-sharded feeds, it
propagates PartitionSpecs through every op (matmul contraction
semantics, elementwise broadcast join, reshape split/merge axis
remapping, transpose permutation, sub-block bodies in enclosing-scope
context like the PR-7 verifier) and reconciles each produced spec
against the activation constraint the executor will pin with
``with_sharding_constraint`` — the constraint is ground truth (the
runtime applies it on every write), so a propagated/constrained
disagreement IS a reshard the step will pay for.

Three checks feed the program verifier (``verifier.CHECKS``):

- ``spec_conflict``: one var, two consumers demanding incompatible
  shardings.  One-sided (sharded meets replicated) resolves as an
  implicit all-gather reshard edge + a warning; both-sided (two
  DIFFERENT mesh axes demanded for the same contraction/dim) is
  cross-rank-ambiguous and an error — GSPMD cannot pick a layout both
  ranks will agree on, so the program refuses at optimize time.
- ``shard_divisibility``: dims the partitioner's divisibility guard
  dropped (``partitioner._spec_for`` keeps non-dividing dims
  replicated); the drop is now named — var, dim, logical axis, mesh
  axis — instead of silent.
- ``mesh_axis_overuse``: one spec using the same mesh axis twice
  (e.g. a table mapping both of a weight's logical axes onto ``mp``);
  pjit would reject it with a shape error deep inside XLA — this names
  the var and table at optimize time with zero dispatches.

The per-edge **reshard plan** prices every GSPMD-induced collective
through the PR-13 ring model (``analysis.comms``): partial-sum
all-reduces of row-parallel matmuls, backward dX all-reduces of
column-parallel ones, vocab-sharded embedding/CE traffic,
constraint-forced all-gather/all-to-all reshards, per-param dp gradient
sync, and ZeRO-1's reduce-scatter + all-gather split.  Each edge
carries ``exact`` (True → the runtime byte accounting matches the plan
to the byte; False → XLA chooses the implementation and the plan is a
band) and ``reason`` (``spec_mismatch`` marks the UNEXPLAINED edges —
a blessed table analyzes with zero of them).

The plan is fingerprint-cached, stamped into
``_attrs["verify"]["sharding"]``, folded into the cross-rank collective
fingerprint as ``#resh=<edges>x<sha8>`` (divergent reshard plans refuse
at the PR-6 step barrier by plan token, not just rule-table name), and
consumed by ``partitioner.choose_rules`` so candidate tables are priced
on real per-edge reshard bytes instead of the coarse matmul heuristic.
``check_decode_hostable`` is the serving-side gate: the paged KV cache
hosts full per-head pages on ONE chip, so an mp-sharded decode program
is statically refused naming the offending specs.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import monitor as _monitor
from ..framework.core import Block, Program
from .verifier import Diagnostic, sub_blocks_of

__all__ = [
    "ReshardEdge", "ShardingPlan", "plan_sharding", "check_decode_hostable",
    "runtime_comms_plan", "stamp_attrs", "clear_cache",
]

#: static per-step GSPMD reshard traffic of the most recently planned
#: partitioned program (logical payload bytes across every edge)
_RESHARD_GAUGE = _monitor.REGISTRY.gauge(
    "paddle_tpu_gspmd_reshard_bytes",
    "static per-step reshard-plan payload bytes of the last planned "
    "partitioned program")

#: reshard kind -> per-rank wire fraction of the logical payload (the
#: comms._ALGO_FACTOR ring discipline; all_to_all moves one shard's
#: (n-1)/n over the wire, i.e. (n-1)/n^2 of the global tensor)
_FACTOR = {
    "all_reduce": lambda n: 2.0 * (n - 1) / n,
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / (n * n),
    "slice": lambda n: 0.0,
}

#: reshard kind -> the explicit-collective op name the runtime byte
#: counter (paddle_tpu_collective_bytes_total) labels its series with
_COLLECTIVE_OP = {
    "all_reduce": "c_allreduce_sum",
    "all_gather": "c_allgather",
    "reduce_scatter": "c_reducescatter",
    "all_to_all": "c_alltoall",
    "slice": "c_split",
}

#: ops whose output keeps the first input's spec (elementwise /
#: layout-preserving); elementwise binaries additionally JOIN specs
_ELTWISE = frozenset((
    "relu", "gelu", "tanh", "sigmoid", "exp", "log", "sqrt", "square",
    "abs", "sign", "scale", "cast", "dropout", "clip", "assign", "pow",
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min", "sum",
))

_BINARY = frozenset((
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_max", "elementwise_min",
))

#: host-side / optimizer ops the forward walk skips outright (their
#: backward traffic is modeled analytically, not re-walked — the grad
#: graph is jax.vjp-generated and mirrors the forward structurally)
_SKIP = frozenset((
    "feed", "fetch", "fill_constant", "increment", "shape",
    "sgd", "momentum", "adam", "adamw", "adagrad", "decayed_adagrad",
    "rmsprop", "lamb", "lars_momentum", "adamax", "ftrl",
))

_CE_OPS = frozenset((
    "cross_entropy", "softmax_with_cross_entropy", "fused_lm_head_ce",
))

#: edge reasons that are NOT "spec_mismatch": semantically derived
#: traffic the table owner signed up for (the smoke's zero-unexplained
#: gate counts only spec_mismatch edges)
EXPLAINED_REASONS = frozenset((
    "partial_sum", "grad_partial", "vocab_embed", "vocab_ce", "gather",
    "norm_stats", "softmax_stats", "loss_reduce", "constraint", "split",
    "grad_sync", "zero1_grad", "zero1_param",
))


@dataclass(frozen=True)
class ReshardEdge:
    """One GSPMD-induced collective: where, what kind, how many bytes.

    ``payload_bytes`` is the GLOBAL logical tensor size (the comms-plan
    convention); ``wire_bytes`` applies the ring algorithm factor for
    ``kind`` over the ``mesh_axis`` ring.  ``exact=True`` edges are
    dispatched verbatim by the runtime accounting; ``exact=False``
    edges are XLA's to implement and the bytes are a band."""

    var: str
    kind: str                      # _FACTOR key
    mesh_axis: str
    nranks: int
    payload_bytes: int
    wire_bytes: int
    est_ms: float
    reason: str
    exact: bool = False
    direction: str = "fwd"         # "fwd" | "bwd"
    op_type: Optional[str] = None
    op_index: Optional[int] = None
    src_spec: Optional[tuple] = None
    dst_spec: Optional[tuple] = None
    dtype: str = "float32"
    shape: Tuple[int, ...] = ()

    @property
    def explained(self) -> bool:
        return self.reason != "spec_mismatch"

    @property
    def collective_op(self) -> str:
        return _COLLECTIVE_OP[self.kind]


@dataclass
class ShardingPlan:
    """Propagated specs + priced reshard edges + diagnostics for one
    partitioned program (module docstring)."""

    rules: Optional[str] = None
    mesh_axes: Dict[str, int] = field(default_factory=dict)
    batch_size: int = 1
    zero_stage: int = 0
    link_bw: float = 1e10
    #: final propagated spec per var (params seeded, activations
    #: settled against their stamped constraints)
    specs: Dict[str, tuple] = field(default_factory=dict)
    edges: List[ReshardEdge] = field(default_factory=list)
    diagnostics: List[Diagnostic] = field(default_factory=list)
    payload_bytes: int = 0
    wire_bytes: int = 0
    est_ms: float = 0.0
    compute_ms: float = 0.0
    #: sha1 over (mesh, rules, ordered edge tuples) — the cross-rank
    #: parity token folded into the collective fingerprint
    fingerprint: str = ""

    @property
    def unexplained(self) -> List[ReshardEdge]:
        return [e for e in self.edges if not e.explained]

    @property
    def resh_token(self) -> str:
        """Compact ``<edges>x<sha8>`` token: what the ``#resh=`` suffix
        of the collective fingerprint carries, so a barrier refusal can
        NAME both ranks' reshard plans."""
        return f"{len(self.edges)}x{self.fingerprint[:8]}"

    def report(self) -> str:
        mesh = ",".join(f"{a}:{s}" for a, s in sorted(
            self.mesh_axes.items()))
        lines = [
            f"sharding plan (rules={self.rules}, mesh {mesh}, "
            f"batch={self.batch_size}, zero{self.zero_stage}): "
            f"{len(self.edges)} reshard edge(s) "
            f"({len(self.unexplained)} unexplained), "
            f"{self.payload_bytes / 1e6:.3f} MB payload, "
            f"{self.wire_bytes / 1e6:.3f} MB wire, "
            f"est {self.est_ms:.3f} ms vs {self.compute_ms:.3f} ms "
            f"compute"]
        for e in self.edges:
            tier = "exact" if e.exact else "band"
            lines.append(
                f"  [{e.direction}] {e.kind:<14} @{e.mesh_axis} "
                f"{e.var:<32} {e.payload_bytes / 1e3:10.2f} kB  "
                f"{e.reason} ({tier})")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# spec algebra helpers
# ---------------------------------------------------------------------------

def _norm(spec) -> Optional[tuple]:
    if spec is None:
        return None
    spec = tuple(spec)
    return spec if any(s is not None for s in spec) else None

def _pad(spec, rank: int) -> tuple:
    """A spec tuple of exactly ``rank`` entries (None-filled)."""
    spec = tuple(spec or ())
    if len(spec) < rank:
        spec = spec + (None,) * (rank - len(spec))
    return spec[:rank]


def _dup_axis(spec) -> Optional[str]:
    seen = set()
    for ax in (spec or ()):
        if ax is None:
            continue
        if ax in seen:
            return ax
        seen.add(ax)
    return None


def _shape_of(block: Block, name, batch: int):
    if not name or not block.has_var(name):
        return None, "float32"
    v = block.var(name)
    if v.shape is None:
        return None, str(v.dtype or "float32")
    return tuple(batch if d in (-1, None) else int(d) for d in v.shape), \
        str(v.dtype or "float32")


def _itemsize(dtype) -> int:
    from .comms import _itemsize as _isz
    return _isz(dtype)


def _numel(shape) -> int:
    n = 1
    for d in shape or ():
        n *= max(int(d), 1)
    return n


# ---------------------------------------------------------------------------
# the propagation pass
# ---------------------------------------------------------------------------

class _Pass:
    """One propagation over one program: mutable spec environment plus
    the edge/diagnostic accumulators (sub-blocks share the env — the
    PR-7 enclosing-scope-context discipline)."""

    def __init__(self, program, seeds, constraints, axis_sizes,
                 batch_size, link_bw):
        self.program = program
        self.block = program.global_block()
        self.constraints = constraints
        self.axis_sizes = {a: int(s) for a, s in (axis_sizes or {}).items()}
        self.batch = int(batch_size)
        self.link_bw = link_bw
        self.spec: Dict[str, Optional[tuple]] = {}
        self.edges: List[ReshardEdge] = []
        self.diags: List[Diagnostic] = []
        self._conflicted: set = set()
        self.dp = "dp" if self.axis_sizes.get("dp", 0) > 1 else None
        for name, s in (seeds or {}).items():
            self.spec[name] = _norm(s)
        # stamped specs (params + activation constraints) never pass
        # through settle(), so duplicate-axis abuse is checked here
        for name, s in sorted(list((seeds or {}).items())
                              + list((constraints or {}).items())):
            dup = _dup_axis(s)
            if dup is not None and name not in self._conflicted:
                self._conflicted.add(name)
                self.diag(
                    "mesh_axis_overuse", "error",
                    f"spec {tuple(s)} for var {name!r} uses mesh axis "
                    f"{dup!r} on more than one dim — pjit cannot lay "
                    "one tensor out twice over the same mesh ring",
                    var=name,
                    fix="remap one of the var's logical axes to a "
                        "different mesh axis (or None) in the rule "
                        "table")
        # feeds carry the batch dim on dp (compiler._build_in_shardings
        # feed discipline: leading dim sharded over dp)
        if self.dp:
            for name in self.block.vars:
                v = self.block.var(name)
                if getattr(v, "is_data", False) and v.shape is not None \
                        and len(v.shape) >= 2:
                    self.spec[name] = _norm(
                        (self.dp,) + (None,) * (len(v.shape) - 1))

    # -- pricing ------------------------------------------------------------
    def edge(self, kind, axis, var, reason, *, exact=False, direction="fwd",
             op=None, idx=None, src=None, dst=None, payload=None):
        n = max(self.axis_sizes.get(axis, 1), 1)
        shape, dtype = _shape_of(self.block, var, self.batch)
        if payload is None:
            payload = _numel(shape) * _itemsize(dtype)
        wire = int(payload * _FACTOR[kind](n)) if n > 1 else 0
        self.edges.append(ReshardEdge(
            var=var, kind=kind, mesh_axis=axis, nranks=n,
            payload_bytes=int(payload), wire_bytes=wire,
            est_ms=wire / self.link_bw * 1e3, reason=reason, exact=exact,
            direction=direction, op_type=getattr(op, "type", None),
            op_index=idx, src_spec=src, dst_spec=dst, dtype=dtype,
            shape=tuple(shape or ())))

    def diag(self, check, severity, message, *, op=None, idx=None,
             var=None, fix=None, path=None):
        self.diags.append(Diagnostic(
            check=check, severity=severity, message=message,
            op_type=getattr(op, "type", None), op_index=idx, var=var,
            fix_hint=fix, block=path))

    # -- per-var settlement --------------------------------------------------
    def settle(self, name, natural, op, idx, path):
        """Reconcile the propagated ``natural`` spec of a fresh write
        against the stamped activation constraint (the layout the
        executor pins): a disagreement is a real reshard the step pays,
        priced here and classified ``constraint``.  Duplicate mesh axes
        in the final spec are a ``mesh_axis_overuse`` error."""
        if not name:
            return
        shape, _ = _shape_of(self.block, name, self.batch)
        natural = _norm(_pad(natural, len(shape or natural or ())))
        final = natural
        con = self.constraints.get(name)
        if con is not None and shape is not None \
                and len(con) == len(shape):
            con = _norm(con)
            if natural is not None and con != natural:
                nat = _pad(natural, len(shape))
                cn = _pad(con, len(shape))
                gathered = [a for a, b in zip(nat, cn)
                            if a is not None and a != b]
                kept = {b for b in cn if b is not None}
                for ax in dict.fromkeys(gathered):       # stable order
                    kind = "all_to_all" if ax in kept else "all_gather"
                    self.edge(kind, ax, name, "constraint", op=op,
                              idx=idx, src=natural, dst=con)
            final = con
        dup = _dup_axis(final)
        if dup is not None and name not in self._conflicted:
            self._conflicted.add(name)
            self.diag(
                "mesh_axis_overuse", "error",
                f"spec {final} for var {name!r} uses mesh axis {dup!r} "
                "on more than one dim — pjit cannot lay one tensor out "
                "twice over the same mesh ring",
                op=op, idx=idx, var=name, path=path,
                fix="remap one of the var's logical axes to a different "
                    "mesh axis (or None) in the rule table")
        self.spec[name] = final

    # -- op walk -------------------------------------------------------------
    def run(self):
        self._walk(self.block, "0")

    def _walk(self, block: Block, path: str):
        for idx, op in enumerate(block.ops):
            t = op.type
            if t in _SKIP or t.endswith("_grad") or t.startswith("c_"):
                continue
            for attr_name, sub in sub_blocks_of(op):
                self._walk(sub, f"{path}/{t}@{idx}/{attr_name}")
            self._op(op, idx, path)

    def _in(self, op, slot):
        names = op.inputs.get(slot, [])
        return names[0] if names else None

    def _out(self, op, *slots):
        for slot in slots:
            names = op.outputs.get(slot, [])
            if names:
                return names[0]
        return None

    def _op(self, op, idx, path):
        t = op.type
        if t in ("lookup_table", "fused_embedding_layer_norm"):
            self._lookup(op, idx, path)
        elif t in ("mul", "matmul", "matmul_v2", "fused_dense_act"):
            # fused_dense_act (fusion pass): X @ W + Bias -> act — the
            # matmul semantics carry; bias/act are layout-preserving
            self._matmul(op, idx, path)
        elif t in ("reshape", "reshape2"):
            self._reshape(op, idx, path)
        elif t in ("transpose", "transpose2"):
            self._transpose(op, idx, path)
        elif t == "layer_norm":
            self._layer_norm(op, idx, path)
        elif t == "softmax":
            self._softmax(op, idx, path)
        elif t in _CE_OPS:
            self._cross_entropy(op, idx, path)
        elif t in ("mean", "reduce_mean", "reduce_sum"):
            self._reduce(op, idx, path)
        elif t == "gather":
            self._gather(op, idx, path)
        elif t == "split":
            self._split(op, idx, path)
        elif t == "concat":
            x = self._in(op, "X")
            self.settle(self._out(op, "Out"), self.spec.get(x), op, idx,
                        path)
        elif t in _ELTWISE:
            self._eltwise(op, idx, path)
        else:
            self._default(op, idx, path)

    def _lookup(self, op, idx, path):
        w, ids = self._in(op, "W"), self._in(op, "Ids")
        out = self._out(op, "Out")
        wspec = _pad(self.spec.get(w), 2)
        ids_spec = self.spec.get(ids)
        oshape, _ = _shape_of(self.block, out, self.batch)
        orank = len(oshape or ()) or (len(_pad(ids_spec, 1)) + 1)
        natural = _pad(ids_spec, orank - 1) + (wspec[1],)
        if wspec[0] is not None:
            # vocab-sharded table: each shard holds a vocab slice, the
            # gathered rows are partial (masked) and all-reduce across
            # the vocab ring forward AND backward (scatter-add of dOut)
            self.edge("all_reduce", wspec[0], out, "vocab_embed", op=op,
                      idx=idx)
            if self._has_backward:
                self.edge("all_reduce", wspec[0], out, "vocab_embed",
                          direction="bwd", op=op, idx=idx)
        self.settle(out, natural, op, idx, path)

    def _matmul(self, op, idx, path):
        x = self._in(op, "X")
        y = self._in(op, "Y") or self._in(op, "W")
        out = self._out(op, "Out")
        xshape, _ = _shape_of(self.block, x, self.batch)
        yshape, _ = _shape_of(self.block, y, self.batch)
        if not xshape or not yshape or not out:
            self.settle(out, None, op, idx, path)
            return
        tx = bool(op.attrs.get("transpose_X"))
        ty = bool(op.attrs.get("transpose_Y"))
        xs = _pad(self.spec.get(x), len(xshape))
        ys = _pad(self.spec.get(y), len(yshape))
        # contraction positions (mul flattens per num_col_dims; its
        # contraction is x's trailing block vs y's leading block —
        # modeled as last-vs-first, the rank-2 common case)
        xc_i = (len(xshape) - 2 if tx else len(xshape) - 1) \
            if len(xshape) >= 2 else 0
        yc_i = (len(yshape) - 1 if ty else len(yshape) - 2) \
            if len(yshape) >= 2 else 0
        yo_i = (len(yshape) - 2 if ty else len(yshape) - 1) \
            if len(yshape) >= 2 else 0
        xc, yc = xs[xc_i], ys[yc_i]
        out_shape, _ = _shape_of(self.block, out, self.batch)
        orank = len(out_shape or ()) or 2
        # batch dims come from x; the last dim from y's out dim
        lead = [s for i, s in enumerate(xs)
                if i != xc_i][:max(orank - 1, 0)]
        natural = list(_pad(tuple(lead), orank - 1)) + [ys[yo_i]]
        if xc is not None and yc is not None:
            if xc == yc:
                # row-parallel: both operands sharded over the
                # contraction — output is a partial sum, all-reduced
                # over the ring in forward
                self.edge("all_reduce", xc, out, "partial_sum", op=op,
                          idx=idx, src=xs, dst=tuple(natural))
            else:
                if x not in self._conflicted:
                    self._conflicted.add(x)
                    self.diag(
                        "spec_conflict", "error",
                        f"matmul contracts {x!r} (sharded {xc!r}) "
                        f"against {y!r} (sharded {yc!r}): two mesh "
                        "axes demanded for one contraction — "
                        "cross-rank-ambiguous, no layout satisfies "
                        "both", op=op, idx=idx, var=x, path=path,
                        fix="align the two operands' contraction axes "
                            "in the rule table (same mesh axis or "
                            "replicate one)")
        elif (xc is None) != (yc is None):
            # one-sided contraction sharding: GSPMD must gather the
            # sharded operand (or re-slice the other — it picks); an
            # implicit reshard edge, surfaced as a spec_conflict
            # warning because the table owner likely did not want it
            sharded_var, ax = (x, xc) if xc is not None else (y, yc)
            self.edge("all_gather", ax, sharded_var, "spec_mismatch",
                      op=op, idx=idx, src=self.spec.get(sharded_var))
            if sharded_var not in self._conflicted:
                self._conflicted.add(sharded_var)
                self.diag(
                    "spec_conflict", "warning",
                    f"matmul contraction of {x!r} x {y!r} is sharded "
                    f"on one side only ({sharded_var!r} over {ax!r}): "
                    "GSPMD inserts an implicit all-gather every step",
                    op=op, idx=idx, var=sharded_var, path=path,
                    fix="shard both contraction operands on the same "
                        "mesh axis, or neither")
        if xc is not None and yc == xc:
            natural[-1] = ys[yo_i]   # psum output: y's out-dim layout
        # Megatron column-parallel backward: dX = dOut @ W^T partials
        # all-reduce over the out-dim ring (the f-operator's g-dual)
        if self._has_backward and ys[yo_i] is not None \
                and yc is None and xc is None:
            self.edge("all_reduce", ys[yo_i], x, "grad_partial",
                      direction="bwd", op=op, idx=idx)
        self.settle(out, tuple(natural), op, idx, path)

    def _reshape(self, op, idx, path):
        x, out = self._in(op, "X"), self._out(op, "Out")
        xshape, _ = _shape_of(self.block, x, self.batch)
        oshape, _ = _shape_of(self.block, out, self.batch)
        xs = self.spec.get(x)
        if xs is None or not xshape or not oshape:
            self.settle(out, None, op, idx, path)
            return
        xs = _pad(xs, len(xshape))
        natural = [None] * len(oshape)
        # greedy split/merge dim matching by running products: a
        # sharded in-dim lands on the FIRST out-dim of its group (the
        # shard boundary falls on the leading factor)
        i = j = 0
        pi = pj = 1
        group_in, group_out = [], []
        while i < len(xshape) or j < len(oshape):
            if pi == pj and (group_in or group_out):
                for gi in group_in:
                    if xs[gi] is not None:
                        tgt = group_out[0] if group_out else None
                        n = self.axis_sizes.get(xs[gi], 1)
                        if tgt is not None and \
                                oshape[tgt] % max(n, 1) == 0:
                            natural[tgt] = xs[gi]
                        else:
                            self.edge("all_gather", xs[gi], x,
                                      "constraint", op=op, idx=idx,
                                      src=xs)
                group_in, group_out = [], []
            if pi <= pj and i < len(xshape):
                group_in.append(i)
                pi *= max(xshape[i], 1)
                i += 1
            elif j < len(oshape):
                group_out.append(j)
                pj *= max(oshape[j], 1)
                j += 1
            else:
                break
        for gi in group_in:
            if xs[gi] is not None and group_out:
                tgt = group_out[0]
                n = self.axis_sizes.get(xs[gi], 1)
                if oshape[tgt] % max(n, 1) == 0:
                    natural[tgt] = xs[gi]
        self.settle(out, tuple(natural), op, idx, path)

    def _transpose(self, op, idx, path):
        x, out = self._in(op, "X"), self._out(op, "Out")
        perm = op.attrs.get("axis") or op.attrs.get("perm") or ()
        xs = self.spec.get(x)
        if xs is None or not perm:
            self.settle(out, xs, op, idx, path)
            return
        xs = _pad(xs, len(perm))
        self.settle(out, tuple(xs[p] for p in perm), op, idx, path)

    def _layer_norm(self, op, idx, path):
        x = self._in(op, "X")
        out = self._out(op, "Y", "Out")
        xs = self.spec.get(x)
        xshape, dtype = _shape_of(self.block, x, self.batch)
        bna = int(op.attrs.get("begin_norm_axis", 1) or 1)
        if xs is not None and xshape:
            xs_p = _pad(xs, len(xshape))
            normed = [a for a in xs_p[bna:] if a is not None]
            for ax in dict.fromkeys(normed):
                # partial mean/var all-reduce: 2 stats per row
                rows = _numel(xshape[:bna])
                self.edge("all_reduce", ax, x, "norm_stats", op=op,
                          idx=idx, payload=2 * rows * _itemsize(dtype))
        self.settle(out, xs, op, idx, path)

    def _softmax(self, op, idx, path):
        x, out = self._in(op, "X"), self._out(op, "Out")
        xs = self.spec.get(x)
        xshape, dtype = _shape_of(self.block, x, self.batch)
        axis = int(op.attrs.get("axis", -1) if op.attrs.get("axis")
                   is not None else -1)
        if xs is not None and xshape:
            xs_p = _pad(xs, len(xshape))
            ax = xs_p[axis]
            if ax is not None:
                rows = _numel(xshape) // max(xshape[axis], 1)
                self.edge("all_reduce", ax, x, "softmax_stats", op=op,
                          idx=idx, payload=2 * rows * _itemsize(dtype))
        self.settle(out, xs, op, idx, path)

    def _cross_entropy(self, op, idx, path):
        slot = "Logits" if "Logits" in op.inputs else "X"
        logits = self._in(op, slot)
        loss = self._out(op, "Loss", "Y", "Out")
        ls = self.spec.get(logits)
        lshape, dtype = _shape_of(self.block, logits, self.batch)
        if ls is not None and lshape:
            ls_p = _pad(ls, len(lshape))
            if ls_p[-1] is not None:
                # vocab-parallel CE: max + sum-exp partials all-reduce
                # over the vocab ring, forward and backward
                rows = _numel(lshape[:-1])
                self.edge("all_reduce", ls_p[-1], logits, "vocab_ce",
                          op=op, idx=idx,
                          payload=2 * rows * _itemsize(dtype))
                if self._has_backward:
                    self.edge("all_reduce", ls_p[-1], logits,
                              "vocab_ce", direction="bwd", op=op,
                              idx=idx,
                              payload=rows * _itemsize(dtype))
            sm = self._out(op, "Softmax")
            if sm:
                self.settle(sm, ls, op, idx, path)
            if loss:
                lshape_out, _ = _shape_of(self.block, loss, self.batch)
                self.settle(
                    loss, _pad(tuple(ls_p[:-1]), len(lshape_out or ())),
                    op, idx, path)
            return
        for o in (self._out(op, "Softmax"), loss):
            if o:
                self.settle(o, ls if o != loss else None, op, idx, path)

    def _reduce(self, op, idx, path):
        x, out = self._in(op, "X"), self._out(op, "Out")
        xs = self.spec.get(x)
        _, dtype = _shape_of(self.block, x, self.batch)
        for ax in dict.fromkeys(a for a in (xs or ()) if a is not None):
            if ax == self.dp:
                continue    # dp partials fold into the loss psum XLA
                            # already inserts for the batch mean
            self.edge("all_reduce", ax, out or x, "loss_reduce", op=op,
                      idx=idx, payload=_itemsize(dtype))
        self.settle(out, None, op, idx, path)

    def _split(self, op, idx, path):
        x = self._in(op, "X")
        outs = op.outputs.get("Out", [])
        xs = self.spec.get(x)
        xshape, _ = _shape_of(self.block, x, self.batch)
        axis = int(op.attrs.get("axis", 0) or 0)
        if xs is not None and xshape:
            xs_p = _pad(xs, len(xshape))
            ax = xs_p[axis]
            if ax is not None and ax != self.dp:
                # splitting a sharded dim (the QKV pack): section
                # boundaries straddle shard boundaries, XLA reshards
                # the pack once per step
                self.edge("all_to_all", ax, x, "split", op=op, idx=idx,
                          src=xs)
        for o in outs:
            natural = xs
            if xs is not None and xshape:
                oshape, _ = _shape_of(self.block, o, self.batch)
                xs_p = list(_pad(xs, len(xshape)))
                n = self.axis_sizes.get(xs_p[axis] or "", 1)
                if xs_p[axis] is not None and oshape \
                        and oshape[axis] % max(n, 1) != 0:
                    xs_p[axis] = None
                natural = tuple(xs_p)
            self.settle(o, natural, op, idx, path)

    def _gather(self, op, idx, path):
        x, index = self._in(op, "X"), self._in(op, "Index")
        out = self._out(op, "Out")
        xs = self.spec.get(x)
        xshape, _ = _shape_of(self.block, x, self.batch)
        xs_p = _pad(xs, len(xshape or ())) if xs is not None else ()
        if xs_p and xs_p[0] is not None:
            # indexing into a sharded leading dim: the rows a shard
            # needs live anywhere on the ring — GSPMD gathers
            self.edge("all_gather", xs_p[0], x, "gather", op=op,
                      idx=idx, src=xs)
        idx_spec = _pad(self.spec.get(index), 1)
        natural = (idx_spec[0],) + tuple(xs_p[1:])
        self.settle(out, natural, op, idx, path)

    def _eltwise(self, op, idx, path):
        x = self._in(op, "X")
        out = self._out(op, "Out")
        xshape, _ = _shape_of(self.block, x, self.batch)
        natural = self.spec.get(x)
        if op.type in _BINARY:
            y = self._in(op, "Y")
            yshape, _ = _shape_of(self.block, y, self.batch)
            ys = self.spec.get(y)
            if ys is not None and xshape is not None \
                    and yshape is not None:
                xs_p = _pad(natural, len(xshape))
                ys_p = _pad(ys, len(yshape))
                rank = max(len(xshape), len(yshape))
                joined = []
                for k in range(1, rank + 1):   # align trailing dims
                    a = xs_p[-k] if k <= len(xs_p) else None
                    b = ys_p[-k] if k <= len(ys_p) else None
                    if a is not None and b is not None and a != b:
                        key = (out or x) + "#join"
                        if key not in self._conflicted:
                            self._conflicted.add(key)
                            self.diag(
                                "spec_conflict", "error",
                                f"{op.type} joins {x!r} ({a!r}) with "
                                f"{y!r} ({b!r}) on the same dim: two "
                                "mesh axes demanded for one var — "
                                "cross-rank-ambiguous",
                                op=op, idx=idx, var=out or x, path=path,
                                fix="shard both operands of the "
                                    "elementwise op identically")
                        joined.append(a)
                    else:
                        joined.append(a if a is not None else b)
                natural = tuple(reversed(joined))
        self.settle(out, natural, op, idx, path)

    def _default(self, op, idx, path):
        """Unmodeled op: output replicated; a sharded (non-dp) input
        is an implicit gather the pass cannot explain."""
        out = self._out(op, "Out", "Y")
        for slot, names in sorted(op.inputs.items()):
            for name in names:
                s = self.spec.get(name)
                axes = [a for a in (s or ())
                        if a is not None and a != self.dp]
                if not axes:
                    continue
                v = self.block.var(name) if self.block.has_var(name) \
                    else None
                if v is not None and getattr(v, "persistable", False):
                    continue
                self.edge("all_gather", axes[0], name, "spec_mismatch",
                          op=op, idx=idx, src=s)
                if name not in self._conflicted:
                    self._conflicted.add(name)
                    self.diag(
                        "spec_conflict", "warning",
                        f"op {op.type!r} consumes {name!r} sharded "
                        f"{s} but has no sharding rule in the static "
                        "pass: modeled as a full all-gather",
                        op=op, idx=idx, var=name, path=path,
                        fix="replicate the producer in the rule table "
                            "or extend analysis.sharding with the op's "
                            "semantics")
        if out:
            self.settle(out, None, op, idx, path)

    # -- analytic gradient-sync traffic --------------------------------------
    @property
    def _has_backward(self) -> bool:
        cached = getattr(self, "_bwd", None)
        if cached is None:
            cached = self._bwd = any(
                o.type.endswith("_grad") for o in self.block.ops)
        return cached

    def grad_sync_edges(self, zero_stage: int):
        """Per-param data-parallel gradient synchronization: replicated
        params all-reduce grad shards over dp (XLA inserts the psum for
        batch-sharded backward passes); ZeRO-1 splits it into a
        reduce-scatter (grads to the owning dp shard) + all-gather
        (updated params back) — same ring bytes, different kinds."""
        if not self._has_backward or not self.dp:
            return
        for name in sorted(self.block.vars):
            v = self.block.var(name)
            if not getattr(v, "is_parameter", False):
                continue
            shape, dtype = _shape_of(self.block, name, self.batch)
            if not shape:
                continue
            nbytes = _numel(shape) * _itemsize(dtype)
            for ax in (self.spec.get(name) or ()):
                if ax is not None:
                    nbytes //= max(self.axis_sizes.get(ax, 1), 1)
            if zero_stage >= 1:
                self.edge("reduce_scatter", self.dp, name, "zero1_grad",
                          direction="bwd", payload=nbytes)
                self.edge("all_gather", self.dp, name, "zero1_param",
                          direction="bwd", payload=nbytes)
            else:
                self.edge("all_reduce", self.dp, name, "grad_sync",
                          direction="bwd", payload=nbytes)


# ---------------------------------------------------------------------------
# entry point + cache
# ---------------------------------------------------------------------------

# (program fingerprint, fetch tuple, batch, zero, layout sha) ->
# ShardingPlan; bounded FIFO — the verifier/comms cache discipline
_CACHE: Dict[tuple, ShardingPlan] = {}  # guarded-by: _CACHE_LOCK
_CACHE_CAP = 128
_CACHE_LOCK = threading.Lock()


def clear_cache() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()


def plan_sharding(program: Program, fetch_names=(), batch_size: int = 1,
                  stamp: Optional[dict] = None, specs=None,
                  axis_sizes=None, rules: Optional[str] = None,
                  zero_stage: Optional[int] = None) \
        -> Optional[ShardingPlan]:
    """Propagate PartitionSpecs and price every reshard edge for one
    partitioned program.  Layout comes from the ``_attrs["partition"]``
    stamp by default; ``choose_rules`` passes candidate ``specs`` (one
    merged {var -> spec} dict — params seed the walk, activations
    become constraints) + ``axis_sizes`` to price tables BEFORE
    stamping.  Returns None for unpartitioned programs.  Cached on
    (program fingerprint, fetch tuple, batch, zero stage, layout)."""
    fetch_names = tuple(
        f.name if hasattr(f, "name") else f for f in (fetch_names or ()))
    dropped = ()
    if specs is None:
        stamp = stamp if stamp is not None else \
            program._attrs.get("partition")
        if not stamp:
            return None
        axis_sizes = dict(stamp.get("mesh_axes") or {}) \
            if axis_sizes is None else dict(axis_sizes)
        seeds = {k: tuple(v) for k, v in
                 (stamp.get("params") or {}).items()}
        constraints = {k: tuple(v) for k, v in
                       (stamp.get("activations") or {}).items()}
        rules = stamp.get("rules") if rules is None else rules
        if zero_stage is None:
            zero_stage = int(stamp.get("zero_stage") or 0)
        dropped = tuple(tuple(d) for d in (stamp.get("dropped") or ()))
    else:
        if axis_sizes is None:
            return None
        axis_sizes = dict(axis_sizes)
        block = program.global_block()
        seeds, constraints = {}, {}
        for k, v in specs.items():
            is_param = block.has_var(k) and \
                getattr(block.var(k), "is_parameter", False)
            (seeds if is_param else constraints)[k] = tuple(v)
    zero_stage = int(zero_stage or 0)
    layout = hashlib.sha1(repr((
        rules, sorted(axis_sizes.items()), sorted(seeds.items()),
        sorted(constraints.items()), dropped)).encode()).hexdigest()
    key = (program.fingerprint(), fetch_names, int(batch_size),
           zero_stage, layout)
    with _CACHE_LOCK:
        cached = _CACHE.get(key)
    if cached is not None:
        return cached
    with _monitor.TRACER.span("sharding.plan", "compile",
                              fetches=len(fetch_names)):
        plan = _plan(program, fetch_names, batch_size, seeds, constraints,
                     axis_sizes, rules, zero_stage, dropped)
    _RESHARD_GAUGE.set(float(plan.payload_bytes))
    with _CACHE_LOCK:
        if key not in _CACHE:
            if len(_CACHE) >= _CACHE_CAP:   # FIFO bound, see _CACHE note
                _CACHE.pop(next(iter(_CACHE)))
            _CACHE[key] = plan
        plan = _CACHE[key]
    return plan


def _plan(program, fetch_names, batch_size, seeds, constraints,
          axis_sizes, rules, zero_stage, dropped) -> ShardingPlan:
    from .comms import device_link_bandwidth
    link_bw = device_link_bandwidth()
    p = _Pass(program, seeds, constraints, axis_sizes, batch_size,
              link_bw)
    for d in dropped:
        var, dim, laxis, maxis, dsize, asize = (tuple(d) + (None,) * 6)[:6]
        p.diag(
            "shard_divisibility", "warning",
            f"dim {dim} of {var!r} (size {dsize}, logical axis "
            f"{laxis!r}) does not divide mesh axis {maxis!r} "
            f"(size {asize}): the partitioner kept it REPLICATED — "
            "the table's sharding silently does not apply here",
            var=var,
            fix=f"pad {var!r} to a multiple of {asize} along dim "
                f"{dim}, or unmap {laxis!r} in the rule table")
    p.run()
    p.grad_sync_edges(zero_stage)
    try:
        from .cost import device_peak_flops, plan_cost
        compute_ms = plan_cost(program, fetch_names,
                               batch_size=batch_size).flops \
            / device_peak_flops() * 1e3
    except Exception:
        compute_ms = 0.0
    edges = p.edges
    # the parity token hashes the TRAFFIC multiset, not var names: a
    # semantics-preserving rewrite (graph fusion renames the anchor
    # vars but moves the same bytes over the same rings) must keep the
    # token stable, or the fusion pass's fingerprint-parity guard would
    # roll back every fusion on a partitioned program
    fp = hashlib.sha1(repr((
        sorted(axis_sizes.items()), rules, zero_stage,
        sorted((e.direction, e.kind, e.mesh_axis, e.nranks,
                e.payload_bytes, e.reason) for e in edges))).encode()
    ).hexdigest()
    return ShardingPlan(
        rules=rules, mesh_axes=dict(axis_sizes),
        batch_size=int(batch_size), zero_stage=zero_stage,
        link_bw=link_bw,
        specs={k: v for k, v in p.spec.items() if v is not None},
        edges=edges, diagnostics=p.diags,
        payload_bytes=sum(e.payload_bytes for e in edges),
        wire_bytes=sum(e.wire_bytes for e in edges),
        est_ms=sum(e.est_ms for e in edges),
        compute_ms=compute_ms, fingerprint=fp)


# ---------------------------------------------------------------------------
# consumers
# ---------------------------------------------------------------------------

def stamp_attrs(plan: Optional[ShardingPlan]) -> Optional[dict]:
    """The machine-readable ``_attrs["verify"]["sharding"]`` payload
    (tools/analyze, the smoke gates, choose_rules auditing)."""
    if plan is None:
        return None
    return {
        "rules": plan.rules,
        "mesh_axes": dict(plan.mesh_axes),
        "zero_stage": plan.zero_stage,
        "n_edges": len(plan.edges),
        "n_unexplained": len(plan.unexplained),
        "payload_bytes": plan.payload_bytes,
        "wire_bytes": plan.wire_bytes,
        "est_ms": round(plan.est_ms, 6),
        "compute_ms": round(plan.compute_ms, 6),
        "fingerprint": plan.fingerprint,
        "resh_token": plan.resh_token,
        "edges": [
            (e.direction, e.kind, e.mesh_axis, e.var, e.payload_bytes,
             e.wire_bytes, e.reason, e.exact) for e in plan.edges],
    }


def as_comms_plan(plan: ShardingPlan):
    """Project a sharding plan onto the ``analysis.comms`` CommsPlan
    shape, so the executor's pre-bound byte-cell accounting, the comms
    monitor's wait/wire decomposition, and the gangtop COMM column all
    work unchanged on pjit-partitioned programs (which launch no
    explicit ``c_*`` ops for plan_comms to find)."""
    from .comms import CollectiveCost, CommsPlan
    nranks = 1
    for s in plan.mesh_axes.values():
        nranks *= max(int(s), 1)
    collectives = [
        CollectiveCost(
            path="gspmd", pos=i, op=e.collective_op, ring_id=0,
            dtype=e.dtype, shape=tuple(e.shape),
            payload_bytes=e.payload_bytes, wire_bytes=e.wire_bytes,
            est_ms=e.est_ms)
        for i, e in enumerate(plan.edges)]
    return CommsPlan(
        nranks=nranks, link_bw=plan.link_bw,
        batch_size=plan.batch_size, collectives=collectives,
        payload_bytes=plan.payload_bytes, wire_bytes=plan.wire_bytes,
        est_ms=plan.est_ms, compute_ms=plan.compute_ms,
        fingerprint="gspmd:" + plan.fingerprint)


def runtime_comms_plan(program: Program, fetch_names=(),
                       batch_size: int = 1):
    """Executor hook (``_resolve_comms`` fallback): the reshard plan of
    a partitioned program at the REAL feed batch, as a CommsPlan — or
    None when the program is unpartitioned."""
    plan = plan_sharding(program, fetch_names, batch_size=batch_size)
    if plan is None or not plan.edges:
        return None
    return as_comms_plan(plan)


def check_decode_hostable(program: Program, raise_on_violation=True):
    """Serving-side gate: the paged KV cache (``serving.kv_cache``)
    allocates full per-head pages and full unsharded decode params on
    ONE chip (``params_from_scope`` pulls whole arrays by name), so an
    mp-sharded decode-path program cannot be hosted until the
    GSPMD-serving arc lands.  Returns the offending ``(param, spec)``
    list; raises ValueError naming them when ``raise_on_violation``."""
    stamp = program._attrs.get("partition") or {}
    offending = [
        (name, tuple(spec))
        for name, spec in sorted((stamp.get("params") or {}).items())
        if any(ax is not None and ax != "dp" for ax in spec)]
    if offending and raise_on_violation:
        named = ", ".join(f"{n}={s}" for n, s in offending)
        raise ValueError(
            f"decode program is model-parallel sharded (rules="
            f"{stamp.get('rules')!r}): the paged KV cache hosts full "
            f"per-head pages and unsharded params on one chip and "
            f"cannot serve these specs: {named}. Serve a replicated "
            "(or dp-only) program, or gather the params before "
            "building the DecodeEngine.")
    return offending
