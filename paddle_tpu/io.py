"""Model persistence: save/load params, persistables, and inference models.

ref ``python/paddle/fluid/io.py``: save_params:254, save_persistables:487,
load_persistables:726, save_inference_model:933, load_inference_model:1113 —
backed by the reference's ``save``/``load``/``save_combine``/``load_combine``
ops (``operators/save_op.cc:25``, ``load_op.cc:22``) serializing LoDTensors.

TPU-native format: one directory per model; tensors stored as ``.npy``
(separate files, one per var — the reference's default) or a single
``npz`` when ``filename`` is given (≈ save_combine); the program is the
JSON ProgramDesc (``Program.serialize_to_string``) in ``__model__``.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from .framework.core import Program, Variable, default_main_program
from .framework.scope import Scope, global_scope

__all__ = [
    "save_vars", "save_params", "save_persistables",
    "load_vars", "load_params", "load_persistables",
    "save_inference_model", "load_inference_model",
    "get_program_persistable_vars",
]


def _is_persistable(var: Variable) -> bool:
    return bool(var.persistable) and var.type not in ("raw", "step_scopes")


def _is_parameter(var: Variable) -> bool:
    return bool(var.is_parameter)


def get_program_persistable_vars(program: Program) -> List[Variable]:
    return [v for v in program.list_vars() if _is_persistable(v)]


def _fsync_dir(path: str) -> None:
    """fsync a directory entry so renames inside it survive a crash (a
    file's own fsync does not persist its directory entry)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return                   # platform without dir-open (best effort)
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _atomic_publish_dir(tmpdir: str, dst: str,
                        preserve_existing: bool = True) -> None:
    """Publish a fully-written staging dir at ``dst`` near-atomically.

    A crash during the (long) blob-writing phase touches only ``tmpdir``
    — the previously-good ``dst`` stays intact, which is the whole point:
    the old in-place writer corrupted a good param dir the moment it
    started overwriting.  When ``dst`` already exists, files it holds that
    the staging dir does not (e.g. ``__model__`` written by
    ``save_inference_model``, or a user's ``assets/`` subdir) are first
    copied in, then the dirs swap via two renames (POSIX rename cannot
    replace a non-empty dir in one shot; the window between the renames
    is two syscalls wide, vs. the entire serialization before).  A
    process dying INSIDE that window leaves the good data parked at
    ``<dst>.old.<pid>`` — :func:`_recover_interrupted_swap` (run by
    ``load_vars``) renames it back, so even that crash is recoverable."""
    import shutil
    dst = os.path.abspath(dst)
    if os.path.isdir(dst):
        if preserve_existing:
            for entry in os.listdir(dst):
                s = os.path.join(dst, entry)
                d = os.path.join(tmpdir, entry)
                if os.path.exists(d):
                    continue     # the fresh save wins
                # hard-link, not copy: tmpdir is a sibling on the same
                # filesystem by construction, so preserving a large
                # foreign assets/ tree costs directory entries, not a
                # re-read/re-write of its bytes (copy2 fallback covers
                # filesystems without link support)
                try:
                    if os.path.isdir(s):
                        shutil.copytree(s, d, copy_function=os.link)
                    elif os.path.isfile(s):
                        os.link(s, d)
                except OSError:
                    if os.path.isdir(s):
                        shutil.rmtree(d, ignore_errors=True)
                        shutil.copytree(s, d)
                    elif os.path.isfile(s):
                        shutil.copy2(s, d)
        old = dst + f".old.{os.getpid()}"
        shutil.rmtree(old, ignore_errors=True)
        os.rename(dst, old)
        try:
            os.rename(tmpdir, dst)
        except BaseException:
            os.rename(old, dst)      # roll the good dir back into place
            raise
        shutil.rmtree(old, ignore_errors=True)
    else:
        os.replace(tmpdir, dst)
    _fsync_dir(os.path.dirname(dst) or ".")


def _recover_interrupted_swap(dirname: str) -> None:
    """If ``dirname`` is missing but a ``<dirname>.old.<pid>`` sibling
    exists, a saver died inside the two-rename publish window — the
    sibling IS the last complete save, so rename it back into place
    (newest first when several crashed savers left debris)."""
    import glob
    import warnings
    dst = os.path.abspath(dirname)
    if os.path.isdir(dst):
        return
    leftovers = sorted(glob.glob(dst + ".old.*"), key=os.path.getmtime)
    if not leftovers:
        return
    warnings.warn(
        f"param dir {dirname!r} missing but {leftovers[-1]!r} exists — a "
        "save died mid-publish; recovering the last complete state")
    os.rename(leftovers[-1], dst)
    _fsync_dir(os.path.dirname(dst) or ".")


def _scope_value(scope: Scope, name: str) -> np.ndarray:
    v = scope.find_var(name)
    if v is None:
        raise ValueError(f"variable {name!r} has no value in scope — run the "
                         f"startup program before saving")
    import jax
    if isinstance(v, jax.Array) and not v.is_fully_addressable:
        # multi-host sharded state (GSPMD meshes spanning processes,
        # ZeRO-1 accumulators): gather the global value before
        # serializing — np.asarray alone cannot see remote shards
        from jax.experimental import multihost_utils as mhu
        return np.asarray(mhu.process_allgather(v, tiled=True))
    return np.asarray(v)


def save_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """ref io.py save_vars — writes each var (or a combined file).

    Atomic: blobs + ``__meta__.json`` are staged into a temp sibling dir,
    fsynced, and swapped into place — a crash mid-save leaves the
    previously-good param dir untouched instead of half-overwritten.

    Single-writer contract: concurrent saves of the SAME dirname from two
    processes now fail loudly at the swap (one rank's rename finds the dir
    gone) — multi-rank jobs must save from one rank, as they always had
    to for a coherent snapshot (the old in-place writer interleaved both
    ranks' blobs into one silently torn directory)."""
    import shutil
    program = main_program or default_main_program()
    scope = scope or global_scope()
    if vars is None:
        vars = [v for v in program.list_vars()
                if (predicate or _is_persistable)(v)]
    dst = os.path.abspath(dirname)
    os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
    tmpdir = dst.rstrip(os.sep) + f".tmp.{os.getpid()}"
    shutil.rmtree(tmpdir, ignore_errors=True)
    os.makedirs(tmpdir)
    try:
        # canonical C-order blobs: device fetches can come back
        # Fortran-contiguous, which non-numpy consumers (demo_predictor.cc)
        # would reject
        arrays = {v.name: np.ascontiguousarray(_scope_value(scope, v.name))
                  for v in vars}
        # bf16 params travel as a uint16 bit view ('<u2' npy): numpy can't
        # round-trip the ml_dtypes descr, and the native predictor widens
        # the u2 payload back to f32 (demo_predictor.cc LoadNpy); the true
        # dtype is recorded in the meta so load_vars can view it back
        dtypes = {name: str(arr.dtype) for name, arr in arrays.items()}
        arrays = {name: (arr.view(np.uint16)
                         if str(arr.dtype) == "bfloat16" else arr)
                  for name, arr in arrays.items()}
        if filename is not None:
            np.savez(os.path.join(tmpdir, filename), **arrays)
        else:
            for name, arr in arrays.items():
                np.save(os.path.join(tmpdir, name.replace("/", "__")), arr)
        meta = {name: {"shape": list(arr.shape), "dtype": dtypes[name]}
                for name, arr in arrays.items()}
        from .framework.core import PROGRAM_FORMAT_VERSION
        from . import __version__
        with open(os.path.join(tmpdir, "__meta__.json"), "w") as f:
            json.dump({"filename": filename, "vars": meta,
                       # ref framework/version.h kCurTensorVersion: stamp
                       # the parameter blobs so cross-version loads are
                       # detectable
                       "version": PROGRAM_FORMAT_VERSION,
                       "framework_version": __version__}, f)
            f.flush()
            os.fsync(f.fileno())
        for entry in os.listdir(tmpdir):
            if entry == "__meta__.json":
                continue         # already synced above
            fd = os.open(os.path.join(tmpdir, entry), os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        _fsync_dir(tmpdir)
        _atomic_publish_dir(tmpdir, dst)
    except BaseException:
        shutil.rmtree(tmpdir, ignore_errors=True)
        raise


def save_params(executor=None, dirname=None, main_program=None, filename=None,
                scope=None):
    """ref io.py:254 — trainable parameters only."""
    save_vars(executor, dirname, main_program, None, _is_parameter,
              filename, scope)


def save_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, scope=None):
    """ref io.py:487 — params + optimizer accumulators + BN stats etc."""
    save_vars(executor, dirname, main_program, None, _is_persistable,
              filename, scope)


def load_vars(executor=None, dirname=None, main_program=None, vars=None,
              predicate=None, filename=None, scope=None):
    """ref io.py load_vars."""
    program = main_program or default_main_program()
    scope = scope or global_scope()
    _recover_interrupted_swap(dirname)
    meta_path = os.path.join(dirname, "__meta__.json")
    if os.path.exists(meta_path):
        from .framework.core import PROGRAM_FORMAT_VERSION
        with open(meta_path) as f:
            meta = json.load(f)
        fmt = int(meta.get("version", 0))
        if fmt > PROGRAM_FORMAT_VERSION:
            raise ValueError(
                f"parameter blobs in {dirname} have format version {fmt}, "
                f"newer than this framework supports "
                f"({PROGRAM_FORMAT_VERSION}; saved by framework "
                f"{meta.get('framework_version', '<unknown>')!r})")
    if vars is None:
        vars = [v for v in program.list_vars()
                if (predicate or _is_persistable)(v)]
    var_meta = (meta.get("vars", {}) if os.path.exists(meta_path) else {})

    def _restore(name, arr):
        # u2 blobs tagged bfloat16 in the meta: view the bits back
        if var_meta.get(name, {}).get("dtype") == "bfloat16" and \
                arr.dtype == np.uint16:
            import jax.numpy as jnp
            arr = arr.view(jnp.bfloat16.dtype)
        return arr

    if filename is not None:
        path = os.path.join(dirname, filename)
        if not os.path.exists(path):
            path = path + ".npz"
        data = np.load(path)
        missing = [v.name for v in vars if v.name not in data]
        if missing:
            raise ValueError(
                f"combined checkpoint {path} is missing vars: {missing}")
        for v in vars:
            scope.set_var(v.name, _restore(v.name, data[v.name]))
    else:
        for v in vars:
            path = os.path.join(dirname, v.name.replace("/", "__") + ".npy")
            if os.path.exists(path):
                scope.set_var(v.name, _restore(v.name, np.load(path)))
            else:
                raise ValueError(f"missing saved var file {path}")


def load_params(executor=None, dirname=None, main_program=None, filename=None,
                scope=None):
    load_vars(executor, dirname, main_program, None, _is_parameter,
              filename, scope)


def load_persistables(executor=None, dirname=None, main_program=None,
                      filename=None, scope=None):
    """ref io.py:726."""
    load_vars(executor, dirname, main_program, None, _is_persistable,
              filename, scope)


def save_inference_model(dirname, feeded_var_names: Sequence[str],
                         target_vars: Sequence, executor=None,
                         main_program: Optional[Program] = None,
                         model_filename=None, params_filename=None,
                         export_for_deployment=True, scope=None):
    """ref io.py:933 — prune to fetch targets, switch to test mode, save
    program + params.  Returns the feed names actually needed."""
    program = main_program or default_main_program()
    scope = scope or global_scope()
    target_names = [t.name if isinstance(t, Variable) else t
                    for t in target_vars]
    infer = program.clone(for_test=True)._prune(target_names)
    os.makedirs(dirname, exist_ok=True)

    # only persistables the pruned program still references
    used = set()
    for op in infer.global_block().ops:
        used.update(op.input_arg_names())
        used.update(op.output_arg_names())
    pvars = [v for v in infer.list_vars() if _is_persistable(v)
             and v.name in used]

    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "wb") as f:
        payload = json.loads(infer.serialize_to_string())
        payload["feed_names"] = list(feeded_var_names)
        payload["fetch_names"] = list(target_names)
        f.write(json.dumps(payload).encode("utf-8"))

    save_vars(executor, dirname, infer, pvars, None,
              params_filename, scope)
    return list(feeded_var_names)


def load_inference_model(dirname, executor=None, model_filename=None,
                         params_filename=None, scope=None):
    """ref io.py:1113 → (program, feed_names, fetch_vars-as-names)."""
    scope = scope or global_scope()
    _recover_interrupted_swap(dirname)
    model_path = os.path.join(dirname, model_filename or "__model__")
    with open(model_path, "rb") as f:
        payload = json.loads(f.read().decode("utf-8"))
    program = Program.parse_from_string(json.dumps(payload).encode("utf-8"))
    feed_names = payload.get("feed_names", [])
    fetch_names = payload.get("fetch_names", [])
    # load exactly the vars that were saved (__meta__.json) — the pruned
    # program's var table still lists training-only persistables (lr,
    # optimizer accumulators) that save_inference_model intentionally omits
    meta_path = os.path.join(dirname, "__meta__.json")
    saved = None
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            saved = set(json.load(f)["vars"])
    vars = [v for v in program.list_vars() if _is_persistable(v)
            and (saved is None or v.name in saved)]
    load_vars(executor, dirname, program, vars, None,
              params_filename, scope)
    return program, feed_names, fetch_names
