"""Program → graphviz drawer CLI (ref ``python/paddle/fluid/net_drawer.py``:
draw_graph(startup, main) emitting a DOT file per program).  The rendering
itself shares the debugger's DOT emitter."""

from __future__ import annotations

import itertools
from typing import Optional

from .debugger import draw_block_graphviz
from .framework.core import Program

__all__ = ["draw_graph"]

_counter = itertools.count()


def unique_id():
    return next(_counter)


def draw_graph(startup_program: Program, main_program: Program,
               graph_attr=None, name: str = "graph",
               output: Optional[str] = None, **kwargs):
    """Write ``<output or name>.dot`` for the main program (the reference
    draws ops as nodes and vars as edges; our DOT emitter does the same)."""
    path = output or (name + ".dot")
    if not path.endswith(".dot"):
        path += ".dot"
    draw_block_graphviz(main_program.global_block(), path=path)
    return path
