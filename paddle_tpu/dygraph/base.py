"""Dygraph mode switch + conversion helpers.

ref ``python/paddle/fluid/dygraph/base.py``: ``guard()``, ``enabled()``,
``to_variable()``, ``no_grad``.
"""

from __future__ import annotations

import contextlib
import functools

import numpy as np

from .tracer import VarBase, default_tracer

_in_dygraph = False


def enabled() -> bool:
    return _in_dygraph


def in_dygraph_mode() -> bool:
    return _in_dygraph


@contextlib.contextmanager
def guard(place=None):
    """``with fluid.dygraph.guard():`` — enables eager execution."""
    global _in_dygraph
    prev = _in_dygraph
    _in_dygraph = True
    try:
        yield
    finally:
        _in_dygraph = prev
        if not prev:  # only the outermost guard owns/clears the tape
            default_tracer().tape.clear()


def to_variable(value, name=None, zero_copy=None) -> VarBase:
    """numpy → eager VarBase (ref dygraph/base.py to_variable)."""
    if isinstance(value, VarBase):
        return value
    arr = np.asarray(value)
    return VarBase(arr, name=name, stop_gradient=False)


class no_grad:
    """Context manager AND decorator disabling grad taping
    (ref dygraph/base.py no_grad)."""

    def __enter__(self):
        t = default_tracer()
        self._prev = t.grad_enabled()
        t.set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        default_tracer().set_grad_enabled(self._prev)
        return False

    def __new__(cls, func=None):
        if func is not None and callable(func):
            @functools.wraps(func)
            def wrapper(*args, **kwargs):
                with no_grad():
                    return func(*args, **kwargs)
            return wrapper
        return super().__new__(cls)


class BackwardStrategy:
    """ref dygraph/backward_strategy.py BackwardStrategy: sort_sum_gradient
    toggles deterministic gradient accumulation order.  The vjp tape here
    accumulates in fixed reverse-topological order already (deterministic),
    so the knob is accepted and recorded for API parity."""

    def __init__(self):
        self.sort_sum_gradient = False
