"""Dygraph LR schedulers (ref ``python/paddle/fluid/dygraph/learning_rate_scheduler.py``):
stateful decay objects the optimizer calls once per step."""

from __future__ import annotations

import math

__all__ = ["LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
           "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
           "CosineDecay", "NoamDecay"]


class LearningRateDecay:
    def __init__(self, begin=0, step=1):
        self.step_num = begin
        self.step_size = step

    def __call__(self):
        lr = self.step()
        self.step_num += self.step_size
        return lr

    def step(self) -> float:
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1):
        super().__init__(begin, step)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def step(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return float(self.values[i])
        return float(self.values[len(self.boundaries)])


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.ds, self.dr, self.staircase = \
            learning_rate, decay_steps, decay_rate, staircase

    def step(self):
        div = self.step_num / self.ds
        if self.staircase:
            div = math.floor(div)
        return self.lr * math.exp(-self.dr * div)


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.ds, self.dr, self.staircase = \
            learning_rate, decay_steps, decay_rate, staircase

    def step(self):
        div = self.step_num / self.ds
        if self.staircase:
            div = math.floor(div)
        return self.lr * (self.dr ** div)


class InverseTimeDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.ds, self.dr, self.staircase = \
            learning_rate, decay_steps, decay_rate, staircase

    def step(self):
        div = self.step_num / self.ds
        if self.staircase:
            div = math.floor(div)
        return self.lr / (1 + self.dr * div)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=0.0001,
                 power=1.0, cycle=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.ds, self.end_lr, self.power, self.cycle = \
            learning_rate, decay_steps, end_learning_rate, power, cycle

    def step(self):
        n = self.step_num
        ds = self.ds
        if self.cycle:
            div = math.ceil(n / ds) or 1
            ds = ds * div
        else:
            n = min(n, ds)
        return ((self.lr - self.end_lr) * (1 - n / ds) ** self.power
                + self.end_lr)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs,
                 begin=0, step=1):
        super().__init__(begin, step)
        self.lr, self.spe, self.epochs = learning_rate, step_each_epoch, epochs

    def step(self):
        epoch = math.floor(self.step_num / self.spe)
        return self.lr * 0.5 * (math.cos(epoch * math.pi / self.epochs) + 1)


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1):
        super().__init__(begin, step)
        self.d_model, self.warmup = d_model, warmup_steps

    def step(self):
        n = max(self.step_num, 1)
        return (self.d_model ** -0.5) * min(n ** -0.5,
                                            n * self.warmup ** -1.5)
