"""Eager (define-by-run) execution: VarBase tensors + a vjp tape.

TPU-native rebuild of the reference imperative engine
(``paddle/fluid/imperative/tracer.h:31-46`` Tracer::TraceOp,
``imperative/layer.h:55,168`` VarBase/OpBase, ``imperative/engine.h`` autograd
Engine, ``imperative/gradient_accumulator.h``).

Design departure: the reference tapes grad *op descs* and re-dispatches C++
kernels on backward.  Here every traced op reuses the SAME registered JAX
lowering the static executor compiles (one kernel source of truth, exactly as
the reference shares kernels between static and dygraph), and the tape stores
the ``jax.vjp`` closure captured at forward time — backward is then a pure
reverse sweep accumulating cotangents (the Engine + GradientAccumulator role).
"""

from __future__ import annotations

import itertools
import weakref
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import registry
from ..framework.core import convert_dtype
from ..framework.executor import LowerCtx
from ..framework import unique_name

_FLOAT0 = jax.dtypes.float0


def _is_inexact(x) -> bool:
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact)


class VarBase:
    """Eager tensor (ref ``imperative/layer.h:55`` VarBase): a concrete JAX
    array + grad slot + autograd metadata."""

    def __init__(self, value, name: Optional[str] = None,
                 stop_gradient: bool = False, persistable: bool = False,
                 trainable: bool = True):
        self._value = value if isinstance(value, jax.Array) else jnp.asarray(value)
        self.name = name or unique_name.generate("eager_tmp")
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.trainable = trainable
        self.grad: Optional[jax.Array] = None

    # -- data access ---------------------------------------------------------
    @property
    def value(self):
        return self._value

    def set_value(self, v):
        if isinstance(v, VarBase):
            v = v._value
        self._value = v if isinstance(v, jax.Array) else jnp.asarray(v)

    def numpy(self) -> np.ndarray:
        return np.asarray(self._value)

    @property
    def shape(self):
        return tuple(self._value.shape)

    @property
    def dtype(self):
        return str(self._value.dtype)

    @property
    def ndim(self):
        return self._value.ndim

    def __len__(self):
        return self._value.shape[0]

    def detach(self) -> "VarBase":
        return VarBase(self._value, stop_gradient=True)

    def astype(self, dtype):
        return _trace_unary("cast", self, {"out_dtype": convert_dtype(dtype)})

    # -- autograd ------------------------------------------------------------
    def backward(self, backward_strategy=None, retain_graph: bool = False):
        """Reverse sweep of the global tape from this var
        (ref ``imperative/engine.cc`` Engine::Execute).  Accepts fluid's
        ``loss.backward(BackwardStrategy())`` call form — the strategy is
        parity-only (accumulation order is already deterministic here) and
        must not bind to retain_graph."""
        from .base import BackwardStrategy
        if backward_strategy is not None and \
                not isinstance(backward_strategy, BackwardStrategy):
            retain_graph = bool(backward_strategy)
        default_tracer().backward(self, retain_graph=retain_graph)

    def gradient(self) -> Optional[np.ndarray]:
        return None if self.grad is None else np.asarray(self.grad)

    def clear_gradient(self):
        self.grad = None

    # -- operator sugar (same op set as static Variable) ---------------------
    def _binary(self, other, op_type, reverse=False):
        x, y = (other, self) if reverse else (self, other)
        return _trace_binary(op_type, x, y)

    def __add__(self, o): return self._binary(o, "elementwise_add")
    def __radd__(self, o): return self._binary(o, "elementwise_add", True)
    def __sub__(self, o): return self._binary(o, "elementwise_sub")
    def __rsub__(self, o): return self._binary(o, "elementwise_sub", True)
    def __mul__(self, o): return self._binary(o, "elementwise_mul")
    def __rmul__(self, o): return self._binary(o, "elementwise_mul", True)
    def __truediv__(self, o): return self._binary(o, "elementwise_div")
    def __rtruediv__(self, o): return self._binary(o, "elementwise_div", True)
    def __pow__(self, o): return self._binary(o, "elementwise_pow")
    def __neg__(self): return _trace_unary("scale", self, {"scale": -1.0})
    def __getitem__(self, idx):
        return default_tracer().trace_fn(
            lambda v: v[idx], [self])[0]

    def __repr__(self):
        return (f"VarBase(name={self.name}, shape={self.shape}, "
                f"dtype={self.dtype}, stop_gradient={self.stop_gradient})\n"
                f"{self.numpy()}")


def _trace_binary(op_type, x, y):
    t = default_tracer()
    outs = t.trace_op(op_type, {"X": [x], "Y": [y]}, {"axis": -1})
    return outs["Out"][0]


def _trace_unary(op_type, x, attrs):
    t = default_tracer()
    outs = t.trace_op(op_type, {"X": [x]}, attrs)
    return outs["Out"][0]


class TapeNode:
    """One recorded forward op: inputs, weak output refs, the vjp closure."""

    __slots__ = ("inputs", "outputs", "vjp_fn", "out_meta")

    def __init__(self, inputs: List[Optional[VarBase]],
                 outputs: List[VarBase], vjp_fn, out_meta):
        self.inputs = inputs
        # weakrefs: a dead output can no longer receive/propagate grad
        self.outputs = [weakref.ref(o) for o in outputs]
        self.vjp_fn = vjp_fn
        self.out_meta = out_meta  # [(shape, dtype)] for zero-cotangent synth


_seed_counter = itertools.count(1)


class Tracer:
    """ref ``imperative/tracer.h:31`` — owns the tape + grad-enabled flag."""

    def __init__(self):
        self.tape: List[TapeNode] = []
        self._grad_enabled = True

    # -- mode ----------------------------------------------------------------
    def grad_enabled(self) -> bool:
        return self._grad_enabled

    def set_grad_enabled(self, flag: bool):
        self._grad_enabled = flag

    # -- forward -------------------------------------------------------------
    def trace_op(self, op_type: str, ins: Dict[str, List[Any]],
                 attrs: Optional[Dict[str, Any]] = None,
                 stop_gradient: bool = False) -> Dict[str, List[VarBase]]:
        """Run one op eagerly through its registered lowering; tape it when
        any input requires grad (ref ``Tracer::TraceOp`` + TraceBackward)."""
        info = registry.get_op_info(op_type)
        if info.raw:
            raise TypeError(
                f"op {op_type!r} is a control-flow (raw) op; use the python "
                f"control flow of dygraph mode instead")
        attrs = dict(attrs or {})
        slots = list(ins.keys())
        flat_vb: List[Optional[VarBase]] = []
        flat_vals: List[Any] = []
        for slot in slots:
            for v in ins[slot]:
                if isinstance(v, VarBase):
                    flat_vb.append(v)
                    flat_vals.append(v._value)
                else:
                    flat_vb.append(None)
                    flat_vals.append(None if v is None else jnp.asarray(v))

        ctx = LowerCtx(next(_seed_counter))
        out_struct: Dict[str, int] = {}

        def fwd(*flat):
            it = iter(flat)
            d = {slot: [next(it) for _ in ins[slot]] for slot in slots}
            outs = info.lower(ctx, d, attrs) or {}
            out_slots = sorted(outs)
            out_struct.clear()
            out_struct.update({s: len(outs[s]) for s in out_slots})
            return [o for s in out_slots for o in outs[s]]

        track = (self._grad_enabled and not stop_gradient and not info.no_grad
                 and any(vb is not None and not vb.stop_gradient
                         and _is_inexact(vb._value) for vb in flat_vb))
        if track:
            flat_outs, vjp_fn = jax.vjp(fwd, *flat_vals)
        else:
            flat_outs, vjp_fn = fwd(*flat_vals), None

        out_vbs = [VarBase(o, stop_gradient=not track) for o in flat_outs]
        if track:
            meta = [(o.shape, o.dtype) for o in flat_outs]
            self.tape.append(TapeNode(flat_vb, out_vbs, vjp_fn, meta))

        result: Dict[str, List[VarBase]] = {}
        i = 0
        for slot in sorted(out_struct):
            n = out_struct[slot]
            result[slot] = out_vbs[i:i + n]
            i += n
        return result

    def trace_fn(self, fn, inputs: List[Any]) -> List[VarBase]:
        """Tape an arbitrary jax-traceable fn(*arrays)->array|list — used for
        python-level tensor sugar (indexing etc.) that has no op type."""
        flat_vb = [v if isinstance(v, VarBase) else None for v in inputs]
        flat_vals = [v._value if isinstance(v, VarBase) else jnp.asarray(v)
                     for v in inputs]

        def wrapped(*args):
            out = fn(*args)
            return list(out) if isinstance(out, (list, tuple)) else [out]

        track = (self._grad_enabled
                 and any(vb is not None and not vb.stop_gradient
                         and _is_inexact(vb._value) for vb in flat_vb))
        if track:
            flat_outs, vjp_fn = jax.vjp(wrapped, *flat_vals)
        else:
            flat_outs, vjp_fn = wrapped(*flat_vals), None
        out_vbs = [VarBase(o, stop_gradient=not track) for o in flat_outs]
        if track:
            meta = [(o.shape, o.dtype) for o in flat_outs]
            self.tape.append(TapeNode(flat_vb, out_vbs, vjp_fn, meta))
        return out_vbs

    # -- backward (the Engine) -----------------------------------------------
    def backward(self, root: VarBase, retain_graph: bool = False):
        if root.grad is None:
            root.grad = jnp.ones_like(root._value)
        for node in reversed(self.tape):
            cts, any_grad = [], False
            for ref, (shape, dtype) in zip(node.outputs, node.out_meta):
                o = ref()
                g = o.grad if o is not None else None
                if g is not None and jnp.issubdtype(jnp.dtype(dtype),
                                                    jnp.inexact):
                    cts.append(g)
                    any_grad = True
                elif jnp.issubdtype(jnp.dtype(dtype), jnp.inexact):
                    cts.append(jnp.zeros(shape, dtype))
                else:
                    cts.append(np.zeros(shape, _FLOAT0))
            if not any_grad:
                continue
            in_grads = node.vjp_fn(cts)
            for vb, g in zip(node.inputs, in_grads):
                if vb is None or vb.stop_gradient or g is None:
                    continue
                if g.dtype == _FLOAT0:
                    continue
                # GradientAccumulator (imperative/gradient_accumulator.h)
                vb.grad = g if vb.grad is None else vb.grad + g
        if not retain_graph:
            self.tape.clear()


_default_tracer = Tracer()


def default_tracer() -> Tracer:
    return _default_tracer
