"""Dygraph data parallelism.

ref ``python/paddle/fluid/dygraph/parallel.py`` (Env:33, DataParallel:84 with
scale_loss:150 / apply_collective_grads:201) + ``imperative/nccl_context.h``.

TPU-native realization: gradients are averaged across *processes* with
``jax.experimental.multihost_utils`` when a multi-process JAX runtime is
initialized (jax.distributed ≈ the reference's NCCLParallelContext bootstrap),
and are exact no-ops single-process — the same semantics as the reference
where world_size==1 short-circuits.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .layers import Layer
from .tracer import VarBase


class ParallelEnv:
    """ref dygraph/parallel.py Env:33 — reads the launcher's env vars."""

    def __init__(self):
        self._nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._dev_id = int(os.getenv("FLAGS_selected_tpus",
                                     os.getenv("FLAGS_selected_gpus", "0")))
        eps = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
        self._trainer_endpoints = eps.split(",") if eps else []
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._dev_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


Env = ParallelEnv


def prepare_context(strategy=None):
    """Initialize the multi-process runtime (≈ NCCLParallelContext::Init:
    exchange ids + create comms).  Uses jax.distributed when endpoints are
    configured; single-process otherwise."""
    env = ParallelEnv()
    if env.nranks > 1 and env.trainer_endpoints:
        coordinator = env.trainer_endpoints[0]
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=env.nranks,
                process_id=env.local_rank)
        except (RuntimeError, ValueError):
            pass  # already initialized
    return env


class DataParallel(Layer):
    """ref dygraph/parallel.py:84 — wraps a Layer; after ``loss.backward()``
    call ``apply_collective_grads()`` to average grads across ranks."""

    def __init__(self, layers: Layer, strategy=None):
        super().__init__("data_parallel")
        self._layers = layers
        self._env = ParallelEnv()

    @property
    def nranks(self):
        return max(self._env.nranks, 1)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss: VarBase) -> VarBase:
        """ref parallel.py:150 — pre-scale loss by 1/nranks so the summed
        collective equals the global mean."""
        if self.nranks <= 1:
            return loss
        return loss * (1.0 / self.nranks)

    def apply_collective_grads(self):
        """ref parallel.py:201 — allreduce-sum every trainable grad.  Uses a
        single fused psum over the process group (the reference coalesced
        grads into chunks for the same reason — one ring launch)."""
        if self.nranks <= 1:
            return
        from jax.experimental import multihost_utils
        params = [p for p in self._layers.parameters() if p.grad is not None]
        if not params:
            return
        flat = [p.grad for p in params]
        summed = multihost_utils.process_allgather(
            jnp.concatenate([jnp.ravel(g) for g in flat]))
        total = jnp.sum(summed, axis=0)
        off = 0
        for p in params:
            n = int(np.prod(p.grad.shape))
            p.grad = total[off:off + n].reshape(p.grad.shape)
            off += n

    # delegate state access
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_dict(self, *a, **k):
        return self._layers.set_dict(*a, **k)

    load_dict = set_dict


def scale_loss(loss, nranks=None):
    n = nranks if nranks is not None else ParallelEnv().nranks
    return loss * (1.0 / n) if n > 1 else loss
