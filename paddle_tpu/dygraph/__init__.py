"""Dygraph (define-by-run) mode — ref ``python/paddle/fluid/dygraph/`` +
``paddle/fluid/imperative/`` (see SURVEY.md §2.8)."""

from . import nn  # noqa
from .base import enabled, guard, in_dygraph_mode, no_grad, to_variable  # noqa
from .checkpoint import load_dygraph, save_dygraph  # noqa
from .layers import Layer  # noqa
from .learning_rate_scheduler import (CosineDecay, ExponentialDecay,  # noqa
                                      InverseTimeDecay, LearningRateDecay,
                                      NaturalExpDecay, NoamDecay,
                                      PiecewiseDecay, PolynomialDecay)
from .nn import (FC, NCE, BatchNorm, BilinearTensorProduct, Conv2D,  # noqa
                 Conv2DTranspose, Conv3D, Dropout, Embedding, GroupNorm,
                 GRUUnit, LayerNorm, Linear, Pool2D, PRelu, RowConv,
                 SequenceConv, SpectralNorm, TreeConv)
from .parallel import DataParallel, Env, ParallelEnv, prepare_context  # noqa
from .tracer import Tracer, VarBase, default_tracer  # noqa
from .base import BackwardStrategy  # noqa
