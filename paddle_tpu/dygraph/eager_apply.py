"""Eager block shim: lets static-graph Optimizer._append_optimize_op run
unchanged in dygraph mode by executing each appended op immediately through
its registered lowering (the reference's shared-kernel design —
``imperative/prepared_operator.h`` prepares the same kernels the static
executor dispatches)."""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework import registry
from ..framework.executor import LowerCtx
from .tracer import VarBase

_seed = itertools.count(10_000_000)


class EagerBlock:
    """Duck-types the subset of Block that optimizer _append_optimize_op and
    clip/regularizer helpers use: append_op + create_var."""

    def __init__(self, lr_value: float):
        self.lr = lr_value
        self._tmp: Dict[str, Any] = {}

    def create_var(self, name=None, shape=None, dtype=None, **kw) -> VarBase:
        v = VarBase(np.zeros(shape or [1], dtype or "float32"),
                    name=name, trainable=False)
        v.stop_gradient = True
        return v

    def _resolve(self, slot: str, v):
        if isinstance(v, VarBase):
            return v.value
        if v is None and slot == "LearningRate":
            return jnp.asarray([self.lr], jnp.float32)
        if hasattr(v, "name") and not hasattr(v, "numpy"):
            # a static Variable leaked in (the learning-rate var) — use the
            # eager lr value
            if slot == "LearningRate":
                return jnp.asarray([self.lr], jnp.float32)
            raise TypeError(
                f"static Variable {v.name!r} passed to eager optimizer "
                f"(slot {slot})")
        return jnp.asarray(v)

    def append_op(self, type: str, inputs: Optional[Dict] = None,
                  outputs: Optional[Dict] = None,
                  attrs: Optional[Dict] = None):
        info = registry.get_op_info(type)
        ins = {slot: [self._resolve(slot, v) for v in vs]
               for slot, vs in (inputs or {}).items()}
        outs = info.lower(LowerCtx(next(_seed)), ins, dict(attrs or {})) or {}
        for slot, targets in (outputs or {}).items():
            vals = outs.get(slot, [])
            for tgt, val in zip(targets, vals):
                if isinstance(tgt, VarBase):
                    tgt.set_value(val)
        return outs


def eager_clip_grads(params_grads: List[Tuple[VarBase, Any]], grad_clip):
    """Eager realization of the three reference clip attrs (ref clip.py)."""
    if grad_clip is None or not params_grads:
        return params_grads
    from ..clip import (GradientClipByGlobalNorm, GradientClipByNorm,
                        GradientClipByValue)
    if isinstance(grad_clip, GradientClipByGlobalNorm):
        gn = jnp.sqrt(sum(jnp.sum(jnp.square(g)) for _, g in params_grads))
        scale = grad_clip.clip_norm / jnp.maximum(gn, grad_clip.clip_norm)
        return [(p, g * scale) for p, g in params_grads]
    if isinstance(grad_clip, GradientClipByNorm):
        out = []
        for p, g in params_grads:
            n = jnp.sqrt(jnp.sum(jnp.square(g)))
            out.append((p, g * jnp.minimum(1.0, grad_clip.clip_norm /
                                           jnp.maximum(n, 1e-12))))
        return out
    if isinstance(grad_clip, GradientClipByValue):
        return [(p, jnp.clip(g, grad_clip.min, grad_clip.max))
                for p, g in params_grads]
    return params_grads
