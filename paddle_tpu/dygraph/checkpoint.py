"""Dygraph state-dict persistence (ref ``python/paddle/fluid/dygraph/checkpoint.py``
save_dygraph/load_dygraph)."""

from __future__ import annotations

import os
import pickle
from typing import Dict, Optional, Tuple

import numpy as np

from .tracer import VarBase

_PARAMS_SUFFIX = ".pdparams"
_OPT_SUFFIX = ".pdopt"


def _to_numpy_dict(state: Dict) -> Dict[str, np.ndarray]:
    out = {}
    for k, v in state.items():
        out[k] = v.numpy() if isinstance(v, VarBase) else np.asarray(v)
    return out


def save_dygraph(state_dict: Dict, model_path: str):
    """Save a Layer.state_dict() (or optimizer state dict) to
    ``model_path + '.pdparams'`` (ref checkpoint.py save_dygraph)."""
    is_opt = any(not isinstance(v, (VarBase, np.ndarray)) and
                 not hasattr(v, "shape") for v in state_dict.values())
    suffix = _OPT_SUFFIX if is_opt else _PARAMS_SUFFIX
    d = os.path.dirname(model_path)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = {}
    for k, v in state_dict.items():
        payload[k] = (v.numpy() if isinstance(v, VarBase)
                      else np.asarray(v) if hasattr(v, "shape") else v)
    with open(model_path + suffix, "wb") as f:
        pickle.dump(payload, f, protocol=2)


def load_dygraph(model_path: str) -> Tuple[Optional[Dict], Optional[Dict]]:
    """Load (param_state, opt_state); either may be None
    (ref checkpoint.py load_dygraph)."""
    params, opt = None, None
    if os.path.exists(model_path + _PARAMS_SUFFIX):
        with open(model_path + _PARAMS_SUFFIX, "rb") as f:
            params = pickle.load(f)
    if os.path.exists(model_path + _OPT_SUFFIX):
        with open(model_path + _OPT_SUFFIX, "rb") as f:
            opt = pickle.load(f)
    if params is None and opt is None:
        raise ValueError(f"no checkpoint found at {model_path}(.pdparams/.pdopt)")
    return params, opt
