"""Layer: the dygraph module base class.

ref ``python/paddle/fluid/dygraph/layers.py`` (Layer) and
``imperative/layer.h:314``: parameter/sublayer registration via attribute
assignment, ``create_parameter``, ``parameters()``, ``state_dict``/
``set_dict``, train/eval mode.
"""

from __future__ import annotations

import itertools
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..framework import registry, unique_name
from ..framework.core import Program, convert_dtype
from ..framework.executor import LowerCtx, _ExecState, run_block
from ..initializer import (ConstantInitializer, Initializer,
                           _global_bias_initializer,
                           _global_weight_initializer)
from ..param_attr import ParamAttr
from .tracer import VarBase

_init_seed = itertools.count(1)


def eager_initialize(shape, dtype, initializer: Initializer,
                     seed: Optional[int] = None) -> VarBase:
    """Run a (startup-op-appending) initializer eagerly: build a one-var
    scratch block, append the init op, execute it through the same lowerings
    the startup program uses — one init semantics for static and dygraph."""
    prog = Program.__new__(Program)
    prog.id = -1
    prog._version = 0
    prog.random_seed = 0
    prog._attrs = {}
    prog._current_block_idx = 0
    from ..framework.core import Block
    prog.blocks = [Block(prog, 0)]
    b = prog.global_block()
    v = b.create_var(name="__param__", shape=shape, dtype=dtype,
                     persistable=True)
    initializer(v, b)
    ctx = LowerCtx(seed if seed is not None else next(_init_seed))
    state = _ExecState({})
    run_block(ctx, b, state)
    return state.values["__param__"]


class Layer:
    """Dygraph module base (ref dygraph/layers.py Layer)."""

    def __init__(self, name_scope: Optional[str] = None,
                 dtype: str = "float32"):
        scope = name_scope or self.__class__.__name__.lower()
        self._full_name = unique_name.generate(scope)
        self._dtype = convert_dtype(dtype)
        self._parameters: "OrderedDict[str, VarBase]" = OrderedDict()
        self._sub_layers: "OrderedDict[str, Layer]" = OrderedDict()
        self._buffers: "OrderedDict[str, VarBase]" = OrderedDict()
        self.training = True

    # -- identity ------------------------------------------------------------
    def full_name(self) -> str:
        return self._full_name

    # -- mode ----------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()
        return self

    # -- parameter creation --------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> VarBase:
        attr = ParamAttr._to_attr(attr)
        dtype = convert_dtype(dtype or self._dtype)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = (_global_bias_initializer() if is_bias
                    else _global_weight_initializer())
        value = eager_initialize(list(shape), dtype, init)
        name = (attr.name if attr is not None and attr.name
                else unique_name.generate(f"{self._full_name}.w"))
        p = VarBase(value, name=name, persistable=True,
                    trainable=attr.trainable if attr is not None else True)
        p.stop_gradient = not p.trainable
        p.regularizer = getattr(attr, "regularizer", None)
        return p

    def create_variable(self, name=None, persistable=False, dtype=None,
                        value=None, shape=None) -> VarBase:
        dtype = convert_dtype(dtype or self._dtype)
        if value is None:
            value = np.zeros(shape or [1], dtype)
        v = VarBase(np.asarray(value, dtype), name=name,
                    persistable=persistable, trainable=False)
        v.stop_gradient = True
        return v

    # -- registration --------------------------------------------------------
    def add_parameter(self, name: str, parameter: VarBase) -> VarBase:
        self._parameters[name] = parameter
        object.__setattr__(self, name, parameter)
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        object.__setattr__(self, name, sublayer)
        return sublayer

    def register_buffer(self, name: str, value: VarBase) -> VarBase:
        self._buffers[name] = value
        object.__setattr__(self, name, value)
        return value

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        if params is not None and isinstance(value, VarBase) \
                and value.persistable:
            params[name] = value
        elif subs is not None and isinstance(value, Layer):
            subs[name] = value
        object.__setattr__(self, name, value)

    # -- traversal -----------------------------------------------------------
    def parameters(self, include_sublayers: bool = True) -> List[VarBase]:
        return [p for _, p in self.named_parameters(include_sublayers)]

    def named_parameters(self, include_sublayers: bool = True, prefix: str = ""):
        seen = set()
        for name, p in self._parameters.items():
            if id(p) not in seen:
                seen.add(id(p))
                yield (f"{prefix}{name}" if prefix else name), p
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                sub_prefix = f"{prefix}{lname}." if prefix else f"{lname}."
                for n, p in l.named_parameters(True, sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def sublayers(self, include_sublayers: bool = True) -> List["Layer"]:
        out = []
        for l in self._sub_layers.values():
            out.append(l)
            if include_sublayers:
                out.extend(l.sublayers(True))
        return out

    def named_sublayers(self, prefix: str = ""):
        for name, l in self._sub_layers.items():
            full = f"{prefix}{name}" if prefix else name
            yield full, l
            yield from l.named_sublayers(f"{full}.")

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # -- state dict ----------------------------------------------------------
    def state_dict(self, include_sublayers: bool = True,
                   prefix: str = "") -> Dict[str, VarBase]:
        out: "OrderedDict[str, VarBase]" = OrderedDict()
        for name, p in self._parameters.items():
            out[(f"{prefix}{name}" if prefix else name)] = p
        for name, b in self._buffers.items():
            out[(f"{prefix}{name}" if prefix else name)] = b
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                sub = l.state_dict(True, f"{prefix}{lname}." if prefix
                                   else f"{lname}.")
                out.update(sub)
        return out

    def set_dict(self, state: Dict, include_sublayers: bool = True,
                 use_structured_name: bool = True):
        own = self.state_dict(include_sublayers)
        for key, target in own.items():
            if key in state:
                v = state[key]
                arr = v.numpy() if isinstance(v, VarBase) else np.asarray(v)
                if tuple(arr.shape) != target.shape:
                    raise ValueError(
                        f"shape mismatch for {key}: saved {arr.shape} vs "
                        f"model {target.shape}")
                target.set_value(arr.astype(target.dtype))
        return self

    load_dict = set_dict

    # -- call ----------------------------------------------------------------
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)
