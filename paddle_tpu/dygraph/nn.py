"""Dygraph nn Layer classes.

ref ``python/paddle/fluid/dygraph/nn.py``: Conv2D:35 Conv3D:244 Pool2D:662
FC:773 BatchNorm:963 Embedding:1178 LayerNorm:1266 GRUUnit:1411 NCE:1564
PRelu:1793 BilinearTensorProduct:1864 Conv2DTranspose:1964 SequenceConv:2199
RowConv:2289 GroupNorm:2365 SpectralNorm:2464 TreeConv:2564.

Each layer owns eager parameters and calls ``Tracer.trace_op`` with the same
op types the static-graph DSL appends — shared lowering = shared semantics,
exactly the reference's shared-C++-kernel design.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..framework.core import convert_dtype
from ..initializer import ConstantInitializer, NormalInitializer, UniformInitializer
from .layers import Layer
from .tracer import VarBase, default_tracer

__all__ = [
    "Conv2D", "Conv3D", "Conv2DTranspose", "Pool2D", "FC", "Linear",
    "BatchNorm", "Embedding", "LayerNorm", "GRUUnit", "NCE", "PRelu",
    "BilinearTensorProduct", "GroupNorm", "SpectralNorm", "SequenceConv",
    "RowConv", "TreeConv", "Dropout",
]


def _pair(v, n=2):
    return list(v) if isinstance(v, (list, tuple)) else [v] * n


def _trace(op_type, ins, attrs=None):
    return default_tracer().trace_op(op_type, ins, attrs)


def _act(x, act: Optional[str]):
    if act is None:
        return x
    return _trace(act, {"X": [x]}, {})["Out"][0]


class Conv2D(Layer):
    """ref dygraph/nn.py:35."""

    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, stride=1, padding=0, dilation=1, groups=None,
                 param_attr=None, bias_attr=None, use_cudnn=True, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        self._stride = _pair(stride)
        self._padding = _pair(padding)
        self._dilation = _pair(dilation)
        self._act = act
        fs = _pair(filter_size)
        filter_shape = [num_filters, num_channels // self._groups] + fs
        std = (2.0 / (fs[0] * fs[1] * num_channels)) ** 0.5
        self.weight = self.create_parameter(
            filter_shape, attr=param_attr, dtype=dtype,
            default_initializer=NormalInitializer(0.0, std))
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_filters], attr=bias_attr, dtype=dtype, is_bias=True))

    def forward(self, input):
        out = _trace("conv2d",
                     {"Input": [input], "Filter": [self.weight]},
                     {"strides": self._stride, "paddings": self._padding,
                      "dilations": self._dilation, "groups": self._groups,
                      "data_format": "NCHW"})["Output"][0]
        if self.bias is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                         {"axis": 1})["Out"][0]
        return _act(out, self._act)


class Conv3D(Layer):
    """ref dygraph/nn.py:244."""

    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, stride=1, padding=0, dilation=1,
                 groups=None, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        self._stride = _pair(stride, 3)
        self._padding = _pair(padding, 3)
        self._dilation = _pair(dilation, 3)
        self._act = act
        fs = _pair(filter_size, 3)
        filter_shape = [num_filters, num_channels // self._groups] + fs
        self.weight = self.create_parameter(filter_shape, attr=param_attr,
                                            dtype=dtype)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_filters], attr=bias_attr, dtype=dtype, is_bias=True))

    def forward(self, input):
        out = _trace("conv3d", {"Input": [input], "Filter": [self.weight]},
                     {"strides": self._stride, "paddings": self._padding,
                      "dilations": self._dilation,
                      "groups": self._groups})["Output"][0]
        if self.bias is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                         {"axis": 1})["Out"][0]
        return _act(out, self._act)


class Conv2DTranspose(Layer):
    """ref dygraph/nn.py:1964."""

    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, padding=0, stride=1, dilation=1,
                 groups=None, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        self._stride = _pair(stride)
        self._padding = _pair(padding)
        self._dilation = _pair(dilation)
        self._act = act
        fs = _pair(filter_size)
        self.weight = self.create_parameter(
            [num_channels, num_filters // self._groups] + fs,
            attr=param_attr, dtype=dtype)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_filters], attr=bias_attr, dtype=dtype, is_bias=True))

    def forward(self, input):
        out = _trace("conv2d_transpose",
                     {"Input": [input], "Filter": [self.weight]},
                     {"strides": self._stride, "paddings": self._padding,
                      "dilations": self._dilation,
                      "groups": self._groups})["Output"][0]
        if self.bias is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                         {"axis": 1})["Out"][0]
        return _act(out, self._act)


class Conv3DTranspose(Layer):
    """ref dygraph/nn.py:441."""

    def __init__(self, name_scope=None, num_channels=None, num_filters=None,
                 filter_size=None, padding=0, stride=1, dilation=1,
                 groups=None, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups or 1
        self._stride = _pair(stride, 3)
        self._padding = _pair(padding, 3)
        self._dilation = _pair(dilation, 3)
        self._act = act
        fs = _pair(filter_size, 3)
        self.weight = self.create_parameter(
            [num_channels, num_filters // self._groups] + fs,
            attr=param_attr, dtype=dtype)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_filters], attr=bias_attr, dtype=dtype, is_bias=True))

    def forward(self, input):
        out = _trace("conv3d_transpose",
                     {"Input": [input], "Filter": [self.weight]},
                     {"strides": self._stride, "paddings": self._padding,
                      "dilations": self._dilation,
                      "groups": self._groups})["Output"][0]
        if self.bias is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                         {"axis": 1})["Out"][0]
        return _act(out, self._act)


class Pool2D(Layer):
    """ref dygraph/nn.py:662."""

    def __init__(self, name_scope=None, pool_size=-1, pool_type="max",
                 pool_stride=1, pool_padding=0, global_pooling=False,
                 use_cudnn=True, ceil_mode=False, exclusive=True,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._attrs = {"pooling_type": pool_type, "ksize": _pair(pool_size),
                       "strides": _pair(pool_stride),
                       "paddings": _pair(pool_padding),
                       "global_pooling": global_pooling,
                       "ceil_mode": ceil_mode, "exclusive": exclusive,
                       "data_format": "NCHW"}

    def forward(self, input):
        return _trace("pool2d", {"X": [input]}, dict(self._attrs))["Out"][0]


class FC(Layer):
    """ref dygraph/nn.py:773 — mul + bias + act; lazy weight creation on the
    first forward (the reference builds from the first input's shape too)."""

    def __init__(self, name_scope=None, size=None, num_flatten_dims=1,
                 param_attr=None, bias_attr=None, act=None, is_test=False,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._num_flatten_dims = num_flatten_dims
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        self.weight: Optional[VarBase] = None
        self.bias: Optional[VarBase] = None

    def _build_once(self, input):
        in_dim = int(np.prod(input.shape[self._num_flatten_dims:]))
        self.weight = self.create_parameter([in_dim, self._size],
                                            attr=self._param_attr,
                                            dtype=self._dtype)
        if self._bias_attr is not False:
            self.bias = self.create_parameter([self._size],
                                              attr=self._bias_attr,
                                              dtype=self._dtype, is_bias=True)

    def forward(self, input):
        if self.weight is None:
            self._build_once(input)
        out = _trace("mul", {"X": [input], "Y": [self.weight]},
                     {"x_num_col_dims": self._num_flatten_dims,
                      "y_num_col_dims": 1})["Out"][0]
        if self.bias is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                         {"axis": -1})["Out"][0]
        return _act(out, self._act)


class Linear(FC):
    """2.0-style alias: explicit input_dim instead of lazy build."""

    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(None, output_dim, param_attr=param_attr,
                         bias_attr=bias_attr, act=act, dtype=dtype)
        self.weight = self.create_parameter([input_dim, output_dim],
                                            attr=param_attr, dtype=dtype)
        if bias_attr is not False:
            self.bias = self.create_parameter([output_dim], attr=bias_attr,
                                              dtype=dtype, is_bias=True)


class BatchNorm(Layer):
    """ref dygraph/nn.py:963 — running stats live as buffers, updated in
    training forward via the batch_norm op's MeanOut/VarianceOut."""

    def __init__(self, name_scope=None, num_channels=None, act=None,
                 is_test=False, momentum=0.9, epsilon=1e-5, param_attr=None,
                 bias_attr=None, dtype="float32", data_layout="NCHW",
                 use_global_stats=False):
        super().__init__(name_scope, dtype)
        self._momentum = momentum
        self._epsilon = epsilon
        self._act = act
        self._layout = data_layout
        self._use_global_stats = use_global_stats
        c = [num_channels]
        self.weight = self.create_parameter(
            c, attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter(c, attr=bias_attr, dtype=dtype,
                                          is_bias=True)
        self.register_buffer("_mean", VarBase(
            np.zeros(c, "float32"), persistable=True, trainable=False,
            stop_gradient=True))
        self.register_buffer("_variance", VarBase(
            np.ones(c, "float32"), persistable=True, trainable=False,
            stop_gradient=True))
        if is_test:
            self.training = False

    def forward(self, input):
        outs = _trace(
            "batch_norm",
            {"X": [input], "Scale": [self.weight], "Bias": [self.bias],
             "Mean": [self._mean], "Variance": [self._variance]},
            {"momentum": self._momentum, "epsilon": self._epsilon,
             "is_test": not self.training, "data_layout": self._layout,
             "use_global_stats": self._use_global_stats})
        if self.training and not self._use_global_stats:
            self._mean.set_value(outs["MeanOut"][0].value)
            self._variance.set_value(outs["VarianceOut"][0].value)
        return _act(outs["Y"][0], self._act)


class Embedding(Layer):
    """ref dygraph/nn.py:1178."""

    def __init__(self, name_scope=None, size=None, is_sparse=False,
                 is_distributed=False, padding_idx=None, param_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._padding_idx = -1 if padding_idx is None else padding_idx
        self.weight = self.create_parameter(
            list(size), attr=param_attr, dtype=dtype,
            default_initializer=UniformInitializer(-0.05, 0.05))

    def forward(self, input):
        return _trace("lookup_table_v2",
                      {"W": [self.weight], "Ids": [input]},
                      {"padding_idx": self._padding_idx})["Out"][0]


class LayerNorm(Layer):
    """ref dygraph/nn.py:1266."""

    def __init__(self, name_scope=None, normalized_shape=None, scale=True,
                 shift=True, begin_norm_axis=1, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._epsilon = epsilon
        self._begin_norm_axis = begin_norm_axis
        self._act = act
        dim = [int(np.prod(normalized_shape))] \
            if normalized_shape is not None else None
        self._dim = dim
        self.weight = None
        self.bias = None
        self._scale, self._shift = scale, shift
        if dim is not None:
            self._build(dim)

    def _build(self, dim):
        if self._scale:
            self.weight = self.create_parameter(
                dim, dtype=self._dtype,
                default_initializer=ConstantInitializer(1.0))
        if self._shift:
            self.bias = self.create_parameter(dim, dtype=self._dtype,
                                              is_bias=True)

    def forward(self, input):
        if self._dim is None:
            self._dim = [int(np.prod(input.shape[self._begin_norm_axis:]))]
            self._build(self._dim)
        ins = {"X": [input]}
        if self.weight is not None:
            ins["Scale"] = [self.weight]
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = _trace("layer_norm", ins,
                     {"epsilon": self._epsilon,
                      "begin_norm_axis": self._begin_norm_axis})["Y"][0]
        return _act(out, self._act)


class GroupNorm(Layer):
    """ref dygraph/nn.py:2365."""

    def __init__(self, name_scope=None, channels=None, groups=None,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups
        self._epsilon = epsilon
        self._act = act
        self.weight = self.create_parameter(
            [channels], attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0))
        self.bias = self.create_parameter([channels], attr=bias_attr,
                                          dtype=dtype, is_bias=True)

    def forward(self, input):
        out = _trace("group_norm",
                     {"X": [input], "Scale": [self.weight],
                      "Bias": [self.bias]},
                     {"groups": self._groups, "epsilon": self._epsilon})
        return _act(out["Y"][0], self._act)


class GRUUnit(Layer):
    """ref dygraph/nn.py:1411 — one GRU step: gates from input + hidden."""

    def __init__(self, name_scope=None, size=None, param_attr=None,
                 bias_attr=None, activation="tanh", gate_activation="sigmoid",
                 origin_mode=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        d = size // 3
        self._d = d
        self._activation = activation
        self._gate_activation = gate_activation
        self._origin_mode = origin_mode
        self.weight = self.create_parameter([d, d * 3], attr=param_attr,
                                            dtype=dtype)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [1, d * 3], attr=bias_attr, dtype=dtype, is_bias=True))

    def forward(self, input, hidden):
        ins = {"Input": [input], "HiddenPrev": [hidden],
               "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        outs = _trace("gru_unit", ins,
                      {"activation": self._activation,
                       "gate_activation": self._gate_activation,
                       "origin_mode": self._origin_mode})
        return (outs["Hidden"][0], outs["ResetHiddenPrev"][0],
                outs["Gate"][0])


class PRelu(Layer):
    """ref dygraph/nn.py:1793."""

    def __init__(self, name_scope=None, mode="all", channel=None,
                 input_shape=None, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._mode = mode
        if mode == "all":
            shape = [1]
        elif mode == "channel":
            shape = [channel if channel is not None else input_shape[1]]
        else:
            shape = list(input_shape[1:])
        self.weight = self.create_parameter(
            shape, attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(0.25))

    def forward(self, input):
        return _trace("prelu", {"X": [input], "Alpha": [self.weight]},
                      {"mode": self._mode})["Out"][0]


class BilinearTensorProduct(Layer):
    """ref dygraph/nn.py:1864: out_k = x W_k y^T + b."""

    def __init__(self, name_scope=None, size=None, x_dim=None, y_dim=None,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._act = act
        self.weight = self.create_parameter([size, x_dim, y_dim],
                                            attr=param_attr, dtype=dtype)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [1, size], attr=bias_attr, dtype=dtype, is_bias=True))

    def forward(self, x, y):
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        out = _trace("bilinear_tensor_product", ins, {})["Out"][0]
        return _act(out, self._act)


class SpectralNorm(Layer):
    """ref dygraph/nn.py:2464 — power-iteration spectral normalization,
    composed from matmul/l2_normalize ops (u, v kept as buffers)."""

    def __init__(self, name_scope=None, weight_shape=None, dim=0,
                 power_iters=1, eps=1e-12, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.register_buffer("weight_u", VarBase(
            np.random.RandomState(0).normal(size=[h]).astype("float32"),
            persistable=True, trainable=False, stop_gradient=True))
        self.register_buffer("weight_v", VarBase(
            np.random.RandomState(1).normal(size=[w]).astype("float32"),
            persistable=True, trainable=False, stop_gradient=True))

    def forward(self, weight):
        import jax.numpy as jnp
        dim, eps = self._dim, self._eps
        wmat = np.moveaxis(np.arange(weight.ndim), 0, 0)  # perm helper
        perm = [dim] + [i for i in range(weight.ndim) if i != dim]
        w = _trace("transpose2", {"X": [weight]}, {"axis": perm})["Out"][0]
        h = w.shape[0]
        w = _trace("reshape2", {"X": [w]}, {"shape": [h, -1]})["Out"][0]
        u, v = self.weight_u.value, self.weight_v.value
        wv = w.value
        for _ in range(self._power_iters):
            v = wv.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wv @ v
            u = u / (jnp.linalg.norm(u) + eps)
        self.weight_u.set_value(u)
        self.weight_v.set_value(v)
        sigma_u = VarBase(u, stop_gradient=True)
        sigma_v = VarBase(v, stop_gradient=True)
        uw = _trace("matmul",
                    {"X": [_trace("reshape2", {"X": [sigma_u]},
                                  {"shape": [1, -1]})["Out"][0]],
                     "Y": [w]}, {})["Out"][0]
        sigma = _trace("matmul",
                       {"X": [uw],
                        "Y": [_trace("reshape2", {"X": [sigma_v]},
                                     {"shape": [-1, 1]})["Out"][0]]},
                       {})["Out"][0]
        sigma = _trace("reshape2", {"X": [sigma]}, {"shape": [1]})["Out"][0]
        return _trace("elementwise_div", {"X": [weight], "Y": [sigma]},
                      {"axis": -1})["Out"][0]


class NCE(Layer):
    """ref dygraph/nn.py:1564 — noise-contrastive estimation head.

    Eager realization: sample ``num_neg_samples`` negatives uniformly, score
    positives + negatives against class embeddings, binary logistic loss
    (the reference nce_op's uniform-sampler path).
    """

    def __init__(self, name_scope=None, num_total_classes=None, dim=None,
                 num_neg_samples=10, param_attr=None, bias_attr=None,
                 dtype="float32", seed=0):
        super().__init__(name_scope, dtype)
        self._num_total_classes = num_total_classes
        self._num_neg = num_neg_samples
        self._rng = np.random.RandomState(seed or 0)
        self.weight = self.create_parameter([num_total_classes, dim],
                                            attr=param_attr, dtype=dtype)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_total_classes], attr=bias_attr, dtype=dtype, is_bias=True))

    def forward(self, input, label):
        n = input.shape[0]
        neg = self._rng.randint(0, self._num_total_classes,
                                (n, self._num_neg)).astype("int64")
        lbl = _trace("reshape2", {"X": [label]}, {"shape": [n, 1]})["Out"][0]
        ids = _trace("concat",
                     {"X": [lbl, VarBase(neg, stop_gradient=True)]},
                     {"axis": 1})["Out"][0]
        emb = _trace("lookup_table_v2", {"W": [self.weight], "Ids": [ids]},
                     {"padding_idx": -1})["Out"][0]       # (n, 1+k, d)
        x3 = _trace("reshape2", {"X": [input]},
                    {"shape": [n, 1, -1]})["Out"][0]
        logits = _trace("matmul", {"X": [emb], "Y": [x3]},
                        {"transpose_Y": True})["Out"][0]  # (n, 1+k, 1)
        logits = _trace("reshape2", {"X": [logits]},
                        {"shape": [n, 1 + self._num_neg]})["Out"][0]
        if self.bias is not None:
            b = _trace("lookup_table_v2",
                       {"W": [_trace("reshape2", {"X": [self.bias]},
                                     {"shape": [-1, 1]})["Out"][0]],
                        "Ids": [ids]}, {"padding_idx": -1})["Out"][0]
            b = _trace("reshape2", {"X": [b]},
                       {"shape": [n, 1 + self._num_neg]})["Out"][0]
            logits = _trace("elementwise_add", {"X": [logits], "Y": [b]},
                            {"axis": -1})["Out"][0]
        targets = np.zeros((n, 1 + self._num_neg), "float32")
        targets[:, 0] = 1.0
        loss = _trace("sigmoid_cross_entropy_with_logits",
                      {"X": [logits],
                       "Label": [VarBase(targets, stop_gradient=True)]},
                      {})["Out"][0]
        loss = _trace("reduce_sum", {"X": [loss]},
                      {"dim": [1], "keep_dim": True})["Out"][0]
        return loss


class SequenceConv(Layer):
    """ref dygraph/nn.py:2199 — context-window conv over the time axis of a
    padded (batch, time, dim) sequence batch (LoD replaced by dense+mask)."""

    def __init__(self, name_scope=None, num_filters=None, filter_size=3,
                 filter_stride=1, padding=True, param_attr=None,
                 bias_attr=None, act=None, dtype="float32", input_dim=None):
        super().__init__(name_scope, dtype)
        self._filter_size = filter_size
        self._act = act
        self._num_filters = num_filters
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self.weight = None
        self.bias = None
        if input_dim is not None:
            self._build(input_dim)

    def _build(self, dim):
        self.weight = self.create_parameter(
            [self._filter_size * dim, self._num_filters],
            attr=self._param_attr, dtype=self._dtype)
        if self._bias_attr is not False:
            self.bias = self.create_parameter([self._num_filters],
                                              attr=self._bias_attr,
                                              dtype=self._dtype, is_bias=True)

    def forward(self, input):
        if self.weight is None:
            self._build(input.shape[-1])
        out = _trace("sequence_conv",
                     {"X": [input], "Filter": [self.weight]},
                     {"contextLength": self._filter_size,
                      "contextStart": -(self._filter_size // 2),
                      "contextStride": 1})["Out"][0]
        if self.bias is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                         {"axis": -1})["Out"][0]
        return _act(out, self._act)


class RowConv(Layer):
    """ref dygraph/nn.py:2289 — lookahead row convolution."""

    def __init__(self, name_scope=None, future_context_size=2,
                 param_attr=None, act=None, dtype="float32", input_dim=None):
        super().__init__(name_scope, dtype)
        self._act = act
        self._k = future_context_size
        self._param_attr = param_attr
        self.weight = None
        if input_dim is not None:
            self._build(input_dim)

    def _build(self, dim):
        self.weight = self.create_parameter([self._k + 1, dim],
                                            attr=self._param_attr,
                                            dtype=self._dtype)

    def forward(self, input):
        if self.weight is None:
            self._build(input.shape[-1])
        out = _trace("row_conv", {"X": [input], "Filter": [self.weight]},
                     {})["Out"][0]
        return _act(out, self._act)


class TreeConv(Layer):
    """ref dygraph/nn.py:2564 — tree-based conv over node features and an
    adjacency-derived edge set; realized densely via matmul over a
    (batch, nodes, nodes) propagation matrix."""

    def __init__(self, name_scope=None, output_size=None, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 dtype="float32", feature_size=None):
        super().__init__(name_scope, dtype)
        self._output_size = output_size
        self._num_filters = num_filters
        self._max_depth = max_depth
        self._act = act
        self.weight = self.create_parameter(
            [feature_size, 3, output_size, num_filters],
            attr=param_attr, dtype=dtype)
        self.bias = (None if bias_attr is False else self.create_parameter(
            [num_filters], attr=bias_attr, dtype=dtype, is_bias=True))

    def forward(self, nodes_vector, edge_set):
        out = _trace("tree_conv",
                     {"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                      "Filter": [self.weight]},
                     {"max_depth": self._max_depth})["Out"][0]
        if self.bias is not None:
            out = _trace("elementwise_add", {"X": [out], "Y": [self.bias]},
                         {"axis": -1})["Out"][0]
        return _act(out, self._act)


class Dropout(Layer):
    """Convenience eager dropout (2.0-style; the reference uses
    fluid.layers.dropout functionally in dygraph)."""

    def __init__(self, p=0.5):
        super().__init__()
        self._p = p

    def forward(self, input):
        return _trace("dropout", {"X": [input]},
                      {"dropout_prob": self._p,
                       "is_test": not self.training,
                       "dropout_implementation": "upscale_in_train"}
                      )["Out"][0]
