"""Preemption-aware checkpointing (SURVEY §5.3/§5.4; orbax-backed)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.checkpoint import CheckpointManager
from paddle_tpu.framework import Executor
from paddle_tpu.framework.core import Program, program_guard
from paddle_tpu.framework.scope import Scope, scope_guard


def _build_and_step(exe, loss, rng, steps):
    out = None
    for _ in range(steps):
        xv = rng.rand(8, 4).astype(np.float32)
        yv = xv.sum(1, keepdims=True).astype(np.float32)
        out, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    return float(out)


def test_save_restore_resume(tmp_path):
    with program_guard(Program(), Program()), scope_guard(Scope()):
        x = layers.data("x", shape=[4], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1, param_attr=fluid.ParamAttr(name="ck_w"))
        loss = layers.mean(layers.square_error_cost(pred, y))
        fluid.optimizer.Adam(0.05).minimize(loss)
        exe = Executor()
        exe.run(fluid.default_startup_program())
        rng = np.random.RandomState(0)
        ckpt = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)

        _build_and_step(exe, loss, rng, 5)
        assert ckpt.save(5)
        w5 = np.asarray(fluid.global_scope().find_var("ck_w")).copy()
        m5 = np.asarray(
            fluid.global_scope().find_var("ck_w_moment1_0")).copy() \
            if fluid.global_scope().find_var("ck_w_moment1_0") is not None \
            else None
        _build_and_step(exe, loss, rng, 5)
        assert ckpt.save(10)
        # keep-last-2: step 5 and 10 retained
        assert ckpt.all_steps() == [5, 10]
        assert ckpt.latest_step() == 10

        # "preemption": wipe the scope and resume from step 5
        restored = ckpt.restore(5)
        assert restored == 5
        np.testing.assert_allclose(
            np.asarray(fluid.global_scope().find_var("ck_w")), w5)
        if m5 is not None:
            # optimizer slots (Adam moments) resume too — true training
            # resume, not params-only
            np.testing.assert_allclose(
                np.asarray(fluid.global_scope().find_var("ck_w_moment1_0")),
                m5)
        # training continues after restore
        out = _build_and_step(exe, loss, rng, 3)
        assert np.isfinite(out)
        ckpt.close()


def test_interval_and_missing(tmp_path):
    with program_guard(Program(), Program()), scope_guard(Scope()):
        x = layers.data("x", shape=[2], dtype="float32")
        layers.fc(x, size=1)
        exe = Executor()
        exe.run(fluid.default_startup_program())
        ckpt = CheckpointManager(str(tmp_path / "r2"),
                                 save_interval_steps=5)
        assert not ckpt.save(3)          # off-interval: skipped
        assert ckpt.save(3, force=True)
        assert ckpt.latest_step() == 3
        import pytest
        with pytest.raises(FileNotFoundError):
            CheckpointManager(str(tmp_path / "empty")).restore()
        ckpt.close()


def test_save_below_latest_reports_false(tmp_path):
    """After restoring an older step, saves below the latest retained step
    are refused by orbax — save() must report that honestly."""
    with program_guard(Program(), Program()), scope_guard(Scope()):
        x = layers.data("x", shape=[2], dtype="float32")
        layers.fc(x, size=1)
        exe = Executor()
        exe.run(fluid.default_startup_program())
        ckpt = CheckpointManager(str(tmp_path / "r3"))
        assert ckpt.save(5)
        assert ckpt.save(10)
        ckpt.restore(5)
        assert not ckpt.save(8), \
            "orbax skipped the write; save() must not claim success"
        ckpt.close()
