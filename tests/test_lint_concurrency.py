"""Concurrency-lint self-test: known-bad snippet fixtures must trip each
rule, near-miss snippets must stay clean, suppression must demote, and
the shipped ``paddle_tpu/`` tree must lint clean (the CI gate)."""

import importlib.util
import subprocess
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
TOOL = REPO / "tools" / "lint_concurrency.py"


def _lint_module():
    spec = importlib.util.spec_from_file_location("lint_concurrency", TOOL)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


LC = _lint_module()


def _lint_snippet(tmp_path, source, name="snippet.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return LC.lint_paths([p])


def _rules(violations, live_only=True):
    return sorted({v.rule for v in violations
                   if not (live_only and v.suppressed)})


# ---------------------------------------------------------------------------
# guarded-field
# ---------------------------------------------------------------------------

def test_guarded_field_trips_on_unlocked_mutation(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def push(self, x):
                self._items.append(x)

            def reset(self):
                self._items = []

            def drop(self, i):
                del self._items[i]
    """)
    assert _rules(vs) == ["guarded-field"]
    assert len(vs) == 3
    assert all("_items" in v.message and "_lock" in v.message for v in vs)


def test_guarded_field_near_miss_locked_mutation_clean(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def push(self, x):
                with self._lock:
                    self._items.append(x)

            def peek(self):
                return len(self._items)      # reads need no lock
    """)
    assert vs == []


def test_guarded_field_module_level_crosses_files(tmp_path):
    (tmp_path / "a.py").write_text(textwrap.dedent("""
        import threading
        _tokens = set()  # guarded-by: _tokens_lock
        _tokens_lock = threading.Lock()
    """))
    (tmp_path / "b.py").write_text(textwrap.dedent("""
        from a import _tokens, _tokens_lock

        def good(t):
            with _tokens_lock:
                _tokens.add(t)

        def bad(t):
            _tokens.discard(t)
    """))
    vs = LC.lint_paths([tmp_path])
    assert len(vs) == 1 and vs[0].rule == "guarded-field"
    assert vs[0].path.endswith("b.py")


def test_guarded_field_init_exempt(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock
                self._items.append(0)        # construction: not shared yet
    """)
    assert vs == []


# ---------------------------------------------------------------------------
# signal-handler
# ---------------------------------------------------------------------------

def test_signal_handler_trips_on_lock_and_telemetry(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import signal
        import threading

        class G:
            def __init__(self):
                self._lock = threading.Lock()
                self._ctr = None

            def _handler(self, signum, frame):
                self._note()

            def _note(self):
                with self._lock:
                    pass
                self._ctr.inc()

            def install(self):
                signal.signal(signal.SIGTERM, self._handler)
    """)
    assert _rules(vs) == ["signal-handler"]
    msgs = " ".join(v.message for v in vs)
    assert "acquires lock" in msgs and "telemetry" in msgs


def test_signal_handler_near_miss_event_set_clean(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import signal
        import threading

        class G:
            def __init__(self):
                self._flag = threading.Event()

            def _handler(self, signum, frame):
                self._signum = signum
                self._flag.set()             # Event.set alone is safe

            def install(self):
                signal.signal(signal.SIGTERM, self._handler)
    """)
    assert vs == []


# ---------------------------------------------------------------------------
# thread-lifetime
# ---------------------------------------------------------------------------

def test_thread_trips_without_daemon_or_join(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn)
            t.start()
    """)
    assert _rules(vs) == ["thread-lifetime"]


def test_thread_near_miss_daemon_or_joined_clean(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        def spawn_daemon(fn):
            threading.Thread(target=fn, daemon=True).start()

        def spawn_joined(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()

        class W:
            def start(self, fn):
                self._t = threading.Thread(target=fn)
                self._t.start()

            def stop(self):
                self._t.join()
    """)
    assert vs == []


# ---------------------------------------------------------------------------
# finalize-lock
# ---------------------------------------------------------------------------

def test_finalize_trips_on_plain_lock(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading
        import weakref

        class C:
            def __init__(self):
                self._mu = threading.Lock()
                weakref.finalize(self, C._evict, self)

            def _evict(self):
                with self._mu:
                    pass
    """)
    assert _rules(vs) == ["finalize-lock"]
    assert "RLock" in vs[0].message


def test_finalize_near_miss_rlock_clean(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading
        import weakref

        class C:
            def __init__(self):
                self._mu = threading.RLock()
                weakref.finalize(self, C._evict, self)

            def _evict(self):
                with self._mu:
                    pass
    """)
    assert vs == []


# ---------------------------------------------------------------------------
# suppression + the shipped tree
# ---------------------------------------------------------------------------

def test_lint_ok_suppression_demotes_but_reports(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def push(self, x):
                self._items.append(x)  # lint-ok: test-only helper
    """)
    assert len(vs) == 1 and vs[0].suppressed == "test-only helper"
    assert _rules(vs) == []              # no LIVE violations


def test_paddle_tpu_tree_lints_clean():
    vs = LC.lint_paths([REPO / "paddle_tpu"])
    live = [v for v in vs if not v.suppressed]
    assert live == [], "\n".join(str(v) for v in live)


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(textwrap.dedent("""
        import threading

        def spawn(fn):
            threading.Thread(target=fn).start()
    """))
    r = subprocess.run([sys.executable, str(TOOL), str(bad)],
                       capture_output=True, text=True)
    assert r.returncode == 1 and "thread-lifetime" in r.stdout
    r = subprocess.run([sys.executable, str(TOOL), str(REPO / "paddle_tpu")],
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


# ---------------------------------------------------------------------------
# guarded-by-caller
# ---------------------------------------------------------------------------

def test_guarded_by_caller_trips_on_unlocked_call_site(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def _push_locked(self, x):  # guarded-by-caller: _lock
                self._items.append(x)

            def good(self, x):
                with self._lock:
                    self._push_locked(x)

            def bad(self, x):
                self._push_locked(x)
    """)
    assert _rules(vs) == ["guarded-by-caller"]
    assert len(vs) == 1 and "without holding '_lock'" in vs[0].message


def test_guarded_by_caller_near_miss_all_callers_locked_clean(tmp_path):
    """The mutation inside the annotated helper needs NO per-line
    suppression, and locked callers satisfy the contract."""
    vs = _lint_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def _push_locked(self, x):  # guarded-by-caller: _lock
                self._items.append(x)

            def one(self, x):
                with self._lock:
                    self._push_locked(x)

            def two(self, x):
                with self._lock:
                    self._push_locked(x + 1)
    """)
    assert vs == []


def test_guarded_by_caller_propagates_through_annotated_helpers(tmp_path):
    """A *_locked helper calling another *_locked helper is clean when
    both assert the same lock (the coordinator pattern)."""
    vs = _lint_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def _push_locked(self, x):  # guarded-by-caller: _lock
                self._items.append(x)

            def _push_two_locked(self, x):  # guarded-by-caller: _lock
                self._push_locked(x)
                self._push_locked(x + 1)

            def entry(self, x):
                with self._lock:
                    self._push_two_locked(x)
    """)
    assert vs == []


def test_guarded_by_caller_trips_when_unverifiable(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class Q:
            def __init__(self):
                self._lock = threading.Lock()
                self._items = []  # guarded-by: _lock

            def _push_locked(self, x):  # guarded-by-caller: _lock
                self._items.append(x)
    """)
    assert _rules(vs) == ["guarded-by-caller"]
    assert "no same-module caller" in vs[0].message


# ---------------------------------------------------------------------------
# cond-misuse (Condition-vs-Lock)
# ---------------------------------------------------------------------------

def test_cond_wait_notify_outside_with_trips(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition(threading.Lock())
                self.ready = False

            def bad_wait(self):
                self._cv.wait(timeout=1)

            def bad_notify(self):
                self.ready = True
                self._cv.notify_all()
    """)
    assert _rules(vs) == ["cond-misuse"]
    assert len(vs) == 2
    msgs = " ".join(v.message for v in vs)
    assert "outside `with _cv:`" in msgs


def test_cond_near_miss_locked_wait_and_event_clean(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition(threading.Lock())
                self._stop = threading.Event()
                self.ready = False

            def wake(self):
                with self._cv:
                    self.ready = True
                    self._cv.notify_all()

            def wait(self):
                with self._cv:
                    while not self.ready:
                        self._cv.wait(timeout=0.1)

            def sleepy(self):
                self._stop.wait(1.0)      # Event.wait needs no lock
    """)
    assert vs == []


def test_cond_notify_without_state_change_trips(tmp_path):
    vs = _lint_snippet(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition(threading.Lock())
                self.ready = False

            def wake(self):
                self.ready = True          # predicate changed OUTSIDE
                with self._cv:
                    self._cv.notify_all()
    """)
    assert _rules(vs) == ["cond-misuse"]
    assert "no state change under the lock" in vs[0].message


def test_cond_notify_in_caller_guarded_helper_clean(tmp_path):
    """The coordinator pattern: a guarded-by-caller helper that changes
    state and notifies is clean, callers hold the condition."""
    vs = _lint_snippet(tmp_path, """
        import threading

        class W:
            def __init__(self):
                self._cv = threading.Condition(threading.Lock())
                self.step = None  # guarded-by: _cv

            def _publish_locked(self, step):  # guarded-by-caller: _cv
                self.step = step
                self._cv.notify_all()

            def publish(self, step):
                with self._cv:
                    self._publish_locked(step)
    """)
    assert vs == []
