"""LookaheadOptimizer (ref ``optimizer.py:2980``): slow/fast weight
dynamics vs a numpy simulation of the paper's update rule."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu import optimizer as opt
from paddle_tpu.framework import (Executor, Program, Scope, program_guard,
                                  scope_guard)


def test_lookahead_matches_reference_dynamics():
    k, alpha, lr, steps = 3, 0.5, 0.1, 8
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        w = layers.create_parameter([4, 1], "float32", name="w_la")
        loss = layers.mean(layers.matmul(x, w))
        la = opt.LookaheadOptimizer(opt.SGDOptimizer(lr), alpha=alpha, k=k)
        la.minimize(loss)
        exe = Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        w0 = np.asarray(scope.find_var("w_la")).copy()
        slow0 = np.asarray(scope.find_var("w_la@SLOW")).copy()
        np.testing.assert_allclose(slow0, w0)

        rng = np.random.RandomState(0)
        xs = [rng.rand(8, 4).astype(np.float32) for _ in range(steps)]
        for xv in xs:
            exe.run(fluid.default_main_program(), feed={"x": xv},
                    fetch_list=[loss.name], scope=scope)
        got_fast = np.asarray(scope.find_var("w_la"))
        got_slow = np.asarray(scope.find_var("w_la@SLOW"))

    # numpy simulation: grad of mean(x @ w) wrt w is x.mean(0)/1 per col
    fast, slow = w0.copy(), w0.copy()
    for t, xv in enumerate(xs, start=1):
        g = xv.mean(axis=0, keepdims=True).T / 1.0
        fast = fast - lr * g
        if t % k == 0:
            slow = slow + alpha * (fast - slow)
            fast = slow.copy()
    np.testing.assert_allclose(got_fast, fast, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(got_slow, slow, rtol=1e-5, atol=1e-6)


def test_lookahead_trains():
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[8], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        pred = layers.fc(x, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        la = opt.LookaheadOptimizer(opt.AdamOptimizer(1e-2), alpha=0.8, k=5)
        la.minimize(loss)
        exe = Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        rng = np.random.RandomState(1)
        xv = rng.rand(32, 8).astype(np.float32)
        yv = xv.sum(1, keepdims=True).astype(np.float32)
        losses = []
        for _ in range(60):
            l, = exe.run(fluid.default_main_program(),
                         feed={"x": xv, "y": yv},
                         fetch_list=[loss.name], scope=scope)
            losses.append(float(l))
        assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])


def test_lookahead_ops_pruned_from_test_clone():
    """clone(for_test=True) must drop the lookahead sync ops (they carry
    op_role='optimize'); otherwise every eval run would bump
    lookahead_step and overwrite the parameters."""
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        w = layers.create_parameter([4, 1], "float32", name="w_la2")
        loss = layers.mean(layers.matmul(x, w))
        la = opt.LookaheadOptimizer(opt.SGDOptimizer(0.1), alpha=0.5, k=2)
        la.minimize(loss)
        test_prog = fluid.default_main_program().clone(for_test=True)

        test_ops = [op.type for op in test_prog.global_block().ops]
        assert "increment" not in test_ops
        for op in test_prog.global_block().ops:
            for out in op.output_arg_names():
                assert not out.endswith("@SLOW"), (
                    f"lookahead sync op {op.type} survived for_test clone")

        exe = Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        w0 = np.asarray(scope.find_var("w_la2")).copy()
        step0 = np.asarray(scope.find_var("lookahead_step")).copy()
        xv = np.random.RandomState(1).rand(8, 4).astype(np.float32)
        for _ in range(3):
            exe.run(test_prog, feed={"x": xv}, fetch_list=[loss.name],
                    scope=scope)
        np.testing.assert_allclose(np.asarray(scope.find_var("w_la2")), w0)
        np.testing.assert_allclose(
            np.asarray(scope.find_var("lookahead_step")), step0)
