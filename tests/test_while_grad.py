"""Differentiable While (max_trip_count path): analytic grads through the
bounded-scan lowering vs numeric central differences and a hand-derived
closed form — parity with ref WhileGradOp coverage
(``operators/controlflow/while_op.cc:312``,
``tests/unittests/test_while_op.py``)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import (Program, Scope, append_backward,
                                  program_guard, scope_guard)


def _build_geometric_loop(max_trips):
    """acc = x; repeat 3 times: acc = acc * w  →  loss = mean(acc).
    d loss/d x = w^3 / n,  d loss/d w = 3 w^2 mean(x)."""
    x = layers.data("x", shape=[4], dtype="float32")
    x.stop_gradient = False
    w = layers.create_parameter([1], "float32", name="w_scale")
    i = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
    limit = layers.fill_constant(shape=[1], dtype="float32", value=3.0)
    acc = layers.elementwise_mul(x, layers.ones_like(x))  # copy of x
    cond = layers.less_than(i, limit)
    wh = layers.While(cond, max_trip_count=max_trips)
    with wh.block():
        layers.assign(layers.elementwise_mul(acc, w), acc)
        layers.increment(i, 1.0)
        layers.less_than(i, limit, cond=cond)
    loss = layers.mean(acc)
    return x, w, loss


def test_while_grad_matches_closed_form():
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x, w, loss = _build_geometric_loop(max_trips=5)
        append_backward(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        scope.set_var("w_scale", np.array([1.5], np.float32))
        xv = np.array([[0.5, -1.0, 2.0, 3.0]], np.float32)
        lv, gx, gw = exe.run(
            fluid.default_main_program(), feed={"x": xv},
            fetch_list=[loss.name, "x@GRAD", "w_scale@GRAD"], scope=scope)
        wv = 1.5
        np.testing.assert_allclose(lv, (xv * wv ** 3).mean(), rtol=1e-5)
        np.testing.assert_allclose(
            gx, np.full_like(xv, wv ** 3 / xv.size), rtol=1e-5)
        np.testing.assert_allclose(
            gw, [3 * wv ** 2 * xv.mean()], rtol=1e-5)


def test_while_grad_numeric_parity():
    """Central-difference check on the loop's parameter gradient."""
    def run(w_val, want_grads):
        scope = Scope()
        with scope_guard(scope), program_guard(Program(), Program()):
            x, w, loss = _build_geometric_loop(max_trips=4)
            if want_grads:
                append_backward(loss)
            exe = fluid.Executor()
            exe.run(fluid.default_startup_program(), scope=scope)
            scope.set_var("w_scale", np.array([w_val], np.float32))
            xv = np.array([[1.0, 2.0, -0.5, 0.25]], np.float32)
            fetch = [loss.name] + (["w_scale@GRAD"] if want_grads else [])
            out = exe.run(fluid.default_main_program(), feed={"x": xv},
                          fetch_list=fetch, scope=scope)
            return [np.asarray(o) for o in out]

    eps = 1e-2
    (l_plus,) = run(1.2 + eps, False)
    (l_minus,) = run(1.2 - eps, False)
    numeric = (float(l_plus) - float(l_minus)) / (2 * eps)
    _, gw = run(1.2, True)
    np.testing.assert_allclose(float(gw[0]), numeric, rtol=1e-3)


def test_while_unbounded_stays_forward_only():
    """No max_trip_count → lax.while_loop path, no grad ops emitted."""
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        i = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        limit = layers.fill_constant(shape=[1], dtype="float32", value=3.0)
        acc = layers.elementwise_mul(x, layers.ones_like(x))
        cond = layers.less_than(i, limit)
        wh = layers.While(cond)
        with wh.block():
            layers.assign(acc * 2.0, acc)
            layers.increment(i, 1.0)
            layers.less_than(i, limit, cond=cond)
        loss = layers.mean(acc)
        append_backward(loss)
        prog = fluid.default_main_program()
        assert not any(op.type == "while_grad"
                       for op in prog.global_block().ops)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        lv, = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)},
                      fetch_list=[loss.name], scope=scope)
        np.testing.assert_allclose(lv, 8.0, rtol=1e-5)


def test_while_grad_multi_consumer():
    """The loop output feeding TWO consumers: parallel contributions must
    sum BEFORE while_grad replays the loop (regression: the grads used to
    silently skip the loop transpose)."""
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[4], dtype="float32")
        x.stop_gradient = False
        w = layers.create_parameter([1], "float32", name="w_scale")
        i = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        limit = layers.fill_constant(shape=[1], dtype="float32", value=3.0)
        acc = layers.elementwise_mul(x, layers.ones_like(x))
        cond = layers.less_than(i, limit)
        wh = layers.While(cond, max_trip_count=5)
        with wh.block():
            layers.assign(layers.elementwise_mul(acc, w), acc)
            layers.increment(i, 1.0)
            layers.less_than(i, limit, cond=cond)
        loss = layers.mean(acc) + layers.mean(acc * 2.0)
        append_backward(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        scope.set_var("w_scale", np.array([1.5], np.float32))
        xv = np.array([[0.5, -1.0, 2.0, 3.0]], np.float32)
        gx, gw = exe.run(fluid.default_main_program(), feed={"x": xv},
                         fetch_list=["x@GRAD", "w_scale@GRAD"],
                         scope=scope)
        wv = 1.5
        np.testing.assert_allclose(
            gx, np.full_like(xv, 3 * wv ** 3 / xv.size), rtol=1e-5)
        np.testing.assert_allclose(
            gw, [3 * 3 * wv ** 2 * xv.mean()], rtol=1e-5)


def test_two_sequential_while_loops_grad():
    """Two bounded loops carrying the SAME var: each loop's grad must
    replay from ITS OWN snapshot (regression: shared snapshot names made
    loop 1 replay from loop 2's input).  acc = x → x^2 → x^4;
    d mean(x^4)/dx = 4 x^3 / n."""
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[2], dtype="float32")
        x.stop_gradient = False
        acc = layers.elementwise_mul(x, layers.ones_like(x))
        for _ in range(2):
            i = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
            lim = layers.fill_constant(shape=[1], dtype="float32",
                                       value=1.0)
            cond = layers.less_than(i, lim)
            wh = layers.While(cond, max_trip_count=2)
            with wh.block():
                layers.assign(layers.elementwise_mul(acc, acc), acc)
                layers.increment(i, 1.0)
                layers.less_than(i, lim, cond=cond)
        loss = layers.mean(acc)
        append_backward(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        xv = np.array([[2.0, 3.0]], np.float32)
        lv, gx = exe.run(fluid.default_main_program(), feed={"x": xv},
                         fetch_list=[loss.name, "x@GRAD"], scope=scope)
        np.testing.assert_allclose(lv, (xv ** 4).mean(), rtol=1e-5)
        np.testing.assert_allclose(gx, 4 * xv ** 3 / xv.size, rtol=1e-5)


def test_while_grad_domain_guard_no_nan():
    """The condition guards a domain (sqrt(limit - i)); dead iterations
    must not re-execute the body on the frozen boundary state (lax.cond
    path) — grads stay finite."""
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[2], dtype="float32")
        x.stop_gradient = False
        acc = layers.elementwise_mul(x, layers.ones_like(x))
        i = layers.fill_constant(shape=[1], dtype="float32", value=0.0)
        lim = layers.fill_constant(shape=[1], dtype="float32", value=3.0)
        cond = layers.less_than(i, lim)
        wh = layers.While(cond, max_trip_count=6)
        with wh.block():
            gap = layers.sqrt(lim - i)        # sqrt(<0) past the boundary
            layers.assign(acc * gap, acc)
            layers.increment(i, 1.0)
            layers.less_than(i, lim, cond=cond)
        loss = layers.mean(acc)
        append_backward(loss)
        exe = fluid.Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        xv = np.array([[1.0, 2.0]], np.float32)
        lv, gx = exe.run(fluid.default_main_program(), feed={"x": xv},
                         fetch_list=[loss.name, "x@GRAD"], scope=scope)
        expect = np.sqrt(3.0) * np.sqrt(2.0) * np.sqrt(1.0)
        np.testing.assert_allclose(lv, (xv * expect).mean(), rtol=1e-5)
        assert np.isfinite(gx).all()
        np.testing.assert_allclose(gx, np.full_like(xv, expect / xv.size),
                                   rtol=1e-5)


def test_while_bounded_early_exit_masking():
    """max_trip_count larger than actual trips: extra iterations must not
    change the result (active-mask passes the carry through)."""
    for trips in (3, 8, 16):
        scope = Scope()
        with scope_guard(scope), program_guard(Program(), Program()):
            x, w, loss = _build_geometric_loop(max_trips=trips)
            exe = fluid.Executor()
            exe.run(fluid.default_startup_program(), scope=scope)
            scope.set_var("w_scale", np.array([2.0], np.float32))
            xv = np.array([[1.0, 1.0, 1.0, 1.0]], np.float32)
            lv, = exe.run(fluid.default_main_program(), feed={"x": xv},
                          fetch_list=[loss.name], scope=scope)
            np.testing.assert_allclose(lv, 8.0, rtol=1e-5,
                                       err_msg=f"trips={trips}")


def test_bounded_while_truncation_warns(capfd):
    """An under-sized max_trip_count must shout at runtime (ADVICE r2):
    the final carried condition is still true -> jax.debug.print fires."""
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        i = layers.fill_constant(shape=[1], dtype="float32", value=0)
        limit = layers.fill_constant(shape=[1], dtype="float32", value=10)
        cond = layers.less_than(i, limit)
        w = layers.While(cond, max_trip_count=3)   # loop needs 10 trips
        with w.block():
            nxt = i + 1.0
            layers.assign(nxt, i)
            layers.less_than(i, limit, cond=cond)
        exe = fluid.framework.Executor()
        exe.run(fluid.default_startup_program(), scope=scope)
        out, = exe.run(fluid.default_main_program(),
                       fetch_list=[i.name], scope=scope)
    assert float(out[0]) == 3.0          # truncated result
    captured = capfd.readouterr()
    assert "truncated" in captured.out or "truncated" in captured.err
