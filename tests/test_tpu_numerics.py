"""On-hardware numerics sweep (VERDICT r1 weak #5): op-level checks on the
real TPU chip with per-dtype tolerance profiles, vs float64 numpy
references.  The reference runs OpTest on both CPUPlace and CUDAPlace
(``tests/unittests/op_test.py:729``); this is the TPU analog.

Run:  PADDLE_TPU_TEST_HW=1 python -m pytest -m tpu_hw tests/test_tpu_numerics.py -q
Skipped automatically on the CPU-mesh test config.
"""

import json
import os

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import (Program, Scope, append_backward,
                                  program_guard, scope_guard)

pytestmark = pytest.mark.tpu_hw


def _record(op, **metrics):
    """Append measured error norms to the sweep artifact when the runner
    (tools/run_tpu_numerics.py) asks for them via env."""
    path = os.environ.get("PADDLE_TPU_NUMERICS_OUT")
    if path:
        with open(path, "a") as f:
            f.write(json.dumps({"op": op, **{
                k: (float(v) if isinstance(v, (int, float, np.floating))
                    else v) for k, v in metrics.items()}}) + "\n")

# TPU tolerance profile: f32 matmuls/convs run bf16-ish passes at default
# precision (per-test bounds below); elementwise/reduction f32 is exact-ish


def test_matmul_mxu_tolerance():
    rng = np.random.RandomState(0)
    a = rng.randn(64, 128).astype(np.float32)
    b = rng.randn(128, 96).astype(np.float32)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("a", shape=[128], dtype="float32")
        w = layers.create_parameter([128, 96], "float32", name="w_mm")
        out = layers.matmul(x, w)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        scope.set_var("w_mm", b)
        got, = exe.run(fluid.default_main_program(), feed={"a": a},
                       fetch_list=[out.name], scope=scope)
    want = a.astype(np.float64) @ b.astype(np.float64)
    # bf16-pass error grows as ~2^-8·sqrt(K)·|a||b| (K=128 → σ≈0.045);
    # near-zero dot products make pure rtol meaningless, so bound the
    # absolute error at ~5σ and the overall relative RMS
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.25)
    rms_rel = np.sqrt(((got - want) ** 2).mean() / (want ** 2).mean())
    assert rms_rel < 5e-3, rms_rel


def test_softmax_cross_entropy_vpu():
    rng = np.random.RandomState(1)
    logits = rng.randn(32, 10).astype(np.float32)
    labels = rng.randint(0, 10, (32, 1)).astype(np.int64)

    def ref():
        x = logits.astype(np.float64)
        m = x.max(1, keepdims=True)
        lse = np.log(np.exp(x - m).sum(1, keepdims=True)) + m
        return (lse[:, 0] - x[np.arange(32), labels[:, 0]]).mean()

    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[10], dtype="float32")
        y = layers.data("y", shape=[1], dtype="int64")
        loss = layers.mean(layers.softmax_with_cross_entropy(x, y))
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        got, = exe.run(fluid.default_main_program(),
                       feed={"x": logits, "y": labels},
                       fetch_list=[loss.name], scope=scope)
    np.testing.assert_allclose(float(got), ref(), rtol=1e-4, atol=1e-5)


def test_layer_norm_stats_f32():
    rng = np.random.RandomState(2)
    xv = (rng.randn(16, 256) * 50 + 1000).astype(np.float32)  # big offset

    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[256], dtype="float32")
        y = layers.layer_norm(x, begin_norm_axis=1)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        got, = exe.run(fluid.default_main_program(), feed={"x": xv},
                       fetch_list=[y.name], scope=scope)
    xf = xv.astype(np.float64)
    m = xf.mean(1, keepdims=True)
    v = xf.var(1, keepdims=True)
    want = (xf - m) / np.sqrt(v + 1e-5)
    # stats must be computed in f32: a bf16-stats implementation would be
    # off by O(1) at mean≈1000, not O(1e-2)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_conv2d_grad_numeric():
    rng = np.random.RandomState(3)
    xv = rng.randn(2, 3, 8, 8).astype(np.float32)

    def run(place):
        scope = Scope()
        with scope_guard(scope), program_guard(Program(), Program()):
            x = layers.data("x", shape=[3, 8, 8], dtype="float32")
            x.stop_gradient = False
            conv = layers.conv2d(x, num_filters=4, filter_size=3,
                                 padding=1,
                                 param_attr=fluid.ParamAttr(name="cw"))
            loss = layers.mean(conv * conv)
            append_backward(loss)
            exe = fluid.Executor(place)
            exe.run(fluid.default_startup_program(), scope=scope, seed=5)
            w = np.asarray(scope.find_var("cw"))
            l, gx = exe.run(fluid.default_main_program(), feed={"x": xv},
                            fetch_list=[loss.name, "x@GRAD"], scope=scope)
            return np.asarray(l), np.asarray(gx), w

    l_tpu, gx_tpu, w_tpu = run(fluid.TPUPlace(0))
    # numeric check of dL/dx against central differences on-device
    eps = 1e-2
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[3, 8, 8], dtype="float32")
        conv = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                             param_attr=fluid.ParamAttr(name="cw"))
        loss = layers.mean(conv * conv)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope, seed=5)
        scope.set_var("cw", w_tpu)

        def f(xx):
            l, = exe.run(fluid.default_main_program(), feed={"x": xx},
                         fetch_list=[loss.name], scope=scope)
            return float(np.asarray(l))

        idxs = [(0, 0, 2, 3), (1, 2, 5, 5), (0, 1, 7, 0)]
        for idx in idxs:
            xp = xv.copy(); xp[idx] += eps
            xm = xv.copy(); xm[idx] -= eps
            numeric = (f(xp) - f(xm)) / (2 * eps)
            np.testing.assert_allclose(gx_tpu[idx], numeric, rtol=5e-2,
                                       atol=5e-3)


def test_embedding_int_ids_roundtrip():
    """int64-declared ids run as int32 on device — values must be exact."""
    rng = np.random.RandomState(4)
    ids = rng.randint(0, 50, (8, 6)).astype(np.int64)
    table = rng.randn(50, 16).astype(np.float32)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[6], dtype="int64")
        emb = layers.embedding(x, size=[50, 16],
                               param_attr=fluid.ParamAttr(name="emb_w"))
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        scope.set_var("emb_w", table)
        got, = exe.run(fluid.default_main_program(), feed={"x": ids},
                       fetch_list=[emb.name], scope=scope)
    np.testing.assert_allclose(got, table[ids], rtol=1e-6, atol=1e-6)


def test_reduction_dtypes():
    rng = np.random.RandomState(5)
    xv = rng.rand(16, 1000).astype(np.float32)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[1000], dtype="float32")
        s = layers.reduce_sum(x)
        m = layers.reduce_mean(x)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        sv, mv = exe.run(fluid.default_main_program(), feed={"x": xv},
                         fetch_list=[s.name, m.name], scope=scope)
    np.testing.assert_allclose(float(sv), xv.astype(np.float64).sum(),
                               rtol=1e-5)
    np.testing.assert_allclose(float(mv), xv.astype(np.float64).mean(),
                               rtol=1e-5)


def test_conv2d_bf16_amp():
    """AMP casts conv inputs to bf16 (MXU path); error must stay within
    the bf16 error model ~2^-8·sqrt(K) relative RMS (K = C·kh·kw)."""
    rng = np.random.RandomState(6)
    xv = rng.randn(2, 8, 16, 16).astype(np.float32)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[8, 16, 16], dtype="float32")
        conv = layers.conv2d(x, num_filters=16, filter_size=3, padding=1,
                             bias_attr=False,
                             param_attr=fluid.ParamAttr(name="cw_bf16"))
        fluid.amp.enable()
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope, seed=6)
        w = np.asarray(scope.find_var("cw_bf16"))
        got, = exe.run(fluid.default_main_program(), feed={"x": xv},
                       fetch_list=[conv.name], scope=scope)
    # f64 reference conv (NCHW direct)
    from numpy.lib.stride_tricks import sliding_window_view
    xp = np.pad(xv.astype(np.float64),
                ((0, 0), (0, 0), (1, 1), (1, 1)))
    win = sliding_window_view(xp, (3, 3), axis=(2, 3))   # [B,C,H,W,3,3]
    want = np.einsum("bchwij,ocij->bohw", win, w.astype(np.float64))
    err = np.asarray(got, np.float64) - want
    rms_rel = np.sqrt((err ** 2).mean() / (want ** 2).mean())
    _record("conv2d_bf16", rms_rel=rms_rel, max_abs=np.abs(err).max())
    assert rms_rel < 2e-2, rms_rel      # bf16 model: 2^-8·sqrt(72) ≈ 0.03


def test_batch_norm_onepass_stats():
    """Training-mode BN computes one-pass E[x²]−E[x]² stats (r3 perf
    change).  The m² cancellation must stay benign at mean≫std — the
    exact regime where a naive implementation loses digits."""
    rng = np.random.RandomState(7)
    xv = (rng.randn(8, 4, 10, 10) * 0.5 + 100.0).astype(np.float32)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[4, 10, 10], dtype="float32")
        y = layers.batch_norm(x)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        got, = exe.run(fluid.default_main_program(), feed={"x": xv},
                       fetch_list=[y.name], scope=scope)
    xf = xv.astype(np.float64)
    m = xf.mean(axis=(0, 2, 3), keepdims=True)
    v = xf.var(axis=(0, 2, 3), keepdims=True)
    want = (xf - m) / np.sqrt(v + 1e-5)
    err = np.abs(np.asarray(got, np.float64) - want)
    _record("batch_norm_onepass", max_abs=err.max(),
            mean_offset=100.0, std=0.5)
    # at mean=100, std=0.5: E[x²]≈10000.25, cancellation leaves ~4 good
    # digits of variance in f32 → normalized output good to ~1e-2
    np.testing.assert_allclose(np.asarray(got), want, rtol=5e-2, atol=5e-2)


def test_int64_feed_wrap_warns():
    """ids beyond int32 wrap on device (x64 off) — the executor must warn
    on the first offending feed (ADVICE r2: silent truncation)."""
    import warnings
    from paddle_tpu.framework import executor as ex_mod
    big = np.array([[2 ** 40]], dtype=np.int64)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[1], dtype="int64")
        y = layers.cast(x, "float32") * 2.0
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        ex_mod._checked_int64_feeds.discard("x")
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            exe.run(fluid.default_main_program(), feed={"x": big},
                    fetch_list=[y.name], scope=scope)
    assert any("WRAP" in str(x.message) for x in w), \
        [str(x.message) for x in w]


def test_int32_arithmetic_exact_in_range():
    """int64-declared arithmetic inside the int32 range must be EXACT on
    device (the r1 int32-truncation warning paths, now canonicalized)."""
    # values chosen so sums and doubles stay inside int32
    vals = np.array([[2 ** 29, -2 ** 29, 123456789, -1]], dtype=np.int64)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[4], dtype="int64")
        s = layers.reduce_sum(x)
        p = layers.elementwise_add(x, x)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        sv, pv = exe.run(fluid.default_main_program(), feed={"x": vals},
                         fetch_list=[s.name, p.name], scope=scope)
    _record("int64_as_int32", sum_exact=bool(
        int(np.asarray(sv)) == int(vals.sum())))
    assert int(np.asarray(sv)) == int(vals.sum())
    np.testing.assert_array_equal(np.asarray(pv), (vals + vals))
