"""On-hardware numerics sweep (VERDICT r1 weak #5): op-level checks on the
real TPU chip with per-dtype tolerance profiles, vs float64 numpy
references.  The reference runs OpTest on both CPUPlace and CUDAPlace
(``tests/unittests/op_test.py:729``); this is the TPU analog.

Run:  PADDLE_TPU_TEST_HW=1 python -m pytest -m tpu_hw tests/test_tpu_numerics.py -q
Skipped automatically on the CPU-mesh test config.
"""

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import (Program, Scope, append_backward,
                                  program_guard, scope_guard)

pytestmark = pytest.mark.tpu_hw

# TPU tolerance profile: f32 matmuls/convs run bf16-ish passes at default
# precision (per-test bounds below); elementwise/reduction f32 is exact-ish


def test_matmul_mxu_tolerance():
    rng = np.random.RandomState(0)
    a = rng.randn(64, 128).astype(np.float32)
    b = rng.randn(128, 96).astype(np.float32)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("a", shape=[128], dtype="float32")
        w = layers.create_parameter([128, 96], "float32", name="w_mm")
        out = layers.matmul(x, w)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        scope.set_var("w_mm", b)
        got, = exe.run(fluid.default_main_program(), feed={"a": a},
                       fetch_list=[out.name], scope=scope)
    want = a.astype(np.float64) @ b.astype(np.float64)
    # bf16-pass error grows as ~2^-8·sqrt(K)·|a||b| (K=128 → σ≈0.045);
    # near-zero dot products make pure rtol meaningless, so bound the
    # absolute error at ~5σ and the overall relative RMS
    np.testing.assert_allclose(got, want, rtol=0.05, atol=0.25)
    rms_rel = np.sqrt(((got - want) ** 2).mean() / (want ** 2).mean())
    assert rms_rel < 5e-3, rms_rel


def test_softmax_cross_entropy_vpu():
    rng = np.random.RandomState(1)
    logits = rng.randn(32, 10).astype(np.float32)
    labels = rng.randint(0, 10, (32, 1)).astype(np.int64)

    def ref():
        x = logits.astype(np.float64)
        m = x.max(1, keepdims=True)
        lse = np.log(np.exp(x - m).sum(1, keepdims=True)) + m
        return (lse[:, 0] - x[np.arange(32), labels[:, 0]]).mean()

    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[10], dtype="float32")
        y = layers.data("y", shape=[1], dtype="int64")
        loss = layers.mean(layers.softmax_with_cross_entropy(x, y))
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        got, = exe.run(fluid.default_main_program(),
                       feed={"x": logits, "y": labels},
                       fetch_list=[loss.name], scope=scope)
    np.testing.assert_allclose(float(got), ref(), rtol=1e-4, atol=1e-5)


def test_layer_norm_stats_f32():
    rng = np.random.RandomState(2)
    xv = (rng.randn(16, 256) * 50 + 1000).astype(np.float32)  # big offset

    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[256], dtype="float32")
        y = layers.layer_norm(x, begin_norm_axis=1)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        got, = exe.run(fluid.default_main_program(), feed={"x": xv},
                       fetch_list=[y.name], scope=scope)
    xf = xv.astype(np.float64)
    m = xf.mean(1, keepdims=True)
    v = xf.var(1, keepdims=True)
    want = (xf - m) / np.sqrt(v + 1e-5)
    # stats must be computed in f32: a bf16-stats implementation would be
    # off by O(1) at mean≈1000, not O(1e-2)
    np.testing.assert_allclose(got, want, rtol=2e-2, atol=2e-2)


def test_conv2d_grad_numeric():
    rng = np.random.RandomState(3)
    xv = rng.randn(2, 3, 8, 8).astype(np.float32)

    def run(place):
        scope = Scope()
        with scope_guard(scope), program_guard(Program(), Program()):
            x = layers.data("x", shape=[3, 8, 8], dtype="float32")
            x.stop_gradient = False
            conv = layers.conv2d(x, num_filters=4, filter_size=3,
                                 padding=1,
                                 param_attr=fluid.ParamAttr(name="cw"))
            loss = layers.mean(conv * conv)
            append_backward(loss)
            exe = fluid.Executor(place)
            exe.run(fluid.default_startup_program(), scope=scope, seed=5)
            w = np.asarray(scope.find_var("cw"))
            l, gx = exe.run(fluid.default_main_program(), feed={"x": xv},
                            fetch_list=[loss.name, "x@GRAD"], scope=scope)
            return np.asarray(l), np.asarray(gx), w

    l_tpu, gx_tpu, w_tpu = run(fluid.TPUPlace(0))
    # numeric check of dL/dx against central differences on-device
    eps = 1e-2
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[3, 8, 8], dtype="float32")
        conv = layers.conv2d(x, num_filters=4, filter_size=3, padding=1,
                             param_attr=fluid.ParamAttr(name="cw"))
        loss = layers.mean(conv * conv)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope, seed=5)
        scope.set_var("cw", w_tpu)

        def f(xx):
            l, = exe.run(fluid.default_main_program(), feed={"x": xx},
                         fetch_list=[loss.name], scope=scope)
            return float(np.asarray(l))

        idxs = [(0, 0, 2, 3), (1, 2, 5, 5), (0, 1, 7, 0)]
        for idx in idxs:
            xp = xv.copy(); xp[idx] += eps
            xm = xv.copy(); xm[idx] -= eps
            numeric = (f(xp) - f(xm)) / (2 * eps)
            np.testing.assert_allclose(gx_tpu[idx], numeric, rtol=5e-2,
                                       atol=5e-3)


def test_embedding_int_ids_roundtrip():
    """int64-declared ids run as int32 on device — values must be exact."""
    rng = np.random.RandomState(4)
    ids = rng.randint(0, 50, (8, 6)).astype(np.int64)
    table = rng.randn(50, 16).astype(np.float32)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[6], dtype="int64")
        emb = layers.embedding(x, size=[50, 16],
                               param_attr=fluid.ParamAttr(name="emb_w"))
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        scope.set_var("emb_w", table)
        got, = exe.run(fluid.default_main_program(), feed={"x": ids},
                       fetch_list=[emb.name], scope=scope)
    np.testing.assert_allclose(got, table[ids], rtol=1e-6, atol=1e-6)


def test_reduction_dtypes():
    rng = np.random.RandomState(5)
    xv = rng.rand(16, 1000).astype(np.float32)
    scope = Scope()
    with scope_guard(scope), program_guard(Program(), Program()):
        x = layers.data("x", shape=[1000], dtype="float32")
        s = layers.reduce_sum(x)
        m = layers.reduce_mean(x)
        exe = fluid.Executor(fluid.TPUPlace(0))
        exe.run(fluid.default_startup_program(), scope=scope)
        sv, mv = exe.run(fluid.default_main_program(), feed={"x": xv},
                         fetch_list=[s.name, m.name], scope=scope)
    np.testing.assert_allclose(float(sv), xv.astype(np.float64).sum(),
                               rtol=1e-5)
    np.testing.assert_allclose(float(mv), xv.astype(np.float64).mean(),
                               rtol=1e-5)
