"""Activation rematerialization (framework/recompute.py; no reference
counterpart — SURVEY §5.7 notes the 2019 codebase has no recompute)."""

import numpy as np

import paddle_tpu as fluid
from paddle_tpu import layers
from paddle_tpu.framework import Executor
from paddle_tpu.framework.core import Program, program_guard
from paddle_tpu.framework.scope import Scope, scope_guard


def _build(n_layers=3):
    x = layers.data("x", shape=[16], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    h = x
    ckpts = []
    for i in range(n_layers):
        h = layers.fc(h, size=16, act="tanh")
        ckpts.append(h)
    pred = layers.fc(h, size=1)
    loss = layers.mean(layers.square_error_cost(pred, y))
    return loss, ckpts


def _train(recompute, steps=10):
    with program_guard(Program(), Program()), scope_guard(Scope()):
        loss, ckpts = _build()
        opt = fluid.optimizer.Adam(0.01)
        if recompute:
            opt = fluid.optimizer.RecomputeOptimizer(opt)
            opt._set_checkpoints(ckpts)
        opt.minimize(loss)
        prog = fluid.default_main_program()
        exe = Executor()
        exe.run(fluid.default_startup_program(), seed=11)
        rng = np.random.RandomState(0)
        out = []
        for _ in range(steps):
            xv = rng.rand(8, 16).astype(np.float32)
            yv = xv.sum(1, keepdims=True).astype(np.float32)
            lv, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
            out.append(float(lv))
        return out, prog


def test_recompute_exact_parity():
    """Recompute must not change a single gradient: loss trajectories are
    bit-identical to the stored-activation run."""
    base, _ = _train(False)
    rc, prog = _train(True)
    np.testing.assert_allclose(base, rc, rtol=0, atol=0)
    types = [op.type for op in prog.global_block().ops]
    assert "optimization_barrier" in types
    assert any("@RECOMPUTE" in n for op in prog.global_block().ops
               for n in op.output_arg_names())


def test_recompute_replays_tagged_dropout():
    """Tagged dropout is replay-safe — its bits are a pure function of
    (per-step key, tag) — so recompute re-emits it instead of storing its
    output; untagged (seed=0) dropout stays stored."""
    with program_guard(Program(), Program()), scope_guard(Scope()):
        x = layers.data("x", shape=[16], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="tanh")
        c1 = h
        h = layers.dropout(h, dropout_prob=0.5)        # tagged (default)
        h = layers.fc(h, size=16, act="tanh")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.1))
        opt._set_checkpoints([c1])
        opt.minimize(loss)
        prog = fluid.default_main_program()
        recomputed = [op for op in prog.global_block().ops
                      if op.type == "dropout" and
                      any("@RECOMPUTE" in n for n in op.output_arg_names())]
        assert recomputed, "tagged dropout should re-emit in the remat chain"
        exe = Executor()
        exe.run(fluid.default_startup_program(), seed=3)
        rng = np.random.RandomState(1)
        last = None
        for _ in range(8):
            xv = rng.rand(8, 16).astype(np.float32)
            yv = xv.sum(1, keepdims=True).astype(np.float32)
            last, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        assert np.isfinite(float(last))


def test_recompute_keeps_untagged_dropout_stored():
    """seed=0 (legacy untagged) dropout draws from the counter stream, so
    re-drawing would change gradients — it must stay OUT of the chain."""
    from paddle_tpu.layer_helper import LayerHelper
    with program_guard(Program(), Program()), scope_guard(Scope()):
        x = layers.data("x", shape=[16], dtype="float32")
        y = layers.data("y", shape=[1], dtype="float32")
        h = layers.fc(x, size=16, act="tanh")
        c1 = h
        helper = LayerHelper("dropout")
        out = helper.create_variable_for_type_inference(h.dtype)
        mask = helper.create_variable_for_type_inference("uint8", True)
        helper.append_op("dropout", inputs={"X": [h]},
                         outputs={"Out": [out], "Mask": [mask]},
                         attrs={"dropout_prob": 0.5, "is_test": False,
                                "seed": 0,
                                "dropout_implementation":
                                    "downgrade_in_infer"})
        h = layers.fc(out, size=16, act="tanh")
        pred = layers.fc(h, size=1)
        loss = layers.mean(layers.square_error_cost(pred, y))
        opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.1))
        opt._set_checkpoints([c1])
        opt.minimize(loss)
        prog = fluid.default_main_program()
        for op in prog.global_block().ops:
            if op.type == "dropout":
                assert not any("@RECOMPUTE" in n
                               for n in op.output_arg_names())


def test_backward_entry_point_applies_recompute():
    """The fluid-style backward()/apply_gradients flow must also remat."""
    with program_guard(Program(), Program()), scope_guard(Scope()):
        loss, ckpts = _build()
        opt = fluid.optimizer.RecomputeOptimizer(fluid.optimizer.SGD(0.1))
        opt._set_checkpoints(ckpts)
        pg = opt.backward(loss)
        opt.apply_gradients(pg)
        prog = fluid.default_main_program()
        types = [op.type for op in prog.global_block().ops]
        assert "optimization_barrier" in types
        # weights are NOT fenced (barriers only on stored activations)
        for op in prog.global_block().ops:
            if op.type == "optimization_barrier":
                src = op.input("X")[0]
                v = prog.global_block().vars.get(src)
                assert v is None or not v.persistable
        exe = Executor()
        exe.run(fluid.default_startup_program())
        xv = np.random.rand(4, 16).astype(np.float32)
        yv = xv.sum(1, keepdims=True).astype(np.float32)
        lv, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        assert np.isfinite(float(lv))
