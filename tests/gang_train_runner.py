"""Subprocess runner for the gang-coordinated checkpoint tests.

One rank of a gang (file rendezvous or socket coordinator — selected by
the env, see ``GangRendezvous.from_env``): trains the same deterministic
linear-regression loop as ``resilience_train_runner.py`` with a
background :class:`CheckpointDaemon` committing every
``GANG_CKPT_INTERVAL`` steps and announcing to the gang; rank 0 publishes
the ``COMMITTED`` manifest.  Prints per step ``STEP <i> LOSS <repr>``
(repr round-trips float32 exactly) and appends completed step indices to
a per-rank progress file (``<PROGRESS_FILE>.r<rank>``) the parent polls.

Usage::

    python gang_train_runner.py CKPT_ROOT TOTAL_STEPS PROGRESS_FILE \
        [SLEEP_PER_STEP]

Env contract (set by the parent test or the launcher):

- ``PADDLE_TRAINER_ID`` / ``PADDLE_TRAINERS_NUM`` / ``PADDLE_GANG_DIR``
  / ``PADDLE_GANG_COORD`` — the launcher's gang contract; each rank
  checkpoints into ``CKPT_ROOT/rank_<id>``.
- ``GANG_CKPT_INTERVAL`` — daemon cadence in steps (default 2).
- ``GANG_EMERGENCY_HANG=1`` — on preemption, make the emergency
  checkpoint write hang (fault-inject ``checkpoint.write`` in hang
  mode) so the parent can SIGKILL this rank mid-emergency-save: the
  torn-save scenario.
- ``GANG_AVOID_MULTIPLE=N`` — keep looping past a preemption until the
  completed-step count is NOT a multiple of N (makes the emergency step
  provably un-announceable by a rank whose cadence is N — the parent
  uses it to force a deterministic torn reject).
- ``GANG_SELF_KILL=RANK:STEP`` — rank RANK SIGKILLs itself at the top
  of step STEP, exactly once per CKPT_ROOT (a marker file arms it):
  the elastic-recovery scenario, run under ``launch.py
  --max_restarts`` which respawns the rank.
- ``GANG_FP_OVERRIDE`` — report this string as the rank's collective
  fingerprint on the socket liveness plane (tests force a cross-rank
  mismatch with it).

Under the socket backend the loop also exercises the liveness plane:
every step updates the heartbeat payload (current step, committed list,
collective fingerprint), and when the coordinator reports the gang
degraded (a peer died) the rank drains its in-flight steps through the
guard and PARKS in ``wait_ready`` until the launcher respawns the peer —
printing ``GANG_DEGRADED dead=[...]`` / ``GANG_READY 1`` around the
park, so the parent can assert the survivor actually took that path.

On SIGTERM the guard drains, commits the last complete step, announces
it, and (rank 0) runs the gang barrier; exit 0.  A rerun with the same
CKPT_ROOT resumes every rank from the manifest step via
``resume_or_init`` (printing ``RESUMED_AT <step>`` and
``TORN_REJECTS <n>``) and finishes the remaining steps.
"""

import os
import signal
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import paddle_tpu as pt  # noqa: E402
from paddle_tpu import layers, monitor  # noqa: E402
from paddle_tpu.checkpoint import CheckpointManager  # noqa: E402
from paddle_tpu.distributed.env import GangRendezvous  # noqa: E402
from paddle_tpu.framework import Executor  # noqa: E402
from paddle_tpu.resilience import (CheckpointDaemon,  # noqa: E402
                                   PreemptionGuard, resume_or_init)


def batch(step):
    rng = np.random.RandomState(1234 + step)
    x = rng.rand(8, 4).astype(np.float32)
    return x, x.sum(1, keepdims=True).astype(np.float32)


def main():
    root, total, progress = sys.argv[1], int(sys.argv[2]), sys.argv[3]
    pause = float(sys.argv[4]) if len(sys.argv) > 4 else 0.0
    rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    interval = int(os.environ.get("GANG_CKPT_INTERVAL", "2"))
    avoid = int(os.environ.get("GANG_AVOID_MULTIPLE", "0"))
    progress = f"{progress}.r{rank}"
    kill_rank, kill_step = -1, -1
    if os.environ.get("GANG_SELF_KILL"):
        kr, _, ks = os.environ["GANG_SELF_KILL"].partition(":")
        kill_rank, kill_step = int(kr), int(ks)
    kill_marker = os.path.join(root, f"killed_rank_{rank}")

    pt.default_startup_program().random_seed = 7
    pt.default_main_program().random_seed = 7
    x = layers.data("x", shape=[4], dtype="float32")
    y = layers.data("y", shape=[1], dtype="float32")
    pred = layers.fc(x, size=1, param_attr=pt.ParamAttr(name="gt_w"),
                     bias_attr=pt.ParamAttr(name="gt_b"))
    loss = layers.mean(layers.square_error_cost(pred, y))
    pt.optimizer.Adam(0.05).minimize(loss)

    exe = Executor()
    gang = GangRendezvous.from_env()
    socket_gang = gang is not None and \
        getattr(gang, "backend", "file") == "socket"
    if socket_gang:
        print(f"GANG_BACKEND socket {gang.address}", flush=True)
        fp = os.environ.get("GANG_FP_OVERRIDE")
        if not fp:
            try:
                from paddle_tpu.analysis.verifier import \
                    collective_fingerprint
                fp = collective_fingerprint(pt.default_main_program())
            except Exception:
                fp = None
        if fp:
            gang.set_progress(fingerprint=fp)
    ckpt = CheckpointManager(os.path.join(root, f"rank_{rank}"),
                             max_to_keep=50)
    before = monitor.counter_totals()
    start = resume_or_init(ckpt, exe,
                           startup_program=pt.default_startup_program(),
                           main_program=pt.default_main_program(),
                           gang=gang)
    after = monitor.counter_totals()
    torn = int(after.get("paddle_tpu_checkpoint_torn_rejects_total", 0)
               - before.get("paddle_tpu_checkpoint_torn_rejects_total", 0))
    print(f"RESUMED_AT {start}", flush=True)
    print(f"TORN_REJECTS {torn}", flush=True)

    daemon = CheckpointDaemon(ckpt, program=pt.default_main_program(),
                              interval_steps=interval, interval_secs=0,
                              gang=gang).start()
    with PreemptionGuard(ckpt, executor=exe,
                         program=pt.default_main_program(),
                         daemon=daemon, gang=gang, exit_code=0) as guard:
        for step in range(start, total):
            if rank == kill_rank and step == kill_step and \
                    not os.path.exists(kill_marker):
                # arm-once marker BEFORE the kill: the respawned rank
                # must not re-kill itself when it re-reaches this step
                with open(kill_marker, "w") as f:
                    f.write(str(step))
                    f.flush()
                    os.fsync(f.fileno())
                print(f"SELF_KILL {step}", flush=True)
                os.kill(os.getpid(), signal.SIGKILL)
            xv, yv = batch(step)
            out, = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
            print(f"STEP {step} LOSS {float(np.asarray(out).ravel()[0])!r}",
                  flush=True)
            guard.completed_step(step + 1)
            if socket_gang:
                gang.set_progress(step=step + 1)
            if os.environ.get("GANG_SYNC_COMMITS") and \
                    daemon._last_capture_step == step + 1:
                # test mode: make every cadence commit deterministic so
                # the parent can reason about exactly which steps each
                # rank announced (coalescing under load would make the
                # committed set timing-dependent)
                daemon.wait_committed(step + 1)
            with open(progress, "a") as f:
                f.write(f"{step}\n")
                f.flush()
                os.fsync(f.fileno())
            if pause:
                time.sleep(pause)
            if socket_gang and gang.degraded:
                # a peer died: drain in-flight steps (never park inside
                # a collective) and wait at the rejoin barrier for the
                # launcher to respawn it
                print(f"GANG_DEGRADED dead={gang.dead_ranks}", flush=True)
                guard.drain()
                ok = gang.wait_ready()
                print(f"GANG_READY {int(bool(ok))}", flush=True)
                if not ok:
                    raise SystemExit(
                        "gang never reconverged; aborting rank")
            if guard.preempted:
                if avoid and (step + 1) % avoid == 0:
                    continue     # force an un-announceable emergency step
                if os.environ.get("GANG_EMERGENCY_HANG"):
                    # the emergency save's checkpoint.write now hangs —
                    # the parent SIGKILLs this rank mid-emergency-save
                    pt.set_flags({"FLAGS_fault_inject":
                                  "checkpoint.write:every=1,hang=120"})
                break
    # clean completion (no preemption): flush a final committed step
    daemon.stop(final_step=total)
    print("DONE", flush=True)


if __name__ == "__main__":
    main()
