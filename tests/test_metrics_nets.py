"""Metrics aggregators, Evaluator, distributions, nets (ref
python/paddle/fluid/{metrics,evaluator,nets}.py, layers/distributions.py)."""

import math

import numpy as np
import pytest

import paddle_tpu as fluid
from paddle_tpu import layers, metrics, nets
from paddle_tpu.framework import Executor
from paddle_tpu.framework.core import Program, program_guard
from paddle_tpu.layers import distributions


def _fresh():
    return program_guard(Program(), Program())


# -- metrics ----------------------------------------------------------------

def test_precision_recall_accuracy():
    p, r = metrics.Precision(), metrics.Recall()
    preds = np.array([1, 1, 0, 1, 0])
    labels = np.array([1, 0, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert p.eval() == pytest.approx(2 / 3)
    assert r.eval() == pytest.approx(2 / 3)
    a = metrics.Accuracy()
    a.update(0.5, 10)
    a.update(1.0, 10)
    assert a.eval() == pytest.approx(0.75)
    a.reset()
    with pytest.raises(ValueError):
        a.eval()


def test_auc_against_sklearn_free_reference():
    rng = np.random.RandomState(0)
    scores = rng.rand(500)
    labels = (scores + rng.rand(500) * 0.7 > 0.8).astype(np.int64)
    m = metrics.Auc()
    m.update(np.stack([1 - scores, scores], 1), labels)
    # exact rank-statistic AUC
    pos, neg = scores[labels == 1], scores[labels == 0]
    exact = np.mean([(pos_i > neg).mean() + 0.5 * (pos_i == neg).mean()
                     for pos_i in pos])
    assert m.eval() == pytest.approx(exact, abs=2e-3)


def test_chunk_edit_composite():
    c = metrics.ChunkEvaluator()
    c.update(10, 8, 6)
    prec, rec, f1 = c.eval()
    assert prec == pytest.approx(0.6)
    assert rec == pytest.approx(0.75)
    assert f1 == pytest.approx(2 * 0.6 * 0.75 / 1.35)
    e = metrics.EditDistance()
    e.update(np.array([0.0, 2.0, 1.0]), 3)
    avg, err = e.eval()
    assert avg == pytest.approx(1.0)
    assert err == pytest.approx(2 / 3)
    comp = metrics.CompositeMetric()
    comp.add_metric(metrics.Precision())
    comp.add_metric(metrics.Recall())
    comp.update(np.array([1, 0]), np.array([1, 1]))
    assert comp.eval() == [1.0, 0.5]


def test_detection_map():
    m = metrics.DetectionMAP(overlap_threshold=0.5)
    gt = np.array([[0, 0, 0, 10, 10], [1, 20, 20, 30, 30]], np.float32)
    pred = np.array([[0, 0.9, 0, 0, 10, 10],       # perfect match
                     [1, 0.8, 21, 21, 30, 30],     # good match
                     [1, 0.7, 50, 50, 60, 60]],    # false positive
                    np.float32)
    m.update(pred, gt)
    val = m.eval()
    assert 0.9 <= val <= 1.0   # both classes found, one fp after the tp


# -- evaluator --------------------------------------------------------------

def test_evaluator_wrappers():
    from paddle_tpu.evaluator import ChunkEvaluator, EditDistance
    c = ChunkEvaluator()
    c.update(4, 4, 4)
    assert c.eval() == (1.0, 1.0, 1.0)
    c.reset()
    e = EditDistance()
    e.update([1.0], 1)
    assert e.eval()[0] == 1.0


# -- distributions ----------------------------------------------------------

def test_normal_uniform_distributions():
    with _fresh():
        n = distributions.Normal(0.0, 2.0)
        u = distributions.Uniform(1.0, 3.0)
        x = layers.data("x", shape=[1], dtype="float32")
        ent_n = n.entropy()
        lp = n.log_prob(x)
        s = n.sample([1000], seed=5)
        ent_u = u.entropy()
        su = u.sample([1000], seed=7)
        kl = n.kl_divergence(distributions.Normal(1.0, 1.0))
        exe = Executor()
        xv = np.array([[1.0]], np.float32)
        en, lpv, sv, eu, suv, klv = exe.run(
            feed={"x": xv}, fetch_list=[ent_n, lp, s, ent_u, su, kl])
        assert float(en[0]) == pytest.approx(
            0.5 + 0.5 * math.log(2 * math.pi) + math.log(2.0), rel=1e-5)
        assert float(lpv[0, 0]) == pytest.approx(
            -0.125 - math.log(2.0) - 0.5 * math.log(2 * math.pi), rel=1e-5)
        assert abs(np.mean(sv)) < 0.3 and 1.5 < np.std(sv) < 2.5
        assert float(eu[0]) == pytest.approx(math.log(2.0), rel=1e-5)
        assert suv.min() >= 1.0 and suv.max() <= 3.0
        # KL(N(0,2) || N(1,1)) = 0.5*(4 + 1 - 1 - ln 4)
        assert float(klv[0]) == pytest.approx(
            0.5 * (4 + 1 - 1 - math.log(4.0)), rel=1e-5)


def test_categorical_and_mvn():
    with _fresh():
        logits = layers.assign(np.array([1.0, 2.0, 3.0], np.float32))
        c = distributions.Categorical(logits)
        c2 = distributions.Categorical(
            layers.assign(np.array([3.0, 2.0, 1.0], np.float32)))
        ent = c.entropy()
        kl = c.kl_divergence(c2)
        m1 = distributions.MultivariateNormalDiag(
            layers.assign(np.zeros(2, np.float32)),
            layers.assign(np.eye(2, dtype=np.float32) * 2.0))
        m2 = distributions.MultivariateNormalDiag(
            layers.assign(np.ones(2, np.float32)),
            layers.assign(np.eye(2, dtype=np.float32)))
        em = m1.entropy()
        klm = m1.kl_divergence(m2)
        exe = Executor()
        e, k, emv, klmv = exe.run(fetch_list=[ent, kl, em, klm])
        p = np.exp([1, 2, 3]) / np.exp([1, 2, 3]).sum()
        q = p[::-1]
        assert float(e) == pytest.approx(-np.sum(p * np.log(p)), rel=1e-5)
        assert float(k) == pytest.approx(np.sum(p * np.log(p / q)), rel=1e-5)
        # H = 0.5*k*(1+ln 2π) + Σ ln σ
        assert float(emv) == pytest.approx(
            (1 + math.log(2 * math.pi)) + 2 * math.log(2.0), rel=1e-5)
        # KL = .5*(Σ σ1²/σ2² + Σ diff²/σ2² - k + Σ ln σ2²/σ1²)
        assert float(klmv) == pytest.approx(
            0.5 * (8 + 2 - 2 + 2 * math.log(1 / 4.0)), rel=1e-5)


# -- nets -------------------------------------------------------------------

def test_simple_img_conv_pool_and_group():
    with _fresh():
        img = layers.data("img", shape=[1, 28, 28], dtype="float32")
        out = nets.simple_img_conv_pool(img, num_filters=4, filter_size=5,
                                        pool_size=2, pool_stride=2,
                                        act="relu")
        grp = nets.img_conv_group(img, conv_num_filter=[4, 4], pool_size=2,
                                  pool_stride=2, conv_with_batchnorm=True,
                                  conv_act="relu")
        exe = Executor()
        exe.run(fluid.default_startup_program())
        xv = np.random.RandomState(0).rand(2, 1, 28, 28).astype(np.float32)
        a, b = exe.run(feed={"img": xv}, fetch_list=[out, grp])
        assert a.shape == (2, 4, 12, 12)
        assert b.shape == (2, 4, 14, 14)


def test_glu_and_sdpa():
    with _fresh():
        x = layers.data("x", shape=[8], dtype="float32")
        g = nets.glu(x, dim=-1)
        q = layers.data("q", shape=[5, 16], dtype="float32")
        kv = layers.data("kv", shape=[7, 16], dtype="float32")
        att = nets.scaled_dot_product_attention(q, kv, kv, num_heads=4)
        exe = Executor()
        xv = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        qv = np.random.RandomState(2).randn(2, 5, 16).astype(np.float32)
        kvv = np.random.RandomState(3).randn(2, 7, 16).astype(np.float32)
        gv, av = exe.run(feed={"x": xv, "q": qv, "kv": kvv},
                         fetch_list=[g, att])
        ref = xv[:, :4] * (1 / (1 + np.exp(-xv[:, 4:])))
        np.testing.assert_allclose(gv, ref, rtol=1e-5)
        assert av.shape == (2, 5, 16)


def test_detection_map_integral_counts_fp():
    """Review repro: TP(0.9), FP(0.8), TP(0.7) over 2 gt -> AP 0.833."""
    m = metrics.DetectionMAP()
    gt = np.array([[0, 0, 0, 10, 10], [0, 20, 20, 30, 30]], np.float32)
    pred = np.array([[0, 0.9, 0, 0, 10, 10],
                     [0, 0.8, 50, 50, 60, 60],
                     [0, 0.7, 20, 20, 30, 30]], np.float32)
    m.update(pred, gt)
    assert m.eval() == pytest.approx(0.5 * 1.0 + 0.5 * (2 / 3), abs=1e-6)


def test_detection_map_difficult_excluded():
    m = metrics.DetectionMAP(evaluate_difficult=False)
    gt = np.array([[0, 0, 0, 10, 10, 0],        # normal
                   [0, 20, 20, 30, 30, 1]],     # difficult
                  np.float32)
    pred = np.array([[0, 0.9, 0, 0, 10, 10],    # tp on normal
                     [0, 0.8, 20, 20, 30, 30]], # match difficult: ignored
                    np.float32)
    m.update(pred, gt)
    assert m.eval() == pytest.approx(1.0)


def test_auc_pr_curve():
    m = metrics.Auc(curve="PR")
    scores = np.array([0.9, 0.8, 0.7, 0.3, 0.2])
    labels = np.array([1, 1, 0, 1, 0])
    m.update(np.stack([1 - scores, scores], 1), labels)
    v = m.eval()
    assert 0.5 < v <= 1.0
    with pytest.raises(ValueError):
        metrics.Auc(curve="XYZ")
